"""Host-side encoding between Python payloads and fixed-shape step tensors.

This is the boundary where variable-length byte-string messages become
slotted fixed-shape arrays (SURVEY.md §7 "hard parts" #1): payloads are
padded into `[B, SB]` uint8 slots with a length vector, counts clamp the
valid prefix. The broker batcher and the test suite share these builders
so there is exactly one encoder (the reference's equivalent boundary is
Java serialization of `List<String>` request DTOs,
mq-common/src/main/java/request/partition/MessageAppendRequest.java).
"""

from __future__ import annotations

import numpy as np

from ripplemq_tpu.core.config import EngineConfig
from ripplemq_tpu.core.state import StepInput


def build_step_input(
    cfg: EngineConfig,
    appends: dict[int, list[bytes]] | None = None,
    offset_updates: dict[int, list[tuple[int, int]]] | None = None,
    leader: dict[int, int] | int = -1,
    term: dict[int, int] | int = 0,
) -> StepInput:
    """Build one round's StepInput from plain Python values.

    `appends` maps partition -> payload list (each <= cfg.slot_bytes,
    at most cfg.max_batch per partition); `offset_updates` maps
    partition -> [(consumer_slot, absolute_offset)]; `leader`/`term` are
    per-partition dicts or one value for all partitions. Raises ValueError
    on oversized payloads or batches — the batcher enforces these limits
    before building, so a trip here is a bug, not backpressure.
    """
    P, B, SB, U = cfg.partitions, cfg.max_batch, cfg.slot_bytes, cfg.max_offset_updates
    entries = np.zeros((P, B, SB), np.uint8)
    lens = np.zeros((P, B), np.int32)
    counts = np.zeros((P,), np.int32)
    off_slots = np.zeros((P, U), np.int32)
    off_vals = np.zeros((P, U), np.int32)
    off_counts = np.zeros((P,), np.int32)

    for p, msgs in (appends or {}).items():
        if not 0 <= p < P:
            raise ValueError(f"partition {p} out of range [0, {P})")
        if len(msgs) > B:
            raise ValueError(f"partition {p}: {len(msgs)} appends > max_batch {B}")
        for i, m in enumerate(msgs):
            if len(m) > SB:
                raise ValueError(
                    f"partition {p}: payload of {len(m)} bytes > slot_bytes {SB}"
                )
            entries[p, i, : len(m)] = np.frombuffer(m, np.uint8)
            lens[p, i] = len(m)
        counts[p] = len(msgs)

    for p, ups in (offset_updates or {}).items():
        if not 0 <= p < P:
            raise ValueError(f"partition {p} out of range [0, {P})")
        if len(ups) > U:
            raise ValueError(
                f"partition {p}: {len(ups)} offset updates > max_offset_updates {U}"
            )
        for i, (slot, off) in enumerate(ups):
            off_slots[p, i] = slot
            off_vals[p, i] = off
        off_counts[p] = len(ups)

    def _per_partition(value, default):
        arr = np.full((P,), default, np.int32)
        if isinstance(value, dict):
            for p, v in value.items():
                if not 0 <= p < P:
                    raise ValueError(f"partition {p} out of range [0, {P})")
                arr[p] = v
        else:
            arr[:] = value
        return arr

    return StepInput(
        entries=entries,
        lens=lens,
        counts=counts,
        off_slots=off_slots,
        off_vals=off_vals,
        off_counts=off_counts,
        leader=_per_partition(leader, -1),
        term=_per_partition(term, 0),
    )


def decode_entries(data, lens, count) -> list[bytes]:
    """Inverse of the slot encoding for a batch read's (data, lens, count)."""
    data, lens, count = np.asarray(data), np.asarray(lens), int(count)
    return [bytes(data[i, : lens[i]].tobytes()) for i in range(count)]
