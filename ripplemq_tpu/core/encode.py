"""Host-side encoding between Python payloads and fixed-shape step tensors.

This is the boundary where variable-length byte-string messages become
slotted fixed-shape arrays (SURVEY.md §7 "hard parts" #1): each payload is
packed into one `slot_bytes` uint8 row behind an 8-byte header (length +
round term, little-endian — see core.config.ROW_HEADER). The broker
batcher and the test suite share these builders so there is exactly one
encoder (the reference's equivalent boundary is Java serialization of
`List<String>` request DTOs,
mq-common/src/main/java/request/partition/MessageAppendRequest.java).
"""

from __future__ import annotations

import numpy as np

from ripplemq_tpu.core.config import ALIGN, ROW_HEADER, EngineConfig
from ripplemq_tpu.core.state import StepInput


def row_extents(counts: np.ndarray) -> np.ndarray:
    """Per-partition write extents (rows, ALIGN-rounded) from payload
    counts — what the packed write path (EngineConfig.packed_writes)
    needs to clip each append DMA to the bytes the round actually
    carries. Host-side analogue of core.step._padded_advance."""
    counts = np.asarray(counts, np.int32)
    return ((counts + ALIGN - 1) // ALIGN * ALIGN).astype(np.int32)


def pack_rows(
    cfg: EngineConfig, payloads: list[bytes], term: int
) -> np.ndarray:
    """Pack payloads into a [B, SB] block of header-prefixed rows.

    Rows beyond len(payloads) carry length 0 and the round term — they
    are the round's ALIGN padding and must still hold a valid term (the
    log-matching check reads the tail row's term, whether or not it holds
    a payload)."""
    B, SB = cfg.max_batch, cfg.slot_bytes
    if len(payloads) > B:
        raise ValueError(f"{len(payloads)} payloads > max_batch {B}")
    rows = np.zeros((B, SB), np.uint8)
    rows[:, 4:8] = np.frombuffer(
        np.int32(term).tobytes(), np.uint8
    )  # little-endian term in every row
    for i, m in enumerate(payloads):
        if not isinstance(m, (bytes, bytearray, memoryview)):
            raise TypeError(f"payloads must be bytes, got {type(m).__name__}")
        m = bytes(m)
        if not m:
            raise ValueError("empty messages are not supported (length-0 "
                             "rows mark alignment padding)")
        if len(m) > cfg.payload_bytes:
            raise ValueError(
                f"payload of {len(m)} bytes > payload_bytes {cfg.payload_bytes}"
            )
        rows[i, 0:4] = np.frombuffer(np.int32(len(m)).tobytes(), np.uint8)
        rows[i, ROW_HEADER : ROW_HEADER + len(m)] = np.frombuffer(m, np.uint8)
    return rows


def pack_payload_rows(cfg: EngineConfig, payloads: list[bytes]) -> np.ndarray:
    """Pack payloads into a [len(payloads), SB] block of header-prefixed
    rows with a ZERO term field — the batcher stamps the round term over
    the whole assembled block at drain time (the term is a round
    property, unknown at submit). Splitting the packing from the term
    stamp lets the per-message work run on the submitting thread (RPC
    workers, in parallel) instead of inside the batcher's lock, where it
    serialized the whole data plane under deep backlogs. Callers
    validate payload sizes/types first (DataPlane.submit_append).

    Uniform-length batches (every producer SDK batch in practice) take a
    vectorized path: ONE join + ONE reshape instead of a python loop of
    per-row numpy assignments — the loop was ~1.2 ms per 256-row batch
    on the profiled host, most of the host's per-message packing cost
    (PROFILE.md "host path")."""
    SB = cfg.slot_bytes
    k = len(payloads)
    rows = np.zeros((k, SB), np.uint8)
    n0 = len(payloads[0]) if k else 0
    if k and all(len(m) == n0 for m in payloads):
        rows[:, 0:4] = np.frombuffer(
            np.full((k,), n0, "<i4").tobytes(), np.uint8
        ).reshape(k, 4)
        rows[:, ROW_HEADER : ROW_HEADER + n0] = np.frombuffer(
            b"".join(payloads), np.uint8
        ).reshape(k, n0)
        return rows
    for i, m in enumerate(payloads):
        n = len(m)
        rows[i, 0:4] = np.frombuffer(np.int32(n).tobytes(), np.uint8)
        rows[i, ROW_HEADER : ROW_HEADER + n] = np.frombuffer(m, np.uint8)
    return rows


def stamp_term(block: np.ndarray, term: int) -> None:
    """Write `term` into every row's term field of an assembled [B, SB]
    block (padding rows included — log-matching reads the tail row's
    term whether or not it holds a payload)."""
    block[:, 4:8] = np.frombuffer(np.int32(term).tobytes(), np.uint8)


def build_step_input(
    cfg: EngineConfig,
    appends: dict[int, list[bytes]] | None = None,
    offset_updates: dict[int, list[tuple[int, int]]] | None = None,
    leader: dict[int, int] | int = -1,
    term: dict[int, int] | int = 0,
) -> StepInput:
    """Build one round's StepInput from plain Python values.

    `appends` maps partition -> payload list (each <= cfg.payload_bytes,
    at most cfg.max_batch per partition); `offset_updates` maps
    partition -> [(consumer_slot, absolute_offset)]; `leader`/`term` are
    per-partition dicts or one value for all partitions. Raises ValueError
    on oversized payloads or batches — the batcher enforces these limits
    before building, so a trip here is a bug, not backpressure.
    """
    P, B, SB, U = cfg.partitions, cfg.max_batch, cfg.slot_bytes, cfg.max_offset_updates

    def _per_partition(value, default):
        arr = np.full((P,), default, np.int32)
        if isinstance(value, dict):
            for p, v in value.items():
                if not 0 <= p < P:
                    raise ValueError(f"partition {p} out of range [0, {P})")
                arr[p] = v
        else:
            arr[:] = value
        return arr

    terms = _per_partition(term, 0)
    entries = np.zeros((P, B, SB), np.uint8)
    counts = np.zeros((P,), np.int32)
    off_slots = np.zeros((P, U), np.int32)
    off_vals = np.zeros((P, U), np.int32)
    off_counts = np.zeros((P,), np.int32)

    for p, msgs in (appends or {}).items():
        if not 0 <= p < P:
            raise ValueError(f"partition {p} out of range [0, {P})")
        entries[p] = pack_rows(cfg, msgs, int(terms[p]))
        counts[p] = len(msgs)

    for p, ups in (offset_updates or {}).items():
        if not 0 <= p < P:
            raise ValueError(f"partition {p} out of range [0, {P})")
        if len(ups) > U:
            raise ValueError(
                f"partition {p}: {len(ups)} offset updates > max_offset_updates {U}"
            )
        for i, (slot, off) in enumerate(ups):
            off_slots[p, i] = slot
            off_vals[p, i] = off
        off_counts[p] = len(ups)

    return StepInput(
        entries=entries,
        counts=counts,
        off_slots=off_slots,
        off_vals=off_vals,
        off_counts=off_counts,
        leader=_per_partition(leader, -1),
        term=terms,
        extents=row_extents(counts),
    )


def decode_entries(data, lens, count) -> list[bytes]:
    """Messages from a batch read's (rows, lens, count). Length-0 rows are
    alignment padding, not messages — skipped."""
    return [m for _, m in decode_entries_with_pos(data, lens, count)]


def decode_entries_with_pos(data, lens, count) -> list[tuple[int, bytes]]:
    """Like decode_entries but yields (row_index, payload) so callers can
    turn a truncated message list back into a storage offset."""
    data, lens, count = np.asarray(data), np.asarray(lens), int(count)
    out = []
    for i in range(count):
        n = int(lens[i])
        if n > 0:
            out.append((i, bytes(data[i, ROW_HEADER : ROW_HEADER + n].tobytes())))
    return out
