"""Static engine configuration.

Every field here is a *shape* as far as XLA is concerned: the whole data
plane is traced once per EngineConfig and never recompiled. Membership
changes, leader changes and partition starts/stops are expressed as masked
*values* (alive masks, leader ids, counts), never as shape changes — see
SURVEY.md §7 "hard parts".
"""

from __future__ import annotations

import dataclasses
import warnings

# Log slot alignment: every committed round advances the log end to a
# multiple of ALIGN so that the append kernel's DMA windows land on TPU
# sublane-tile boundaries (Mosaic requires row offsets divisible by the
# uint8 sublane tile of 8). Consequence: offsets are STORAGE offsets —
# dense within a round, with up to ALIGN-1 empty padding slots between
# rounds; the wire protocol therefore always reports `next_offset`
# explicitly instead of letting clients compute `offset + n` (a documented
# deviation from the reference's dense-offset arithmetic,
# ConsumerClientImpl.java:103-109).
ALIGN = 8

# Bytes reserved at the head of every log row for metadata:
#   [0:4)  payload length, little-endian int32 (0 = empty/padding row)
#   [4:8)  Raft term of the writing round, little-endian int32
# Embedding the header in the row keeps the data plane to ONE array and
# the append to ONE DMA per (replica, partition) per round.
ROW_HEADER = 8

# Ring-stride aliasing hazard (PROFILE.md round-5 finding 2): when the
# per-partition ring stride (slots + max_batch) * slot_bytes lands on or
# near a power of two >= 2^20, the append kernel's strided partition DMAs
# alias HBM channels and the measured write rate drops 25-35% (slots 8192
# at SB 128 — stride 2^20 + 32 KiB — vs slots 8448/12352 in the same
# process). The measured-bad stride sat 3.1% off the power of two, so the
# "near" band is 1/16 relative.
STRIDE_POW2_FLOOR = 1 << 20
_STRIDE_REL_TOL = 16  # flag within pow2/16 of the power of two
# Below this many partition rings RESIDENT ON ONE DEVICE there are too
# few concurrent strided streams to alias measurably. The count is a
# per-device property, not a config property: the local (vmap) binding
# keeps every replica's rings on one chip (partitions * replicas
# streams), while the spmd binding's devices each hold ONE replica's
# shard (partitions / part_shards streams — parallel.engine re-prices
# the hazard there, since the config cannot know the mesh).
STRIDE_WARN_MIN_PARTITIONS = 64


def ring_stride_bytes(slots: int, max_batch: int, slot_bytes: int) -> int:
    """Per-partition byte stride of the physical log array
    [P, slots + max_batch, slot_bytes] (the ring plus its wrap margin)."""
    return (slots + max_batch) * slot_bytes


def stride_alias_hazard(slots: int, max_batch: int, slot_bytes: int,
                        streams: int | None = None) -> str | None:
    """Non-None iff the ring stride lands on/near a >= 2^20 power of two
    (the HBM-channel-aliasing shapes PROFILE.md r5 measured). Returns the
    warning text so callers can warn, log, or assert on it.

    `streams` is the number of partition rings resident on ONE device —
    the concurrent strided-DMA streams that actually hammer the HBM
    channels. Below STRIDE_WARN_MIN_PARTITIONS the aliasing is
    unmeasurable and the verdict is None regardless of the stride:
    pricing the GLOBAL partition count instead gets sharded deployments
    wrong in both directions (a P=1024 config sharded 32 ways leaves 32
    rings per device — clean — while a P=32 R=3 local binding keeps 96
    rings on one chip — hazardous). None = stride-only verdict (the
    caller applies its own stream gate)."""
    if streams is not None and streams < STRIDE_WARN_MIN_PARTITIONS:
        return None
    stride = ring_stride_bytes(slots, max_batch, slot_bytes)
    if stride <= 0:
        return None
    lo = 1 << (stride.bit_length() - 1)
    for pow2 in (lo, lo << 1):
        if pow2 >= STRIDE_POW2_FLOOR and (
            abs(stride - pow2) <= pow2 // _STRIDE_REL_TOL
        ):
            return (
                f"ring stride {stride} B/partition "
                f"((slots={slots} + max_batch={max_batch}) * "
                f"slot_bytes={slot_bytes}) is within {100 / _STRIDE_REL_TOL:.1f}% "
                f"of 2^{pow2.bit_length() - 1}; strided append DMAs at this "
                f"shape alias HBM channels (measured 25-35% write-rate "
                f"penalty, PROFILE.md r5). Nudge `slots` so the stride "
                f"moves off the power of two."
            )
    return None


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Shape/config of one replication-engine program.

    The reference runs one JRaft group per topic-partition, all multiplexed
    on a single RPC server (reference:
    mq-broker/src/main/java/metadata/raft/PartitionRaftServer.java:93).
    Here the multiplexing is a tensor axis: `partitions` is the leading
    vmap axis of every state array.
    """

    partitions: int = 8          # P — total partition slots in the program
    replicas: int = 3            # R — replication factor == mesh axis size
    slots: int = 1024            # S — log capacity per partition (entries)
    slot_bytes: int = 128        # SB — bytes per log slot (incl. ROW_HEADER)
    max_batch: int = 32          # B — max appended entries per partition/step
    read_batch: int = 32         # RB — max entries per batch read
    max_consumers: int = 64      # C — consumer-offset table width
    max_offset_updates: int = 8  # U — max offset commits per partition/step
    # Hot-path levers (PROFILE.md r5: the sustained engine is pinned by
    # the balanced control and write phases — both must shrink to move).
    # Each is independently A/B-able against the legacy path and
    # bit-identical to it (tests/test_control_fusion.py):
    fused_control: bool = False  # bookkeeping scalars as one [K, P] ctrl
    #                              array updated by wide fused ops instead
    #                              of per-field element-wise ops. Honored
    #                              by BOTH bindings: under shard_map the
    #                              stacked leader broadcast is ONE psum on
    #                              the replica mesh axis per round (one
    #                              ICI collective where the legacy control
    #                              phase issues two)
    packed_writes: bool = False  # clip append DMA windows to the round's
    #                              payload extent instead of always moving
    #                              the full [B, SB] block
    # Host-path knob (NOT a device shape — no recompile): how many
    # dispatched rounds may have their standby replication in flight
    # while the device advances. Acks and the settled-read horizon are
    # released strictly in round order; the window backpressures when
    # full and drains on any fencing/deposition/membership event, so the
    # chaos plane's handover invariants hold verbatim at any width
    # (broker/dataplane.py settle pipeline). 1 = legacy serialized
    # settle (each round's acks land before the next round's release).
    settle_window: int = 4

    def __post_init__(self) -> None:
        if self.replicas < 1:
            raise ValueError("replicas must be >= 1")
        if self.settle_window < 1:
            raise ValueError("settle_window must be >= 1")
        if self.max_batch > self.slots:
            raise ValueError("max_batch cannot exceed slots")
        if self.read_batch > self.slots:
            raise ValueError("read_batch cannot exceed slots")
        if self.slot_bytes <= ROW_HEADER:
            raise ValueError(f"slot_bytes must exceed the {ROW_HEADER}-byte row header")
        if self.max_batch % ALIGN:
            raise ValueError(f"max_batch must be a multiple of {ALIGN}")
        if self.slots % ALIGN:
            raise ValueError(f"slots must be a multiple of {ALIGN}")
        # The aliasing penalty comes from MANY concurrent strided
        # partition DMAs hammering the same HBM channels; at small
        # per-device ring counts the effect is negligible (the shipped
        # P=8 example keeps its round numbers on purpose — see
        # examples/cluster.yaml's sizing note), so only fan-out shapes
        # warn. The stream count priced here is the DEFAULT local
        # binding's: one device holds every replica's rings (P * R). A
        # sharded deployment's devices hold only partitions/part_shards
        # rings each — parallel.engine.make_spmd_fns re-prices the
        # hazard at that per-device shard and is the authority there.
        hazard = stride_alias_hazard(self.slots, self.max_batch,
                                     self.slot_bytes,
                                     streams=self.partitions * self.replicas)
        if hazard is not None:
            warnings.warn(hazard, UserWarning, stacklevel=2)

    @property
    def quorum(self) -> int:
        """Majority of the full membership (Raft quorum)."""
        return self.replicas // 2 + 1

    @property
    def payload_bytes(self) -> int:
        """Max message payload per slot (slot minus the row header)."""
        return self.slot_bytes - ROW_HEADER
