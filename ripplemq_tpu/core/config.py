"""Static engine configuration.

Every field here is a *shape* as far as XLA is concerned: the whole data
plane is traced once per EngineConfig and never recompiled. Membership
changes, leader changes and partition starts/stops are expressed as masked
*values* (alive masks, leader ids, counts), never as shape changes — see
SURVEY.md §7 "hard parts".
"""

from __future__ import annotations

import dataclasses

# Log slot alignment: every committed round advances the log end to a
# multiple of ALIGN so that the append kernel's DMA windows land on TPU
# sublane-tile boundaries (Mosaic requires row offsets divisible by the
# uint8 sublane tile of 8). Consequence: offsets are STORAGE offsets —
# dense within a round, with up to ALIGN-1 empty padding slots between
# rounds; the wire protocol therefore always reports `next_offset`
# explicitly instead of letting clients compute `offset + n` (a documented
# deviation from the reference's dense-offset arithmetic,
# ConsumerClientImpl.java:103-109).
ALIGN = 8

# Bytes reserved at the head of every log row for metadata:
#   [0:4)  payload length, little-endian int32 (0 = empty/padding row)
#   [4:8)  Raft term of the writing round, little-endian int32
# Embedding the header in the row keeps the data plane to ONE array and
# the append to ONE DMA per (replica, partition) per round.
ROW_HEADER = 8


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Shape/config of one replication-engine program.

    The reference runs one JRaft group per topic-partition, all multiplexed
    on a single RPC server (reference:
    mq-broker/src/main/java/metadata/raft/PartitionRaftServer.java:93).
    Here the multiplexing is a tensor axis: `partitions` is the leading
    vmap axis of every state array.
    """

    partitions: int = 8          # P — total partition slots in the program
    replicas: int = 3            # R — replication factor == mesh axis size
    slots: int = 1024            # S — log capacity per partition (entries)
    slot_bytes: int = 128        # SB — bytes per log slot (incl. ROW_HEADER)
    max_batch: int = 32          # B — max appended entries per partition/step
    read_batch: int = 32         # RB — max entries per batch read
    max_consumers: int = 64      # C — consumer-offset table width
    max_offset_updates: int = 8  # U — max offset commits per partition/step

    def __post_init__(self) -> None:
        if self.replicas < 1:
            raise ValueError("replicas must be >= 1")
        if self.max_batch > self.slots:
            raise ValueError("max_batch cannot exceed slots")
        if self.read_batch > self.slots:
            raise ValueError("read_batch cannot exceed slots")
        if self.slot_bytes <= ROW_HEADER:
            raise ValueError(f"slot_bytes must exceed the {ROW_HEADER}-byte row header")
        if self.max_batch % ALIGN:
            raise ValueError(f"max_batch must be a multiple of {ALIGN}")
        if self.slots % ALIGN:
            raise ValueError(f"slots must be a multiple of {ALIGN}")

    @property
    def quorum(self) -> int:
        """Majority of the full membership (Raft quorum)."""
        return self.replicas // 2 + 1

    @property
    def payload_bytes(self) -> int:
        """Max message payload per slot (slot minus the row header)."""
        return self.slot_bytes - ROW_HEADER
