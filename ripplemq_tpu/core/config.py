"""Static engine configuration.

Every field here is a *shape* as far as XLA is concerned: the whole data
plane is traced once per EngineConfig and never recompiled. Membership
changes, leader changes and partition starts/stops are expressed as masked
*values* (alive masks, leader ids, counts), never as shape changes — see
SURVEY.md §7 "hard parts".
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Shape/config of one replication-engine program.

    The reference runs one JRaft group per topic-partition, all multiplexed
    on a single RPC server (reference:
    mq-broker/src/main/java/metadata/raft/PartitionRaftServer.java:93).
    Here the multiplexing is a tensor axis: `partitions` is the leading
    vmap axis of every state array.
    """

    partitions: int = 8          # P — total partition slots in the program
    replicas: int = 3            # R — replication factor == mesh axis size
    slots: int = 1024            # S — log capacity per partition (entries)
    slot_bytes: int = 128        # SB — payload bytes per log slot
    max_batch: int = 32          # B — max appended entries per partition/step
    read_batch: int = 32         # RB — max entries per batch read
    max_consumers: int = 64      # C — consumer-offset table width
    max_offset_updates: int = 8  # U — max offset commits per partition/step

    def __post_init__(self) -> None:
        if self.replicas < 1:
            raise ValueError("replicas must be >= 1")
        if self.max_batch > self.slots:
            raise ValueError("max_batch cannot exceed slots")
        if self.read_batch > self.slots:
            raise ValueError("read_batch cannot exceed slots")

    @property
    def quorum(self) -> int:
        """Majority of the full membership (Raft quorum)."""
        return self.replicas // 2 + 1
