"""Pure Raft data-plane steps, written against a named "replica" axis.

This module is the TPU-native replacement for the reference's hot loop:
JRaft AppendEntries replication + per-entry quorum ack + state-machine
apply (reference call stack: MessageAppendRequestProcessor.java:59 →
JRaft replication → PartitionStateMachine.onApply:38). There, each message
is one Raft task on one of many per-partition JVM actor groups. Here, ONE
jitted step replicates a (partition × entry) batch across every replica
and advances every partition's commit index in a single psum round:

  1. Every replica receives the round's batch (the broadcast over the
     replica axis is the AppendEntries transfer; under SPMD it rides ICI).
  2. A replica *acks* iff it is alive, its log end matches the leader's
     pre-append log end (the Raft log-matching check) and the leader's
     term is current.
  3. votes = lax.psum(ack) over the replica axis — the ballot happens
     BEFORE any write (the ack predicate only reads pre-round state).
  4. Rounds are atomic: iff the ballot reached quorum, acking replicas
     append the batch and advance commit; a failed round leaves no trace
     on any replica, so retries are always safe. (Wire Raft instead lets
     leader/follower logs diverge and repairs them with nextIndex
     backtracking — pointless here, where ballot + write are one fused
     device program.)
  5. Committed offset updates are scattered into the replicated
     consumer-offset table (the reference routes these through the same
     per-partition Raft log — PartitionStateMachine.java:71-77).

Rare, branchy transitions (elections, membership, resync after a replica
returns from the dead) are host-coordinated; the per-step path is
branch-free so XLA compiles it once per EngineConfig. Leader election's
vote *counting* does run on device (`vote_step`) as a psum reduction.

The functions take per-replica state and use collectives over the axis
name "replica"; wrap them with `jax.vmap(..., axis_name="replica")` for a
single-device simulation or shard the replica axis over a mesh with
`shard_map` for real multi-chip SPMD (see ripplemq_tpu.parallel.engine).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ripplemq_tpu.core.config import EngineConfig
from ripplemq_tpu.core.state import ReplicaState, StepInput, StepOutput

AXIS = "replica"


def _bcast_from_leader(value: jax.Array, is_leader: jax.Array) -> jax.Array:
    """Broadcast a per-replica value from each partition's leader to all
    replicas: mask to the leader's contribution, sum over the replica axis.
    `value`/`is_leader` are [P]-shaped per-replica arrays."""
    contrib = jnp.where(is_leader, value, jnp.zeros_like(value))
    return lax.psum(contrib, AXIS)


def _append_one(
    log_data, log_len, log_term, entries, lens, count, start, term, do_append
):
    """Append up to B entries at `start` into one partition's slotted log.

    Reads a [B, SB] window, blends the valid prefix of the batch in,
    writes it back. `do_append` disables the write (identity blend) for
    replicas that did not ack. Shapes: log_data [S, SB], entries [B, SB],
    lens [B], scalars otherwise.

    dynamic_slice/update clamp the window start so the window fits; when
    `start > S - B` (tail of the log) the window begins `shift` rows
    before `start`, so the batch and its validity mask are rolled forward
    by `shift` to land on the right absolute slots. The caller guarantees
    start + count <= S, hence count <= B - shift and nothing wraps.
    """
    B = entries.shape[0]
    S = log_data.shape[0]
    sl_start = jnp.clip(start, 0, S - B)
    shift = start - sl_start
    valid = (jnp.arange(B, dtype=jnp.int32) < count) & do_append  # [B]
    valid = jnp.roll(valid, shift, axis=0)
    entries = jnp.roll(entries, shift, axis=0)
    lens = jnp.roll(lens, shift, axis=0)

    window = lax.dynamic_slice(log_data, (sl_start, 0), (B, log_data.shape[1]))
    window = jnp.where(valid[:, None], entries, window)
    log_data = lax.dynamic_update_slice(log_data, window, (sl_start, 0))

    len_win = lax.dynamic_slice(log_len, (sl_start,), (B,))
    len_win = jnp.where(valid, lens, len_win)
    log_len = lax.dynamic_update_slice(log_len, len_win, (sl_start,))

    term_win = lax.dynamic_slice(log_term, (sl_start,), (B,))
    term_win = jnp.where(valid, jnp.full((B,), term, jnp.int32), term_win)
    log_term = lax.dynamic_update_slice(log_term, term_win, (sl_start,))

    return log_data, log_len, log_term


def _normalize_alive(alive: jax.Array, P: int, R: int) -> jax.Array:
    """Accept a [R] cluster-wide or [P, R] per-partition liveness mask.

    Per-partition masks exist because each partition maps its replica
    slots to different brokers (sticky assignment): one dead broker kills
    slot 2 of one partition and slot 0 of another (the reference's
    per-group peer lists, PartitionRaftServer.java:83).
    """
    if alive.ndim == 1:
        return jnp.broadcast_to(alive[None, :], (P, R))
    return alive


def replica_step(
    cfg: EngineConfig,
    state: ReplicaState,
    inp: StepInput,
    rep_idx: jax.Array,   # int32 scalar — this replica's id on the axis
    alive: jax.Array,     # bool [R] or [P, R] — membership mask (replicated)
    quorum: jax.Array | None = None,  # int32 [P] — per-partition quorum
) -> tuple[ReplicaState, StepOutput]:
    """One replication round, from one replica's point of view.

    `quorum` is per-partition because topics can carry different
    replication factors than the mesh's replica-axis size: a partition
    with RF 3 on an R=5 program commits at 2 acks, with its two unused
    slots permanently masked dead in `alive`.
    """
    S, B, R = cfg.slots, cfg.max_batch, cfg.replicas
    P = cfg.partitions
    if quorum is None:
        quorum = jnp.full((P,), cfg.quorum, jnp.int32)

    # Sanitize host-fed control values: an out-of-range index is undefined
    # behavior on TPU gathers (observed: backend InvalidArgument), and an
    # oversized count would advance log_end past what was written
    # (phantom committed entries).
    counts = jnp.clip(inp.counts, 0, B)
    inp = inp._replace(counts=counts)

    alive = _normalize_alive(alive, P, R)                # [P, R]
    self_alive = alive[:, rep_idx]                       # [P]
    leader_known = (inp.leader >= 0) & (inp.leader < R)  # [P]
    is_leader = (inp.leader == rep_idx) & leader_known   # [P]
    leader_alive = jnp.where(
        leader_known,
        jnp.take_along_axis(
            alive, jnp.clip(inp.leader, 0, R - 1)[:, None], axis=1
        )[:, 0],
        False,
    )

    # --- 1. leader's pre-append log end ("prevLogIndex" of AppendEntries)
    # and the term of its last entry ("prevLogTerm").
    base = _bcast_from_leader(state.log_end, is_leader & self_alive)  # [P]
    last_idx = jnp.maximum(state.log_end - 1, 0)
    my_last_term = jnp.where(
        state.log_end > 0,
        jnp.take_along_axis(state.log_term, last_idx[:, None], axis=1)[:, 0],
        0,
    )
    leader_last_term = _bcast_from_leader(my_last_term, is_leader & self_alive)

    # --- 2. ack: alive + log-matching + term current. Log matching is the
    # full Raft check — prevLogIndex (log_end == base) AND prevLogTerm:
    # a replica whose log is the same length but whose tail entry was
    # written under a different term has a divergent uncommitted suffix
    # and must NOT ack (it re-enters via host-driven resync). Length alone
    # would let divergent committed data survive below the commit index.
    term_ok = inp.term >= state.current_term
    log_match = (state.log_end == base) & (
        (base == 0) | (my_last_term == leader_last_term)
    )
    capacity_ok = base + inp.counts <= S  # backpressure: full partitions never ack
    # A round is ack-worthy if it carries entries OR offset commits: offset
    # commits on idle partitions must still replicate (the reference routes
    # them through the partition Raft log regardless of appends).
    has_work = (inp.counts > 0) | (inp.off_counts > 0)
    ack = (
        self_alive
        & leader_alive
        & term_ok
        & log_match
        & capacity_ok
        & has_work
    )  # [P]

    # Followers adopt the leader's (host/election-issued) term.
    new_current_term = jnp.maximum(state.current_term, inp.term)

    # --- 3. quorum vote FIRST: count acks across the replica axis. The
    # ack predicate depends only on pre-round state, so the ballot can
    # precede the write — and therefore gate it.
    votes = lax.psum(ack.astype(jnp.int32), AXIS)          # [P]
    committed = votes >= quorum                            # [P]

    # --- 4. ATOMIC ROUNDS: writes land only where the round committed.
    # A failed round (no quorum) leaves no trace on ANY replica — leader
    # included — so host-level retries can never create divergent or
    # duplicate entries. This is a deliberate departure from wire Raft
    # (where a leader appends locally first and followers converge later
    # via nextIndex backtracking): on TPU the ballot and the write are one
    # fused program, so the log simply never holds uncommitted entries,
    # and replica repair reduces to the explicit host resync path.
    do_write = ack & committed                             # [P]
    log_data, log_len, log_term = jax.vmap(_append_one)(
        state.log_data,
        state.log_len,
        state.log_term,
        inp.entries,
        inp.lens,
        inp.counts,
        jnp.where(do_write, base, 0),
        inp.term,
        do_write,
    )
    new_log_end = jnp.where(do_write, base + inp.counts, state.log_end)

    # Commit index == log end on every writing replica; never regresses.
    commit_target = jnp.where(do_write, base + inp.counts, 0)
    new_commit = jnp.maximum(state.commit, commit_target)

    # --- 5. committed consumer-offset updates (scatter into the table).
    # The reference replicates offset commits through the same partition
    # Raft log (ConsumerOffsetUpdateRequestProcessor.java:38-69 →
    # PartitionStateMachine.java:71-77); here they ride the same quorum
    # round as the data batch.
    U = cfg.max_offset_updates
    off_counts = jnp.clip(inp.off_counts, 0, U)
    off_valid = (jnp.arange(U, dtype=jnp.int32)[None, :] < off_counts[:, None])
    off_apply = off_valid & do_write[:, None]               # [P, U]
    C = cfg.max_consumers
    scatter_idx = jnp.where(off_apply, inp.off_slots, C)    # C = out of range → dropped

    def _scatter_offsets(offs, idx, vals):
        return offs.at[idx].set(vals, mode="drop")

    new_offsets = jax.vmap(_scatter_offsets)(state.offsets, scatter_idx, inp.off_vals)

    new_state = ReplicaState(
        log_data=log_data,
        log_len=log_len,
        log_term=log_term,
        log_end=new_log_end,
        current_term=new_current_term,
        commit=new_commit,
        offsets=new_offsets,
    )
    out = StepOutput(
        base=base,
        votes=votes,
        committed=committed,
        commit=lax.pmax(new_commit, AXIS),
    )
    return new_state, out


def vote_step(
    cfg: EngineConfig,
    state: ReplicaState,
    cand: jax.Array,       # int32 [P] — candidate replica id per partition (-1 = no election)
    cand_term: jax.Array,  # int32 [P] — candidate's proposed term
    rep_idx: jax.Array,
    alive: jax.Array,
    quorum: jax.Array | None = None,  # int32 [P]
) -> tuple[ReplicaState, jax.Array, jax.Array]:
    """One RequestVote round: grants counted as a psum reduction.

    Returns (state', elected[P] bool, votes[P] int32). The up-to-date
    check is Raft §5.4.1: grant only to candidates whose log is at least
    as complete. Replaces JRaft's per-group ballot
    (NodeOptions.setElectionTimeoutMs — reference
    PartitionRaftServer.java:85 — with timeouts host-vectorized).
    """
    R = cfg.replicas
    alive = _normalize_alive(alive, cfg.partitions, R)  # [P, R]
    if quorum is None:
        quorum = jnp.full((cfg.partitions,), cfg.quorum, jnp.int32)
    electing = (cand >= 0) & (cand < R)
    is_cand = (cand == rep_idx) & electing
    self_alive = alive[:, rep_idx]
    cand_alive = jnp.where(
        electing,
        jnp.take_along_axis(alive, jnp.clip(cand, 0, R - 1)[:, None], axis=1)[:, 0],
        False,
    )

    last_idx = jnp.maximum(state.log_end - 1, 0)
    my_last_term = jnp.where(
        state.log_end > 0,
        jnp.take_along_axis(state.log_term, last_idx[:, None], axis=1)[:, 0],
        0,
    )
    c_end = _bcast_from_leader(state.log_end, is_cand & self_alive)
    c_last_term = _bcast_from_leader(my_last_term, is_cand & self_alive)

    up_to_date = (c_last_term > my_last_term) | (
        (c_last_term == my_last_term) & (c_end >= state.log_end)
    )
    grant = electing & self_alive & cand_alive & (cand_term > state.current_term) & up_to_date

    votes = lax.psum(grant.astype(jnp.int32), AXIS)
    elected = votes >= quorum

    new_term = jnp.where(grant, cand_term, state.current_term)
    return state._replace(current_term=new_term), elected, votes


def read_batch(
    cfg: EngineConfig,
    state: ReplicaState,
    partition: jax.Array,  # int32 scalar
    offset: jax.Array,     # int32 scalar — absolute offset to read from
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Read up to RB *committed* entries of one partition from this replica.

    Returns (data [RB, SB] uint8, lens [RB] int32, count int32). Serves
    the consume path; like the reference this is a replica-local read with
    no extra consensus round (PartitionStateMachine.handleBatchRead:85 —
    leader-local, no read-index), but unlike the reference it only exposes
    entries below the commit index.
    """
    RB = cfg.read_batch
    partition = jnp.clip(partition, 0, cfg.partitions - 1)
    commit = state.commit[partition]
    start = jnp.clip(offset, 0, cfg.slots)
    count = jnp.clip(commit - start, 0, RB)
    # dynamic_slice clamps the start so the window fits; compensate by
    # slicing at a clamped start and rolling the wanted rows to the front
    # (count never exceeds RB - shift, so rolled-in garbage is masked out).
    sl_start = jnp.clip(start, 0, cfg.slots - RB)
    shift = start - sl_start
    data = lax.dynamic_slice(
        state.log_data,
        (partition, sl_start, 0),
        (1, RB, cfg.slot_bytes),
    )[0]
    lens = lax.dynamic_slice(state.log_len, (partition, sl_start), (1, RB))[0]
    data = jnp.roll(data, -shift, axis=0)
    lens = jnp.roll(lens, -shift, axis=0)
    valid = jnp.arange(RB, dtype=jnp.int32) < count
    return jnp.where(valid[:, None], data, 0), jnp.where(valid, lens, 0), count


def read_offset(
    state: ReplicaState,
    partition: jax.Array,
    consumer_slot: jax.Array,
) -> jax.Array:
    """Current committed offset for one consumer slot."""
    P, C = state.offsets.shape
    return state.offsets[
        jnp.clip(partition, 0, P - 1), jnp.clip(consumer_slot, 0, C - 1)
    ]
