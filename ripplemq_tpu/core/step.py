"""Pure Raft data-plane steps, written against a named "replica" axis.

This module is the TPU-native replacement for the reference's hot loop:
JRaft AppendEntries replication + per-entry quorum ack + state-machine
apply (reference call stack: MessageAppendRequestProcessor.java:59 →
JRaft replication → PartitionStateMachine.onApply:38). There, each message
is one Raft task on one of many per-partition JVM actor groups. Here, ONE
jitted step replicates a (partition × entry) batch across every replica
and advances every partition's commit index in a single psum round:

  1. Every replica receives the round's batch (the broadcast over the
     replica axis is the AppendEntries transfer; under SPMD it rides ICI).
  2. A replica *acks* iff it is alive, its log end matches the leader's
     pre-append log end AND its tail term matches the leader's (the full
     Raft log-matching check) and the leader's term is current.
  3. votes = lax.psum(ack) over the replica axis — the ballot happens
     BEFORE any write (the ack predicate only reads pre-round state).
  4. Rounds are atomic: iff the ballot reached quorum, acking replicas
     append the batch and advance commit; a failed round leaves no trace
     on any replica, so retries are always safe. (Wire Raft instead lets
     leader/follower logs diverge and repairs them with nextIndex
     backtracking — pointless here, where ballot + write are one fused
     device program.)
  5. Committed consumer-offset updates blend into the replicated offset
     table in the same round (the reference routes them through the same
     per-partition Raft log — PartitionStateMachine.java:71-77).

The step is split in two phases for the hardware's sake:
- `replica_control` — everything EXCEPT the log write: acks, ballot,
  commit bookkeeping, offset-table blend. Cheap [P]-shaped vector ops;
  runs per replica under vmap (local) or shard_map (SPMD).
- the log write — one [B, SB] block per committed partition at a
  variable, ALIGN-aligned offset. This is `ripplemq_tpu.ops.append`
  (Pallas DMA kernel on TPU; XLA scatter fallback), called once on the
  full [R, P, S, SB] log by the engine wrappers, NOT per replica.

Each committed round advances log_end to the next ALIGN boundary; padding
rows carry length 0 and the round's term (core.config.ALIGN rationale).

Rare, branchy transitions (elections, membership, resync after a replica
returns from the dead) are host-coordinated; the per-step path is
branch-free so XLA compiles it once per EngineConfig. Leader election's
vote *counting* does run on device (`vote_step`) as a psum reduction.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ripplemq_tpu.core.config import ALIGN, EngineConfig
from ripplemq_tpu.core.state import (
    FusedReplicaState,
    ReplicaState,
    StepInput,
    StepOutput,
    row_lens,
)

AXIS = "replica"


def _bcast_from_leader(value: jax.Array, is_leader: jax.Array) -> jax.Array:
    """Broadcast a per-replica value from each partition's leader to all
    replicas: mask to the leader's contribution, sum over the replica axis.
    `value`/`is_leader` are [P]-shaped per-replica arrays."""
    contrib = jnp.where(is_leader, value, jnp.zeros_like(value))
    return lax.psum(contrib, AXIS)


def _normalize_alive(alive: jax.Array, P: int, R: int) -> jax.Array:
    """Accept a [R] cluster-wide or [P, R] per-partition liveness mask.

    Per-partition masks exist because each partition maps its replica
    slots to different brokers (sticky assignment): one dead broker kills
    slot 2 of one partition and slot 0 of another (the reference's
    per-group peer lists, PartitionRaftServer.java:83).
    """
    if alive.ndim == 1:
        return jnp.broadcast_to(alive[None, :], (P, R))
    return alive


def _padded_advance(counts: jax.Array) -> jax.Array:
    """Slots consumed by a round: counts rounded up to ALIGN (0 stays 0)."""
    return ((counts + ALIGN - 1) // ALIGN) * ALIGN


class ControlOut(NamedTuple):
    out: StepOutput     # per-partition round results (replica-invariant)
    do_write: jax.Array  # bool [P] — this replica writes the round's block
    extent: jax.Array    # int32 [P] — rows of the [B, SB] window the write
    #                      phase covers (== B unless packed_writes clips
    #                      it; replica-invariant — derived from the input)


def _write_extent(cfg: EngineConfig, inp: StepInput,
                  advance: jax.Array) -> jax.Array:
    """Rows the write phase covers: the host-declared extent, ALIGN-
    rounded and clamped to [advance, B] so a committed round's rows are
    always covered no matter what the host fed. None extents (or a
    config without packed writes) mean the full legacy window."""
    B = cfg.max_batch
    if not cfg.packed_writes or inp.extents is None:
        return jnp.full_like(advance, B)
    ext = _padded_advance(jnp.clip(inp.extents, 0, B))
    return jnp.clip(ext, advance, B)


def _blend_offsets(cfg: EngineConfig, state_offsets: jax.Array,
                   inp: StepInput, do_write: jax.Array) -> jax.Array:
    """Committed consumer-offset updates: blended (not scattered —
    scatters row-serialize on TPU) into the [P, C] table; U is small and
    static, so the update unrolls to U masked selects."""
    U = cfg.max_offset_updates
    C = cfg.max_consumers
    off_counts = jnp.clip(inp.off_counts, 0, U)
    new_offsets = state_offsets
    cols = jnp.arange(C, dtype=jnp.int32)[None, :]         # [1, C]
    for u in range(U):
        apply_u = do_write & (u < off_counts)              # [P]
        mask = (inp.off_slots[:, u : u + 1] == cols) & apply_u[:, None]
        new_offsets = jnp.where(mask, inp.off_vals[:, u : u + 1], new_offsets)
    return new_offsets


def replica_control(
    cfg: EngineConfig,
    state: ReplicaState,
    inp: StepInput,
    rep_idx: jax.Array,   # int32 scalar — this replica's id on the axis
    alive: jax.Array,     # bool [R] or [P, R] — membership mask (replicated)
    quorum: jax.Array | None = None,  # int32 [P] — per-partition quorum
    trim: jax.Array | None = None,    # int32 [P] — retention watermark
) -> tuple[ReplicaState, ControlOut]:
    """One round's control phase from one replica's point of view: the
    ballot and all scalar-state updates. The returned state has every
    field advanced EXCEPT `log_data` (the write phase owns that).

    `quorum` is per-partition because topics can carry different
    replication factors than the mesh's replica-axis size: a partition
    with RF 3 on an R=5 program commits at 2 acks, with its two unused
    slots permanently masked dead in `alive`.

    `trim` is the host's retention watermark (absolute offset, identical
    on every replica — it rides the round input like `alive`): ring rows
    holding offsets below `trim` are reclaimable, so a round fits iff its
    full B-row window only ever lands on free-or-reclaimable rows
    (`base + B - trim <= S`). Host contracts: trim is monotone per
    partition, never exceeds the persisted/committed prefix, and the
    host clamps each round's batch so `advance <= S - (base % S)` (live
    rows never land in the wrap margin — see core.state ring doc).
    """
    S, B, R = cfg.slots, cfg.max_batch, cfg.replicas
    # Shard-shape note: under shard_map this function sees [local_P]
    # SHARDS of every per-partition argument, not the global [P] — all
    # the arithmetic below is shape-agnostic, but these cfg.partitions-
    # shaped defaults are NOT, so the spmd wrappers always pass quorum/
    # trim explicitly (parallel.engine fills them before the smapped
    # call). The defaults exist for the local binding and direct use.
    P = cfg.partitions
    if quorum is None:
        quorum = jnp.full((P,), cfg.quorum, jnp.int32)
    if trim is None:
        trim = jnp.zeros((P,), jnp.int32)

    # Sanitize host-fed control values: an out-of-range index is undefined
    # behavior on TPU gathers (observed: backend InvalidArgument), and an
    # oversized count would advance log_end past what was written
    # (phantom committed entries).
    counts = jnp.clip(inp.counts, 0, B)
    advance = _padded_advance(counts)                    # [P]

    alive = _normalize_alive(alive, P, R)                # [P, R]
    self_alive = alive[:, rep_idx]                       # [P]
    leader_known = (inp.leader >= 0) & (inp.leader < R)  # [P]
    is_leader = (inp.leader == rep_idx) & leader_known   # [P]
    leader_alive = jnp.where(
        leader_known,
        jnp.take_along_axis(
            alive, jnp.clip(inp.leader, 0, R - 1)[:, None], axis=1
        )[:, 0],
        False,
    )

    # --- 1. leader's pre-append log end ("prevLogIndex" of AppendEntries)
    # and the term of its tail row ("prevLogTerm"; cached in state).
    base = _bcast_from_leader(state.log_end, is_leader & self_alive)  # [P]
    leader_last_term = _bcast_from_leader(
        state.last_term, is_leader & self_alive
    )

    # --- 2. ack: alive + log-matching + term current. Log matching is the
    # full Raft check — prevLogIndex (log_end == base) AND prevLogTerm:
    # a replica whose log is the same length but whose tail was written
    # under a different term has a divergent suffix and must NOT ack (it
    # re-enters via host-driven resync).
    term_ok = inp.term >= state.current_term
    log_match = (state.log_end == base) & (
        (base == 0) | (state.last_term == leader_last_term)
    )
    # Capacity: the write phase always lands a full B-row window on the
    # ring, which (previous lap) covers absolute offsets
    # [base - S, base + B - S) — all of which must be below the trim
    # watermark. With trim pinned at 0 this reduces to the bounded-log
    # rule base + B <= S. Offsets-only rounds (counts == 0) consume no
    # log space and must keep committing on a full partition: consumers
    # still need to advance their positions through the backlog.
    capacity_ok = (counts == 0) | (base + B - trim <= S)
    # A round is ack-worthy if it carries entries OR offset commits: offset
    # commits on idle partitions must still replicate (the reference routes
    # them through the partition Raft log regardless of appends).
    has_work = (counts > 0) | (inp.off_counts > 0)
    ack = (
        self_alive
        & leader_alive
        & term_ok
        & log_match
        & capacity_ok
        & has_work
    )  # [P]

    # --- 3. ballot before any write.
    votes = lax.psum(ack.astype(jnp.int32), AXIS)          # [P]
    committed = votes >= quorum                            # [P]
    do_write = ack & committed                             # [P]

    # --- 4. scalar state advances (atomic with the ballot). wrote_rows
    # additionally gates the write phase: offsets-only rounds must not pay
    # the (hottest-op) append DMA for an all-zero window.
    wrote_rows = do_write & (advance > 0)
    new_log_end = jnp.where(wrote_rows, base + advance, state.log_end)
    new_last_term = jnp.where(wrote_rows, inp.term, state.last_term)
    new_current_term = jnp.maximum(state.current_term, inp.term)
    commit_target = jnp.where(do_write, base + advance, 0)
    new_commit = jnp.maximum(state.commit, commit_target)

    # --- 5. committed consumer-offset updates (shared with the fused
    # path — see _blend_offsets).
    new_offsets = _blend_offsets(cfg, state.offsets, inp, do_write)

    new_state = state._replace(
        log_end=new_log_end,
        last_term=new_last_term,
        current_term=new_current_term,
        commit=new_commit,
        offsets=new_offsets,
    )
    out = StepOutput(
        base=base,
        votes=votes,
        committed=committed,
        commit=lax.pmax(new_commit, AXIS),
    )
    return new_state, ControlOut(out, wrote_rows, _write_extent(cfg, inp, advance))


def replica_control_fused(
    cfg: EngineConfig,
    state: FusedReplicaState,
    inp: StepInput,
    rep_idx: jax.Array,
    alive: jax.Array,
    quorum: jax.Array | None = None,
    trim: jax.Array | None = None,
) -> tuple[FusedReplicaState, ControlOut]:
    """replica_control on the stacked-ctrl state (EngineConfig.
    fused_control), bit-identical to the legacy path by construction
    (asserted across scenarios in tests/test_control_fusion.py).

    What actually shrinks (PROFILE.md r5 finding 3 — the control phase
    is fusion-boundary overhead, not arithmetic):
    - the two leader broadcasts (prevLogIndex + prevLogTerm) ride ONE
      [2, P] psum instead of two [P] psums — under shard_map that is one
      collective instead of two, under vmap one fused reduction;
    - the four bookkeeping advances collapse into ONE [K, P] select on
      one buffer instead of four where/maximum ops on four buffers
      (each a separate XLA fusion in the scanned chain body);
    - the scan carry of a chained launch is three leaves, not six.

    Equivalence notes (each update is the exact legacy expression, just
    restacked): `maximum(x, y)` == `where(y > x, y, x)` bitwise for
    int32, which rewrites current_term/commit as selects; log_end and
    last_term keep their wrote_rows selects unchanged.
    """
    S, B, R = cfg.slots, cfg.max_batch, cfg.replicas
    # Same shard-shape note as replica_control: [local_P] shards under
    # shard_map; the spmd wrappers never rely on these [P] defaults.
    P = cfg.partitions
    if quorum is None:
        quorum = jnp.full((P,), cfg.quorum, jnp.int32)
    if trim is None:
        trim = jnp.zeros((P,), jnp.int32)

    ctrl = state.ctrl                                     # [K, P]
    log_end, last_term = ctrl[0], ctrl[1]
    current_term, commit = ctrl[2], ctrl[3]

    counts = jnp.clip(inp.counts, 0, B)
    advance = _padded_advance(counts)                    # [P]

    alive = _normalize_alive(alive, P, R)                # [P, R]
    self_alive = alive[:, rep_idx]                       # [P]
    leader_known = (inp.leader >= 0) & (inp.leader < R)  # [P]
    is_leader = (inp.leader == rep_idx) & leader_known   # [P]
    leader_alive = jnp.where(
        leader_known,
        jnp.take_along_axis(
            alive, jnp.clip(inp.leader, 0, R - 1)[:, None], axis=1
        )[:, 0],
        False,
    )

    # --- 1. leader's pre-append log end + tail term: ONE stacked psum.
    lead_mask = (is_leader & self_alive)[None, :]         # [1, P]
    led = lax.psum(
        jnp.where(lead_mask, ctrl[0:2], jnp.zeros_like(ctrl[0:2])), AXIS
    )                                                     # [2, P]
    base, leader_last_term = led[0], led[1]

    # --- 2. ack (identical predicate to the legacy path).
    term_ok = inp.term >= current_term
    log_match = (log_end == base) & (
        (base == 0) | (last_term == leader_last_term)
    )
    capacity_ok = (counts == 0) | (base + B - trim <= S)
    has_work = (counts > 0) | (inp.off_counts > 0)
    ack = (
        self_alive
        & leader_alive
        & term_ok
        & log_match
        & capacity_ok
        & has_work
    )  # [P]

    # --- 3. ballot before any write.
    votes = lax.psum(ack.astype(jnp.int32), AXIS)          # [P]
    committed = votes >= quorum                            # [P]
    do_write = ack & committed                             # [P]

    # --- 4. the four scalar advances as ONE wide select on the stacked
    # buffer (see the docstring's equivalence notes).
    wrote_rows = do_write & (advance > 0)
    adv_target = base + advance
    conds = jnp.stack([
        wrote_rows,                                        # log_end
        wrote_rows,                                        # last_term
        inp.term > current_term,                           # current_term
        do_write & (adv_target > commit),                  # commit
    ])                                                     # [K, P] bool
    cands = jnp.stack([adv_target, inp.term, inp.term, adv_target])
    new_ctrl = jnp.where(conds, cands, ctrl)               # [K, P]

    # --- 5. committed consumer-offset updates (shared helper).
    new_offsets = _blend_offsets(cfg, state.offsets, inp, do_write)

    new_state = state._replace(ctrl=new_ctrl, offsets=new_offsets)
    out = StepOutput(
        base=base,
        votes=votes,
        committed=committed,
        commit=lax.pmax(new_ctrl[3], AXIS),
    )
    return new_state, ControlOut(out, wrote_rows, _write_extent(cfg, inp, advance))


def replica_step(
    cfg: EngineConfig,
    state: ReplicaState,
    inp: StepInput,
    rep_idx: jax.Array,
    alive: jax.Array,
    quorum: jax.Array | None = None,
    trim: jax.Array | None = None,
) -> tuple[ReplicaState, StepOutput]:
    """Complete per-replica round: control phase + per-replica XLA append.

    This is the portable all-in-one composition (works under plain vmap on
    any backend, e.g. the driver's single-chip compile check). The engine
    wrappers instead run `replica_control` under vmap/shard_map and hand
    the write phase to the batched Pallas kernel (ops.append) — same
    semantics, asserted by tests. The write lands at the PHYSICAL ring
    position `base % slots` (base itself is absolute).
    """
    new_state, ctl = replica_control(cfg, state, inp, rep_idx, alive, quorum,
                                     trim)
    from ripplemq_tpu.ops.append import append_rows_xla  # local: avoid cycle

    log_data = append_rows_xla(
        state.log_data, inp.entries, ctl.out.base % cfg.slots, ctl.do_write
    )
    return new_state._replace(log_data=log_data), ctl.out


def vote_step(
    cfg: EngineConfig,
    state: ReplicaState,
    cand: jax.Array,       # int32 [P] — candidate replica id per partition (-1 = no election)
    cand_term: jax.Array,  # int32 [P] — candidate's proposed term
    rep_idx: jax.Array,
    alive: jax.Array,
    quorum: jax.Array | None = None,  # int32 [P]
) -> tuple[ReplicaState, jax.Array, jax.Array]:
    """One RequestVote round: grants counted as a psum reduction.

    Returns (state', elected[P] bool, votes[P] int32). The up-to-date
    check is Raft §5.4.1: grant only to candidates whose log is at least
    as complete. Replaces JRaft's per-group ballot
    (NodeOptions.setElectionTimeoutMs — reference
    PartitionRaftServer.java:85 — with timeouts host-vectorized).
    """
    new_term, elected, votes = _vote_core(
        cfg, state.log_end, state.last_term, state.current_term,
        cand, cand_term, rep_idx, alive, quorum,
    )
    return state._replace(current_term=new_term), elected, votes


def vote_step_fused(
    cfg: EngineConfig,
    state: FusedReplicaState,
    cand: jax.Array,
    cand_term: jax.Array,
    rep_idx: jax.Array,
    alive: jax.Array,
    quorum: jax.Array | None = None,
) -> tuple[FusedReplicaState, jax.Array, jax.Array]:
    """vote_step on the stacked-ctrl state: same ballot core, the term
    grant lands in ctrl row 2."""
    new_term, elected, votes = _vote_core(
        cfg, state.ctrl[0], state.ctrl[1], state.ctrl[2],
        cand, cand_term, rep_idx, alive, quorum,
    )
    new_ctrl = state.ctrl.at[2].set(new_term)
    return state._replace(ctrl=new_ctrl), elected, votes


def _vote_core(
    cfg: EngineConfig,
    log_end: jax.Array,
    last_term: jax.Array,
    current_term: jax.Array,
    cand: jax.Array,
    cand_term: jax.Array,
    rep_idx: jax.Array,
    alive: jax.Array,
    quorum: jax.Array | None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    R = cfg.replicas
    alive = _normalize_alive(alive, cfg.partitions, R)  # [P, R]
    if quorum is None:
        quorum = jnp.full((cfg.partitions,), cfg.quorum, jnp.int32)
    electing = (cand >= 0) & (cand < R)
    is_cand = (cand == rep_idx) & electing
    self_alive = alive[:, rep_idx]
    cand_alive = jnp.where(
        electing,
        jnp.take_along_axis(alive, jnp.clip(cand, 0, R - 1)[:, None], axis=1)[:, 0],
        False,
    )

    my_last_term = last_term
    c_end = _bcast_from_leader(log_end, is_cand & self_alive)
    c_last_term = _bcast_from_leader(my_last_term, is_cand & self_alive)

    up_to_date = (c_last_term > my_last_term) | (
        (c_last_term == my_last_term) & (c_end >= log_end)
    )
    grant = electing & self_alive & cand_alive & (cand_term > current_term) & up_to_date

    votes = lax.psum(grant.astype(jnp.int32), AXIS)
    elected = votes >= quorum

    new_term = jnp.where(grant, cand_term, current_term)
    return new_term, elected, votes


def read_batch(
    cfg: EngineConfig,
    state: ReplicaState,
    partition: jax.Array,  # int32 scalar
    offset: jax.Array,     # int32 scalar — storage offset to read from
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Read up to RB *committed* rows of one partition from this replica.

    Returns (rows [RB, SB] uint8 — header-prefixed, lens [RB] int32,
    count int32). `count` counts storage rows (including length-0
    alignment padding; decode_entries skips those), so the caller's next
    storage offset is `offset + count`. Serves the consume path; like the
    reference this is a replica-local read with no extra consensus round
    (PartitionStateMachine.handleBatchRead:85 — leader-local, no
    read-index), but unlike the reference it only exposes rows below the
    commit index.

    `offset` is an ABSOLUTE storage offset; the physical row of offset
    `a` is `a % slots` (ring — see core.state). The read window may wrap
    the ring end, so rows are blended from two windows: [pos, pos+RB)
    (clamped+rolled) and the ring head [0, RB). Host contract: offset is
    at least the host's trim watermark — ring rows below trim may have
    been reclaimed (the host serves those from the segment store).
    """
    return read_batch_at(
        cfg, state.log_data[None], state.commit[None], jnp.int32(0),
        partition, offset,
    )


def read_batch_at(
    cfg: EngineConfig,
    log_data: jax.Array,   # uint8 [R, P, S+B, SB] — FULL log, no copy
    commit: jax.Array,     # int32 [R, P]
    replica: jax.Array,    # int32 scalar
    partition: jax.Array,  # int32 scalar
    offset: jax.Array,     # int32 scalar — absolute storage offset
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """read_batch addressing the full multi-replica log with dynamic
    slices — NO whole-replica gather. This matters under vmap (batched
    reads, ops-level: engine read_many): `tree.map(x[replica])` per query
    would materialize a [P, S, SB] copy of the log PER QUERY; here each
    query moves exactly 2xRB rows."""
    RB, S = cfg.read_batch, cfg.slots
    SP = S + cfg.max_batch  # physical rows incl. wrap margin
    R = log_data.shape[0]
    replica = jnp.clip(replica, 0, R - 1)
    partition = jnp.clip(partition, 0, cfg.partitions - 1)
    com = lax.dynamic_slice(commit, (replica, partition), (1, 1))[0, 0]
    start = jnp.maximum(offset, 0)
    count = jnp.clip(com - start, 0, RB)
    pos = start % S
    # Window A: physical [pos, pos+RB). dynamic_slice clamps the start so
    # the window fits; compensate by slicing at a clamped start and
    # rolling the wanted rows to the front.
    sl_start = jnp.clip(pos, 0, SP - RB)
    shift = pos - sl_start
    rows_a = lax.dynamic_slice(
        log_data,
        (replica, partition, sl_start, 0),
        (1, 1, RB, cfg.slot_bytes),
    )[0, 0]
    rows_a = jnp.roll(rows_a, -shift, axis=0)
    # Window B: ring head [0, RB) — serves row i when pos + i wraps past
    # the ring end (margin rows are never live; see core.state).
    rows_b = lax.dynamic_slice(
        log_data, (replica, partition, 0, 0), (1, 1, RB, cfg.slot_bytes)
    )[0, 0]
    wrap_at = S - pos  # first window-index served from the ring head
    rows_b = jnp.roll(rows_b, wrap_at, axis=0)  # b[i] = head[i - wrap_at]
    i = jnp.arange(RB, dtype=jnp.int32)
    rows = jnp.where((i < wrap_at)[:, None], rows_a, rows_b)
    valid = i < count
    rows = jnp.where(valid[:, None], rows, 0)
    lens = jnp.where(valid, row_lens(rows), 0)
    return rows, lens, count


def read_offset(
    state: ReplicaState,
    partition: jax.Array,
    consumer_slot: jax.Array,
) -> jax.Array:
    """Current committed offset for one consumer slot."""
    P, C = state.offsets.shape
    return state.offsets[
        jnp.clip(partition, 0, P - 1), jnp.clip(consumer_slot, 0, C - 1)
    ]
