"""Pure data-plane core: fixed-shape log tensors and jitted Raft steps."""

from ripplemq_tpu.core.config import EngineConfig
from ripplemq_tpu.core.encode import build_step_input, decode_entries
from ripplemq_tpu.core.state import ReplicaState, StepInput, StepOutput, init_state

__all__ = [
    "EngineConfig",
    "ReplicaState",
    "StepInput",
    "StepOutput",
    "init_state",
    "build_step_input",
    "decode_entries",
]
