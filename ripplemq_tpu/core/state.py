"""Replicated data-plane state as fixed-shape arrays.

One `ReplicaState` is the full data-plane state of ONE replica: the slotted
message log, Raft bookkeeping scalars and the consumer-offset table for
every partition hosted by the program. The reference keeps the equivalent
state as `List<String> messages` + `Map<String, Long> consumerOffsets` per
partition group (reference:
mq-broker/src/main/java/metadata/raft/PartitionStateMachine.java:26-27),
purely in JVM heap; here it is a pytree of device arrays so that
replication, quorum and apply are tensor ops.

Row format: every log slot is `slot_bytes` of uint8 with an embedded
8-byte header — payload length then Raft term, both little-endian int32
(see core.config.ROW_HEADER). One array holds everything the Raft log
needs, so the append write phase is ONE DMA per (replica, partition).

Ring retention: `log_end` and `commit` are MONOTONE absolute storage
offsets; the physical log holds the last `slots` rows as a ring (row for
absolute offset `a` lives at physical row `a % slots`) plus a
`max_batch`-row margin so the append DMA's fixed [B, SB] window never
wraps (rows landing in the margin are always beyond the round's advance —
dead padding that no read ever selects). Overwriting ring rows is gated
by a host-fed `trim` watermark (see step.replica_control): rows below
`trim` are reclaimable because the host has already persisted them to the
segment store (the disk is the log of record; the device ring is the hot
serving window). The reference instead grows partition state without
bound in JVM heap (PartitionStateMachine.java:26-27) — bounded HBM +
unbounded disk strictly dominates that over time. Offsets are int32 (the
TPU-native scalar width); the host refuses appends near the 2^31-row
per-partition horizon (broker.dataplane._OFFSET_HORIZON) rather than
letting them wrap.

Axis conventions (see EngineConfig):
  P = partitions, R = replicas, S = log slots, SB = slot bytes,
  B = append batch, C = consumer table width, U = offset-update batch.

Arrays never carry the replica axis here — the replica axis is added
either by `jax.vmap(..., axis_name="replica")` (single-device simulation)
or by sharding over a mesh axis with `shard_map` (real SPMD). The step
functions in `core.step` are written against axis name "replica" and run
unchanged under both.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ripplemq_tpu.core.config import EngineConfig


class ReplicaState(NamedTuple):
    """Per-replica data-plane state (one replica's view of P partitions)."""

    log_data: jax.Array     # uint8 [P, S+B, SB] — ring rows + margin (see module doc)
    log_end: jax.Array      # int32 [P]        — next ABSOLUTE storage offset (ALIGN-padded)
    last_term: jax.Array    # int32 [P]        — term of the tail row (cached
    #                         prevLogTerm: maintained by every committed
    #                         round, travels with resync copies; avoids a
    #                         per-round row gather)
    current_term: jax.Array  # int32 [P]       — latest term this replica has seen
    commit: jax.Array       # int32 [P]        — commit index (absolute offsets
    #                         [trim, commit) are committed and ring-resident)
    offsets: jax.Array      # int32 [P, C]     — replicated consumer offsets


# Bookkeeping scalars stacked (in this order) into FusedReplicaState.ctrl.
CTRL_FIELDS = ("log_end", "last_term", "current_term", "commit")
CTRL_K = len(CTRL_FIELDS)


class FusedReplicaState(NamedTuple):
    """ReplicaState with the four per-partition bookkeeping vectors
    stacked into ONE [K, P] int32 array (EngineConfig.fused_control).

    Rationale (PROFILE.md r5 finding 3): the control phase's cost is
    fusion-boundary overhead across dozens of small [R, P] element-wise
    ops, not arithmetic. Carrying the scalars as one array lets the
    round's bookkeeping advance as a handful of wide ops on one buffer
    (core.step.replica_control_fused) and keeps the scan carry of a
    chained launch to three leaves instead of six.

    The named accessors mirror ReplicaState so host-side readers
    (DataPlane._fetch_state, read paths, tests) work on either
    representation; they are views, not extra buffers. Conversion in
    both directions is exact (`fuse_state` / `unfuse_state`).

    Under the spmd binding the engine-stacked ctrl is [R, K, P] sharded
    ("replica", None, "part") — the K bookkeeping rows stay whole on
    every device while replicas and partitions shard
    (parallel.engine._fused_state_specs), which is what lets the round's
    two leader broadcasts ride ONE [2, local_P] psum over the replica
    mesh axis (one ICI collective where the legacy layout issues two)
    and keeps the named-accessor views valid on process-sharded state
    (the slice is along the unsharded K axis)."""

    log_data: jax.Array     # uint8 [P, S+B, SB] — identical to ReplicaState
    ctrl: jax.Array         # int32 [K, P]       — CTRL_FIELDS, stacked
    offsets: jax.Array      # int32 [P, C]       — identical to ReplicaState

    # A leading replica axis (engine-stacked state) moves ctrl to
    # [R, K, P]; `...` keeps the accessors shape-agnostic.
    @property
    def log_end(self) -> jax.Array:
        return self.ctrl[..., 0, :]

    @property
    def last_term(self) -> jax.Array:
        return self.ctrl[..., 1, :]

    @property
    def current_term(self) -> jax.Array:
        return self.ctrl[..., 2, :]

    @property
    def commit(self) -> jax.Array:
        return self.ctrl[..., 3, :]


def fuse_state(state: ReplicaState) -> FusedReplicaState:
    """Stack the bookkeeping scalars into the fused layout (exact)."""
    ctrl = jnp.stack(
        [getattr(state, f) for f in CTRL_FIELDS], axis=-2
    ).astype(jnp.int32)
    return FusedReplicaState(
        log_data=state.log_data, ctrl=ctrl, offsets=state.offsets
    )


def unfuse_state(state: FusedReplicaState) -> ReplicaState:
    """Split the fused layout back into named fields (exact inverse)."""
    return ReplicaState(
        log_data=state.log_data,
        log_end=state.log_end,
        last_term=state.last_term,
        current_term=state.current_term,
        commit=state.commit,
        offsets=state.offsets,
    )


class StepInput(NamedTuple):
    """One replication round's input (per partition).

    Fed identically to every replica by the single controller: the
    leader→follower AppendEntries transfer of the reference
    (mq-broker/.../MessageAppendRequestProcessor.java:59) is realised by
    the input's sharding layout — XLA broadcasts the batch over the
    replica mesh axis on ICI as part of data distribution.

    `entries` rows are pre-packed with headers (length + round term) by
    the host encoder; rows at index >= counts[p] carry length 0 but still
    a valid term (they become the round's alignment padding).
    """

    entries: jax.Array     # uint8 [P, B, SB] — packed rows (leader's batch)
    counts: jax.Array      # int32 [P]        — how many of B carry payloads
    off_slots: jax.Array   # int32 [P, U]     — consumer-table slots to update
    off_vals: jax.Array    # int32 [P, U]     — new absolute offsets
    off_counts: jax.Array  # int32 [P]        — how many of U are valid
    leader: jax.Array      # int32 [P]        — replica id of partition leader (-1 = none)
    term: jax.Array        # int32 [P]        — leader's term (host/election-managed)
    extents: jax.Array | None = None  # int32 [P] — rows of the [B, SB]
    #                        window the write phase must cover this round
    #                        (the host knows the payload extent at
    #                        pack time; EngineConfig.packed_writes clips
    #                        the append DMA to it — ops/append.py). The
    #                        control phase clamps to [advance, B], so a
    #                        missing/short extent can never under-write a
    #                        committed round. None (pytree-empty) means
    #                        "full window", the legacy write shape.


class StepOutput(NamedTuple):
    """Per-partition results of one round (identical on every replica
    after the psum — the host reads any one replica's copy)."""

    base: jax.Array        # int32 [P] — leader log_end before append (first assigned slot)
    votes: jax.Array       # int32 [P] — number of replicas that acked the round
    committed: jax.Array   # bool  [P] — quorum reached this round
    commit: jax.Array      # int32 [P] — post-round commit index


def init_state(cfg: EngineConfig) -> ReplicaState:
    """Zero state for one replica."""
    P, S, SB, C = cfg.partitions, cfg.slots, cfg.slot_bytes, cfg.max_consumers
    return ReplicaState(
        log_data=jnp.zeros((P, S + cfg.max_batch, SB), jnp.uint8),
        log_end=jnp.zeros((P,), jnp.int32),
        last_term=jnp.zeros((P,), jnp.int32),
        current_term=jnp.zeros((P,), jnp.int32),
        commit=jnp.zeros((P,), jnp.int32),
        offsets=jnp.zeros((P, C), jnp.int32),
    )


def empty_input(cfg: EngineConfig) -> StepInput:
    """An all-empty round (no appends, no offset commits, no leaders)."""
    P, B, SB, U = cfg.partitions, cfg.max_batch, cfg.slot_bytes, cfg.max_offset_updates
    return StepInput(
        entries=jnp.zeros((P, B, SB), jnp.uint8),
        counts=jnp.zeros((P,), jnp.int32),
        off_slots=jnp.zeros((P, U), jnp.int32),
        off_vals=jnp.zeros((P, U), jnp.int32),
        off_counts=jnp.zeros((P,), jnp.int32),
        leader=jnp.full((P,), -1, jnp.int32),
        term=jnp.zeros((P,), jnp.int32),
        extents=jnp.zeros((P,), jnp.int32),
    )


def row_lens(rows: jax.Array) -> jax.Array:
    """Payload lengths from packed rows' headers: uint8 [..., SB] → int32
    [...]. Little-endian, matching the host encoder (encode.pack_row)."""
    hdr = rows[..., 0:4].astype(jnp.int32)
    return hdr[..., 0] | (hdr[..., 1] << 8) | (hdr[..., 2] << 16) | (hdr[..., 3] << 24)


def row_terms(rows: jax.Array) -> jax.Array:
    """Raft terms from packed rows' headers."""
    hdr = rows[..., 4:8].astype(jnp.int32)
    return hdr[..., 0] | (hdr[..., 1] << 8) | (hdr[..., 2] << 16) | (hdr[..., 3] << 24)
