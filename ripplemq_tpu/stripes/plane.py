"""StripeReplicator: the striped twin of broker/replication.py's
RoundReplicator — same interface (begin/wait/replicate/catchup/
sync_members/take_suspects/stop), different durability mechanics.

Instead of streaming a FULL copy of every committed-round record to
every standby, one ENCODER thread drains the queued backlog as group
commits (the same caps as the full-copy sender), serializes each group
into one blob, runs ONE GF(2⁸) matmul through ops/rs.py to produce
RS_K data + RS_M parity stripes (stripes/codec.py), and fans each
stripe out to the standby its replicated assignment names
(stripe_assignment beside the standby set in metadata). Standbys
persist stripe frames (REC_STRIPE, header-covered CRC) instead of full
rows — replication bytes scale with (k+m)/k instead of the standby
count.

The durability fence generalizes PR 2/3's discipline:

- **Settle at any k stripe-acks.** A round's future resolves once
  acked stripes cover >= RS_K DISTINCT indices — the blob is then
  reconstructible from standbys alone, which is the full-copy
  invariant ("every settled append survives controller death")
  restated for stripes. The remaining m stripes keep streaming in the
  background, raising tolerance to m holder losses.
- **Fewer than k reachable stripe-holders refuses to settle** (the
  PR 2 empty-set refusal generalized): if members leave the set until
  the not-yet-acked stripes can no longer reach k distinct indices,
  the round fails with ReplicationError — producers get a retryable
  refusal, nothing acks without a rebuildable copy. An EMPTY set
  refuses outright once members ever existed (genesis keeps the
  bootstrap behavior).
- **Epoch fencing** is unchanged: every repl.stripes RPC is stamped
  from the ACTIVE view per delivery attempt, standbys refuse stale
  epochs, and a deposed sender fails its backlog with FencedError.
- **Per-member FIFO order** is unchanged: one encoder assigns group
  sequence numbers (gsn, monotone per controller generation; the
  frame's epoch disambiguates across generations) and each member's
  sender delivers its frames in gsn order, so every store receives a
  consistently ordered stripe stream (recovery replays groups in
  (epoch, catchup-first, gsn) order — stripes/recovery.py).

Catch-up re-stripes: a joining standby receives the controller's FULL
store prefix as fresh catch-up groups encoded under the prospective
membership (only the joiner's stripe indices are streamed to it), with
live groups buffering behind exactly like the full-copy protocol — so
membership change is also the re-striping path that restores coverage
after a member loss.
"""

from __future__ import annotations

import heapq
import threading
import time
from concurrent.futures import Future
from typing import Callable, Optional

from ripplemq_tpu.broker.replication import (
    FencedError,
    ReplicationError,
)
from ripplemq_tpu.stripes.codec import (
    RS_K,
    RS_M,
    encode_group,
    stripe_assignment,
)
from ripplemq_tpu.obs.lockwitness import make_condition, make_lock
from ripplemq_tpu.obs.spans import ctx_from_wire
from ripplemq_tpu.utils.logs import get_logger

log = get_logger("stripes")

# Group-commit caps (the full-copy sender's, applied at the encoder:
# one blob per drained backlog up to these bounds).
_GROUP_COMMIT_BYTES = 8 << 20
_GROUP_COMMIT_ROUNDS = 128
_CATCHUP_BATCH_RECORDS = 256
_CATCHUP_BATCH_BYTES = 1 << 20
# One repl.stripes RPC carries at most this many queued frame batches.
_SEND_BATCH_BYTES = 8 << 20


class StripeTicket:
    """One round's in-flight striped replication (opaque; pass back to
    wait())."""

    __slots__ = ("fut", "start")

    def __init__(self, fut: Future, start: float) -> None:
        self.fut = fut
        self.start = start


class _Group:
    """Ack tracker for one encoded group: which stripe indices (and
    which MEMBERS) acked, which member holds each not-yet-acked stripe,
    and the round futures that resolve at quorum.

    Quorum = k distinct stripe indices AND min(#distinct members, k)
    distinct member acks. The member clause matters below k+m
    standbys, where the wrapped assignment loads several stripes onto
    one broker: counting indices alone would settle a round on a
    SINGLE standby's ack (its 3 stripes cover k) with nothing persisted
    anywhere else — strictly worse than full-copy mode's every-member
    fence. Requiring the member spread makes the settle wait for every
    distinct holder up to k of them, which is the best durability the
    small-set geometry admits (see ClusterConfig.replication docs)."""

    __slots__ = ("key", "futs", "targets", "acked", "acked_members",
                 "need_members")

    def __init__(self, key, futs, targets) -> None:
        self.key = key
        self.futs = futs          # list[Future] (one per round)
        self.targets = targets    # stripe idx -> broker id
        self.acked: set[int] = set()
        self.acked_members: set[int] = set()
        self.need_members = min(len(set(targets.values())), RS_K)

    def quorum(self) -> bool:
        return (len(self.acked) >= RS_K
                and len(self.acked_members) >= self.need_members)


class _StripeSender(threading.Thread):
    """Ordered stripe-frame stream to one standby. Entries are
    (key, frames, idxs, fut-or-None, tctxs-or-None): live entries ack
    through the replicator's group tracker, catch-up entries resolve
    their own future at RPC-ok; tctxs are the wire-form trace contexts
    of the group's sampled produces, stamped onto the repl.stripes
    request so holder-side apply spans join the trace."""

    def __init__(self, rep: "StripeReplicator", broker_id: int) -> None:
        super().__init__(daemon=True, name=f"stripe-sender-{broker_id}")
        self.broker_id = broker_id
        self._rep = rep
        self._cond = make_condition("_StripeSender._cond")
        self._queue: list[tuple] = []
        self._buffer: Optional[list[tuple]] = None
        self._stopped = False
        self.unreachable = False

    def enqueue(self, entry: tuple) -> None:
        with self._cond:
            if self._stopped:
                self._fail_entry(entry, ReplicationError("sender stopped"))
                return
            if self._buffer is not None:
                self._buffer.append(entry)
            else:
                self._queue.append(entry)
                self._cond.notify()

    def enqueue_catchup(self, entry: tuple) -> None:
        with self._cond:
            if self._stopped:
                self._fail_entry(entry, ReplicationError("sender stopped"))
                return
            self._queue.append(entry)
            self._cond.notify()

    def begin_buffer(self) -> None:
        with self._cond:
            if self._buffer is None:
                self._buffer = []

    def end_buffer(self) -> None:
        with self._cond:
            if self._buffer is not None:
                self._queue.extend(self._buffer)
                self._buffer = None
                self._cond.notify()

    @staticmethod
    def _fail_entry(entry: tuple, exc: Exception) -> None:
        fut = entry[3]
        if fut is not None and not fut.done():
            fut.set_exception(exc)

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            leftovers = self._queue + (self._buffer or [])
            self._queue = []
            self._buffer = None
            self._cond.notify()
        for entry in leftovers:
            self._fail_entry(entry, ReplicationError("sender stopped"))
        # No group notification needed: wait()'s coverage check treats a
        # member with a stopped sender (pruned from the map) as unable
        # to contribute its stripes.

    def run(self) -> None:
        backoff = 0.05
        failures = 0
        while True:
            with self._cond:
                while not self._queue and not self._stopped:
                    self._cond.wait(timeout=0.2)
                if self._stopped:
                    return
                batch = [self._queue.pop(0)]
                nbytes = sum(len(f) for f in batch[0][1])
                while self._queue and nbytes < _SEND_BATCH_BYTES:
                    nbytes += sum(len(f) for f in self._queue[0][1])
                    batch.append(self._queue.pop(0))
            frames = [f for entry in batch for f in entry[1]]
            tctxs = [t for entry in batch for t in (entry[4] or ())]

            def fail_all(exc: Exception) -> None:
                for entry in batch:
                    self._fail_entry(entry, exc)
                # Live entries' groups are failed by the tracker so
                # every round future of the group resolves at once.
                self._rep._fail_groups(
                    [e[0] for e in batch if e[3] is None], exc
                )

            while True:
                if self._stopped:
                    # A stopped sender (member pruned / replicator
                    # stopping) only fails ITS OWN per-entry futures
                    # (catch-up). Live groups are NOT failed: the other
                    # k+ holders can still settle them — failing (and
                    # tombstoning) them here would nack whole in-flight
                    # batches on an ordinary single-member prune. The
                    # wait()-side coverage check handles the case where
                    # this member's stripes were actually needed.
                    for entry in batch:
                        self._fail_entry(
                            entry, ReplicationError("sender stopped")
                        )
                    break
                if not self._rep.active():
                    fail_all(FencedError("controller deposed (local "
                                         "metadata)"))
                    break
                # Stamped per delivery attempt from the ACTIVE view —
                # never re-read after a deposition (the full-copy
                # sender's discipline, broker/replication.py).
                epoch = self._rep.epoch_fn()
                if not self._rep.active():
                    fail_all(FencedError("controller deposed (local "
                                         "metadata)"))
                    break
                t0 = (self._rep._clock()
                      if self._rep._h_frame_us is not None else 0.0)
                req = {"type": "repl.stripes", "epoch": epoch,
                       "frames": frames}
                if tctxs:
                    req["tctx"] = tctxs
                try:
                    resp = self._rep.client.call(
                        self._rep.addr_of(self.broker_id), req,
                        timeout=self._rep.rpc_timeout_s,
                    )
                except Exception:
                    failures += 1
                    if self._rep._c_retries is not None:
                        self._rep._c_retries.inc()
                    if failures >= 3:
                        self.unreachable = True
                    time.sleep(min(0.5, backoff * failures))
                    continue
                failures = 0
                self.unreachable = False
                if resp.get("ok"):
                    if self._rep._h_frame_us is not None:
                        self._rep._h_frame_us.observe(
                            self._rep._clock() - t0
                        )
                        self._rep._c_bytes.inc(nbytes)
                        self._rep._c_frames.inc(len(frames))
                    for entry in batch:
                        key, idxs, fut = entry[0], entry[2], entry[3]
                        if fut is not None:
                            if not fut.done():
                                fut.set_result(True)
                        else:
                            self._rep._ack(key, idxs,
                                           member=self.broker_id)
                    break
                if resp.get("error") == "stale_epoch":
                    fail_all(FencedError("standby reports newer epoch"))
                    break
                if resp.get("error") == "store_quarantined":
                    with self._rep._lock:
                        self._rep._suspects.add(self.broker_id)
                # Transient refusal (incl. bad_stripe_frame — a frame
                # damaged in flight re-sends from the in-memory copy).
                failures += 1
                time.sleep(min(0.5, backoff * failures))


class StripeReplicator:
    """Controller-side striped fan-out (see module docstring).

    Same constructor surface as RoundReplicator plus `stripe_map_fn`
    (the replicated stripe→member assignment; defaults to deriving it
    from members_fn via stripes/codec.stripe_assignment, which is
    byte-identical to what every manager apply records)."""

    def __init__(
        self,
        client,
        addr_of: Callable[[int], str],
        epoch_fn: Callable[[], int],
        members_fn: Callable[[], tuple],
        active_fn: Callable[[], bool],
        rpc_timeout_s: float = 3.0,
        ack_timeout_s: float = 5.0,
        metrics=None,
        stripe_map_fn: Optional[Callable[[], tuple]] = None,
        live_fn: Optional[Callable[[], list]] = None,
        encode_kw: Optional[dict] = None,
        sender_id: int = -1,
        pipeline_depth: int = 1,
    ) -> None:
        self.client = client
        self.addr_of = addr_of
        self.epoch_fn = epoch_fn
        self.members_fn = members_fn
        self.active = active_fn
        self.rpc_timeout_s = rpc_timeout_s
        self.ack_timeout_s = ack_timeout_s
        # Constructor parity with RoundReplicator (the broker passes one
        # kwargs dict to either plane). The stripe stream settles at
        # any-k acks, so one slow member never heads-of-line the round
        # the way the full-copy stream did — per-stream pipelining is
        # carried for parity and future use, not consulted yet.
        self.sender_id = int(sender_id)
        self.pipeline_depth = max(1, int(pipeline_depth))
        self.stripe_map_fn = stripe_map_fn or (
            lambda: stripe_assignment(members_fn())
        )
        # Liveness view (the manager's replicated `live` list): a holder
        # that is a set member but DEAD cannot contribute its stripes,
        # so the below-k refusal counts it out before a round queues.
        # None → every member counts (tests / bare planes).
        self.live_fn = live_fn
        # Extra kwargs for encode_group (tests pin platform="cpu").
        self.encode_kw = dict(encode_kw or {})
        if metrics is not None and getattr(metrics, "enabled", True):
            self._h_encode_us = metrics.histogram("stripes.encode_us")
            self._h_group = metrics.histogram("stripes.group_rounds")
            self._h_frame_us = metrics.histogram("stripes.frame_us")
            self._c_bytes = metrics.counter("stripes.bytes")
            self._c_frames = metrics.counter("stripes.frames")
            self._c_groups = metrics.counter("stripes.groups")
            self._c_retries = metrics.counter("stripes.send_retries")
            self._clock = metrics.clock
        else:
            self._h_encode_us = self._h_group = self._h_frame_us = None
            self._c_bytes = self._c_frames = None
            self._c_groups = self._c_retries = None
            self._clock = time.perf_counter
        # Causal-tracing hook (obs/spans.py): the owning broker sets
        # this to its SpanRing when trace sampling is configured;
        # begin() then records stripe.send spans (see its docstring).
        self.spans = None
        self._lock = make_lock("StripeReplicator._lock")
        self._senders: dict[int, _StripeSender] = {}
        self._joining: set[int] = set()
        self._suspects: set[int] = set()
        self._groups: dict[tuple[int, int], _Group] = {}
        # Future → group key (populated at encode, popped at group
        # resolution): wait()'s per-tick group lookup must be O(1), not
        # a scan of every in-flight group's round futures under the
        # lock the ack path contends on.
        self._fut_key: dict[Future, tuple[int, int]] = {}
        self._had_members = False
        self._stopped = False
        # Group sequence numbers must be unique across controller
        # RESTARTS at the same epoch (a plain 0-based counter collided
        # with the previous boot's groups on standby stores, read by
        # recovery as mixed generations — the seed-2 striped soak
        # found it as quarantine-grade data loss): seed the counter
        # from wall-clock milliseconds shifted past a 23-bit per-boot
        # counter space. Monotone as long as the clock advances ~1 ms
        # between boots of one broker — restarts take seconds.
        self._gsn = (int(time.time() * 1000) & 0xFFFFFFFFFF) << 23
        # Contiguous-settle watermark (the frames' `settled_floor`):
        # highest gsn at-or-below which every TRACKED group resolved
        # (settled or terminally failed). Stamped into every encoded
        # frame so recovery can tell acked loss (short group <= floor:
        # quarantine-grade) from a torn tail (short group > every
        # observed floor: never settled, droppable).
        self._floor = 0
        self._floor_pending: list[int] = []  # heapq of outstanding gsns
        self._floor_done: set[int] = set()
        self._enc_cond = make_condition("StripeReplicator._enc_cond")
        # Encoder inbox entries: (records, fut, tctxs) — tctxs the
        # wire-form trace contexts of the round's sampled produces
        # (None when untraced), carried through encode into the
        # sender entries and onto the repl.stripes frames.
        self._pending: list[tuple[list, Future, Optional[list]]] = []
        self._encoder = threading.Thread(
            target=self._encode_loop, daemon=True, name="stripe-encoder"
        )
        self._encoder.start()

    # -- sender management (RoundReplicator surface) --

    def _sender(self, bid: int) -> _StripeSender:
        with self._lock:
            if self._stopped:
                raise ReplicationError("replicator stopped")
            s = self._senders.get(bid)
            if s is None:
                s = _StripeSender(self, bid)
                self._senders[bid] = s
                s.start()
            return s

    def sync_members(self) -> None:
        members = set(self.members_fn())
        with self._lock:
            drop = [
                bid for bid in self._senders
                if bid not in members and bid not in self._joining
            ]
            dropped = [self._senders.pop(bid) for bid in drop]
        for s in dropped:
            s.stop()

    def is_joining(self, bid: int) -> bool:
        with self._lock:
            return bid in self._joining

    def take_suspects(self) -> set[int]:
        with self._lock:
            out = self._suspects
            self._suspects = set()
            return out

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            senders = list(self._senders.values())
            self._senders.clear()
            groups = list(self._groups.values())
            self._groups.clear()
            self._fut_key.clear()
        with self._enc_cond:
            # The encoder queue is _enc_cond's domain (begin/encode
            # touch it under that lock, never _lock).
            pending = list(self._pending)
            self._pending.clear()
            self._enc_cond.notify_all()
        for s in senders:
            s.stop()
        exc = ReplicationError("replicator stopped")
        for g in groups:
            for f in g.futs:
                if not f.done():
                    f.set_exception(exc)
        for entry in pending:
            if not entry[1].done():
                entry[1].set_exception(exc)

    # -- group ack tracking --

    def _mark_resolved_locked(self, gsn: int) -> None:
        """Advance the contiguous-settle floor past `gsn` (caller holds
        self._lock). Terminal failures count too: a failed group's
        rounds were NACKED, so recovery owes them nothing."""
        self._floor_done.add(gsn)
        while (self._floor_pending
               and self._floor_pending[0] in self._floor_done):
            g = heapq.heappop(self._floor_pending)
            self._floor_done.discard(g)
            if g > self._floor:
                self._floor = g

    def _ack(self, key, idxs: list[int],
             member: Optional[int] = None) -> None:
        """A member acked (persisted) stripes `idxs` of group `key`."""
        done: Optional[_Group] = None
        with self._lock:
            g = self._groups.get(key)
            if g is None:
                return  # already settled (quorum reached earlier)
            g.acked.update(idxs)
            if member is not None:
                g.acked_members.add(member)
            if g.quorum():
                done = self._groups.pop(key)
                self._forget_futs_locked(done)
                self._mark_resolved_locked(key[1])
        if done is not None:
            for f in done.futs:
                if not f.done():
                    f.set_result(True)

    def _fail_groups(self, keys: list, exc: Exception) -> None:
        failed: list[_Group] = []
        with self._lock:
            for key in keys:
                if key is None:
                    continue
                g = self._groups.pop(key, None)
                if g is not None:
                    failed.append(g)
                    self._forget_futs_locked(g)
                    self._mark_resolved_locked(key[1])
        for g in failed:
            for f in g.futs:
                if not f.done():
                    f.set_exception(exc)
        # TOMBSTONE the nacked groups (best-effort, not under a fence:
        # a deposed sender's streams are dead anyway): some of a failed
        # group's stripes may already sit on standby disks, and the
        # settled floor advances past the failure — without a tombstone
        # a later promotion would read the partial leftovers as ACKED
        # loss (short group <= floor) and falsely quarantine a healthy
        # store. Any one surviving tombstone frame tells recovery the
        # group was nacked and must drop.
        if failed and not isinstance(exc, FencedError) and self.active():
            for g in failed:
                try:
                    epoch, gsn = g.key
                    frames = encode_group([], epoch, gsn, tombstone=True,
                                          **self.encode_kw)
                    for bid in set(g.targets.values()):
                        idx = next(i for i, b in g.targets.items()
                                   if b == bid)
                        self._sender(bid).enqueue(
                            (None, [frames[idx]], [idx], None, None)
                        )
                except Exception:  # best-effort by design
                    log.debug("tombstone send for %s failed", g.key,
                              exc_info=True)

    def _group_of(self, fut: Future) -> Optional[_Group]:
        with self._lock:
            key = self._fut_key.get(fut)
            return self._groups.get(key) if key is not None else None

    def _forget_futs_locked(self, g: _Group) -> None:
        for f in g.futs:
            self._fut_key.pop(f, None)

    # -- hot path (DataPlane settle pipeline) --

    def begin(self, records: list,
              tctxs: Optional[list] = None) -> StripeTicket:
        """Queue one round for encoding; returns the ticket wait()
        blocks on. Fences and the generalized empty/below-k refusal
        fire HERE (before anything is enqueued) from the current map;
        the encoder and wait() re-check as membership moves. `tctxs`
        carries the wire-form trace contexts of the round's sampled
        produces: stamped onto the stripe frames and recorded as
        sender-side stripe.send spans that end when the round's stripe
        quorum (or terminal failure) resolves."""
        if not self.active():
            raise FencedError("controller deposed (local metadata)")
        held = self.stripe_map_fn()
        if held:
            self._had_members = True
        elif self._had_members:
            raise ReplicationError(
                "stripe-holder set empty (failover armed): no "
                "reconstructible copy to settle against"
            )
        fut: Future = Future()
        if not held:
            with self._lock:
                joining = bool(self._joining)
            if not joining:
                # Genesis (no standby ever joined, none joining):
                # bootstrap behavior — nothing to stripe against, the
                # round settles locally.
                fut.set_result(True)
                return StripeTicket(fut, time.monotonic())
            # A joiner's catch-up is in flight: the round must still
            # reach its buffered stream (the gap-free join invariant —
            # any record the catch-up scan misses must arrive live),
            # but no MEMBER holds stripes yet, so nothing gates the
            # settle. The encoder resolves the future after fan-out.
        reachable = set(self.members_fn())
        if self.live_fn is not None:
            reachable &= set(self.live_fn())
        coverage = {i for i, b in enumerate(held) if b in reachable}
        if len(coverage) < RS_K:
            # The generalized PR 2 refusal: fewer than k live stripe-
            # holders means no settleable round can be reconstructed
            # from standbys — refuse retryably until membership heals.
            raise ReplicationError(
                f"only {len(coverage)} of {RS_K + RS_M} stripes held by "
                f"live members (need {RS_K}): refusing to settle"
            )
        if tctxs and self.spans is not None:
            # One stripe.send span per sampled produce, covering encode
            # queue + fan-out + the k-quorum wait (the sender-side half
            # of the striped replication edge; holders record
            # stripe.apply on their side).
            for raw in tctxs:
                ctx = ctx_from_wire(raw)
                if ctx is None:
                    continue
                sp = self.spans.span("stripe.send", ctx)
                fut.add_done_callback(lambda _f, s=sp: s.end())
        with self._enc_cond:
            if self._stopped:
                raise ReplicationError("replicator stopped")
            self._pending.append((records, fut, tctxs))
            self._enc_cond.notify()
        return StripeTicket(fut, time.monotonic())

    def wait(self, ticket: StripeTicket,
             timeout_s: Optional[float] = None) -> None:
        """Block until the round's group reaches k distinct stripe-acks
        (or a fence/refusal). Ack deadline counts from begin(); slow
        members holding unacked stripes are flagged suspect after
        ack_timeout_s (the duty loop prunes them from the set, which in
        turn shrinks the achievable coverage — below k, the round
        refuses instead of hanging)."""
        fut = ticket.fut
        start = ticket.start
        suspected = False
        while True:
            try:
                fut.result(timeout=0.05)
                return
            except Exception as e:  # noqa: BLE001 — timeout vs outcome
                from concurrent.futures import (
                    TimeoutError as FuturesTimeoutError,
                )

                if not isinstance(e, (TimeoutError, FuturesTimeoutError)):
                    raise
            if not self.active():
                raise FencedError("controller deposed (local metadata)")
            elapsed = time.monotonic() - start
            if timeout_s is not None and elapsed > timeout_s:
                raise ReplicationError(
                    f"stripe quorum unconfirmed after {timeout_s}s"
                )
            g = self._group_of(fut)
            if g is None:
                continue  # not yet encoded, or resolving right now
            live = set(self.members_fn())
            achievable = set(g.acked) | {
                i for i, b in g.targets.items() if b in live
            }
            if len(achievable) < RS_K:
                if not self.active():
                    raise FencedError(
                        "controller deposed (local metadata)"
                    )
                self._fail_groups([g.key], ReplicationError(
                    f"stripe coverage fell below k={RS_K} "
                    f"(achievable {sorted(achievable)})"
                ))
                continue  # the future now carries the error
            # Member-quorum waiver (the full-copy member-left waiver
            # restated): a PRUNED member can never contribute its ack,
            # so the member requirement adapts down to what the
            # remaining holders can supply — stripes-acked >= k stays
            # the hard floor. Settle here if the adapted quorum is met
            # (the sender-side check uses the static requirement).
            ach_members = set(g.acked_members) | {
                b for b in g.targets.values() if b in live
            }
            need = min(len(ach_members), g.need_members)
            if len(g.acked) >= RS_K and len(g.acked_members) >= need:
                done: Optional[_Group] = None
                with self._lock:
                    if self._groups.get(g.key) is g:
                        done = self._groups.pop(g.key)
                        self._forget_futs_locked(done)
                        self._mark_resolved_locked(g.key[1])
                if done is not None:
                    for f in done.futs:
                        if not f.done():
                            f.set_result(True)
                continue
            if not suspected and elapsed > self.ack_timeout_s:
                suspected = True
                slow = {
                    b for i, b in g.targets.items()
                    if i not in g.acked and b in live
                }
                if slow:
                    log.warning(
                        "stripe holders %s not acking after %.1fs; "
                        "flagged suspect", sorted(slow),
                        self.ack_timeout_s,
                    )
                    with self._lock:
                        self._suspects.update(slow)

    def replicate(self, records: list,
                  timeout_s: Optional[float] = None) -> None:
        self.wait(self.begin(records), timeout_s=timeout_s)

    # -- encoder --

    def _encode_loop(self) -> None:
        while True:
            with self._enc_cond:
                while not self._pending and not self._stopped:
                    self._enc_cond.wait(timeout=0.2)
                if self._stopped:
                    return
                group = [self._pending.pop(0)]
                nbytes = sum(len(r[3]) for r in group[0][0])
                while (self._pending
                       and len(group) < _GROUP_COMMIT_ROUNDS
                       and nbytes < _GROUP_COMMIT_BYTES):
                    recs = self._pending[0][0]
                    nbytes += sum(len(r[3]) for r in recs)
                    group.append(self._pending.pop(0))
            try:
                self._encode_and_send(group)
            except Exception as e:  # encoder must never die
                log.warning("stripe encode failed: %s: %s",
                            type(e).__name__, e)
                for entry in group:
                    f = entry[1]
                    if not f.done():
                        f.set_exception(ReplicationError(
                            f"stripe encode failed: {e}"
                        ))

    def _encode_and_send(self, group: list[tuple]) -> None:
        futs = [e[1] for e in group]
        tctxs = [t for e in group for t in (e[2] or ())] or None
        if not self.active():
            exc = FencedError("controller deposed (local metadata)")
            for f in futs:
                if not f.done():
                    f.set_exception(exc)
            return
        held = self.stripe_map_fn()
        with self._lock:
            joining = set(self._joining)
        if not held and not joining:
            # Membership emptied between begin() and here: refuse (the
            # begin-side latch has already seen members, or begin
            # resolved the genesis case without enqueueing).
            exc = ReplicationError(
                "stripe-holder set empty (failover armed): no "
                "reconstructible copy to settle against"
            )
            for f in futs:
                if not f.done():
                    f.set_exception(exc)
            return
        epoch = self.epoch_fn()
        if not self.active():
            exc = FencedError("controller deposed (local metadata)")
            for f in futs:
                if not f.done():
                    f.set_exception(exc)
            return
        records = [r for e in group for r in e[0]]
        with self._lock:
            gsn = self._gsn
            self._gsn += 1
            floor = self._floor
            if held:
                # Tracked group: outstanding until its quorum (or its
                # terminal failure) — blocks the settle floor meanwhile.
                heapq.heappush(self._floor_pending, gsn)
        t0 = self._clock() if self._h_encode_us is not None else 0.0
        frames = encode_group(records, epoch, gsn, settled_floor=floor,
                              **self.encode_kw)
        if self._h_encode_us is not None:
            self._h_encode_us.observe(self._clock() - t0)
            self._c_groups.inc()
            self._h_group.observe_int(len(futs))
        key = (epoch, gsn)
        by_member: dict[int, list[int]] = {}
        for i, b in enumerate(held):
            by_member.setdefault(b, []).append(i)
        if held:
            # Only SET MEMBERS gate the settle: the tracker counts their
            # stripe-acks toward the k quorum. Joiners receive the round
            # too (below) but never count — a promotion only ever plans
            # from the replicated set, so a copy held solely by a
            # not-yet-admitted joiner proves nothing (the full-copy
            # waiver discipline restated for stripes).
            g = _Group(key, futs, {i: b for i, b in enumerate(held)})
            with self._lock:
                if self._stopped:
                    raise ReplicationError("replicator stopped")
                self._groups[key] = g
                for f in futs:
                    self._fut_key[f] = key
        for bid, idxs in by_member.items():
            self._sender(bid).enqueue(
                (key, [frames[i] for i in idxs], idxs, None, tctxs)
            )
        # Joining brokers get the round's DATA stripes on their
        # buffered stream (the gap-free join invariant: any record the
        # catch-up scan misses must reach the joiner live, exactly the
        # full-copy protocol's buffering) — key=None marks the entry
        # untracked, so joiner acks never reach the quorum tracker.
        for bid in joining:
            if bid in by_member:
                continue
            self._sender(bid).enqueue(
                (None, [frames[i] for i in range(RS_K)],
                 list(range(RS_K)), None, tctxs)
            )
        if not held:
            # No member gates the settle (first join in flight): the
            # round settles now that the joiner's stream carries it.
            for f in futs:
                if not f.done():
                    f.set_result(True)

    # -- catch-up (controller duty worker thread) --

    def catchup(self, bid: int, store, timeout_s: float = 600.0) -> None:
        """Stream the full local store prefix to a joining broker as
        catch-up groups carrying the k DATA stripes (buffering live
        groups behind, exactly like the full-copy protocol). Data
        stripes are plain slices of the blob, so the joiner holds the
        prefix SELF-reconstructible at exactly 1.0× its bytes — the
        same transfer cost as a full-copy catch-up. Only live rounds
        pay for (and benefit from) cross-set striping: a catch-up
        group sent with just the joiner's assigned indices would sit
        below k forever (no other broker ever held its stripes), which
        the first promotion smoke hit as an unrecoverable-group boot
        loop. This is also the re-striping path: a membership repair
        re-runs it, restoring any-k coverage after holder loss."""
        from ripplemq_tpu.storage.segment import REC_STRIPE

        s = self._sender(bid)
        with self._lock:
            self._joining.add(bid)
        data_idxs = list(range(RS_K))
        s.begin_buffer()
        last_fut: Optional[Future] = None
        try:
            batch: list = []
            nbytes = 0
            for rec in store.scan():
                if rec[0] == REC_STRIPE:
                    continue  # never re-stripe foreign stripes
                batch.append(rec)
                nbytes += len(rec[3])
                if (len(batch) >= _CATCHUP_BATCH_RECORDS
                        or nbytes >= _CATCHUP_BATCH_BYTES):
                    last_fut = self._enqueue_catchup(s, data_idxs, batch)
                    batch, nbytes = [], 0
            if batch or last_fut is None:
                last_fut = self._enqueue_catchup(s, data_idxs, batch)
        finally:
            s.end_buffer()
        last_fut.result(timeout=timeout_s)

    def _enqueue_catchup(self, s: _StripeSender, idxs: list[int],
                         records: list) -> Future:
        epoch = self.epoch_fn()
        with self._lock:
            gsn = self._gsn
            self._gsn += 1
            floor = self._floor
        frames = encode_group(records, epoch, gsn, catchup=True,
                              settled_floor=floor, **self.encode_kw)
        fut: Future = Future()
        s.enqueue_catchup(((epoch, gsn), [frames[i] for i in idxs],
                           idxs, fut, None))
        return fut

    def finish_join(self, bid: int) -> None:
        with self._lock:
            self._joining.discard(bid)
