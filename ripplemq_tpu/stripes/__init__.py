"""Striped replication plane: Reed–Solomon erasure coding on the hot
replication path (see stripes/codec.py for the geometry and frame
format, stripes/plane.py for the sender, stripes/recovery.py for the
rebuilt-from-any-k promotion path).

The codec is imported eagerly (it is the shared-geometry anchor
storage/erasure.py depends on); the plane and recovery modules load
LAZILY — they import the broker stack, and `storage.erasure →
stripes.codec` must not drag broker/server machinery into every
store open (the groups package learned the same lesson in PR 7)."""

from ripplemq_tpu.stripes.codec import (
    RS_K,
    RS_M,
    StripeFrame,
    StripeShortError,
    encode_group,
    parse_frame,
    reconstruct_group,
    stripe_assignment,
)

__all__ = [
    "RS_K",
    "RS_M",
    "StripeFrame",
    "StripeShortError",
    "StripeReplicator",
    "StripeDataLossError",
    "StripeRecoveryError",
    "encode_group",
    "parse_frame",
    "reconstruct_group",
    "rebuild_records",
    "stripe_assignment",
]

_LAZY = {
    "StripeReplicator": ("ripplemq_tpu.stripes.plane", "StripeReplicator"),
    "StripeDataLossError": (
        "ripplemq_tpu.stripes.recovery", "StripeDataLossError",
    ),
    "StripeRecoveryError": (
        "ripplemq_tpu.stripes.recovery", "StripeRecoveryError",
    ),
    "rebuild_records": ("ripplemq_tpu.stripes.recovery", "rebuild_records"),
}


def __getattr__(name: str):
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(name)
    import importlib

    return getattr(importlib.import_module(target[0]), target[1])
