"""Rebuilt-from-any-k recovery: turn a standby's stripe store (plus any
reachable peers' stripes) back into the full committed-round record
stream a promoted controller can replay.

A standby in striped mode persists REC_STRIPE frames for only ITS
assigned stripe indices, so promotion must gather the missing indices
from surviving peers: any RS_K distinct valid stripes of a group
reconstruct its blob byte-for-byte (ops/rs.py inverse solver through
stripes/codec.reconstruct_group). Groups replay in a deterministic
total order — (epoch, catchup-groups-first, gsn) — which reproduces
every store's arrival order: one encoder per controller generation
assigns monotone gsns, catch-up groups (full-prefix content) are
delivered ahead of the live groups buffered during the join, and
epochs order controller generations.

Failure ladder (the rebuild-or-quarantine contract, PR 4):

- a group short of k stripes while some configured peer was
  UNREACHABLE → StripeRecoveryError (transient: the takeover duty
  retries next tick; boot-failure abdication caps the loop);
- short of k with EVERY peer consulted → classified by the frames'
  SETTLED-FLOOR watermark (stripes/codec.py): every encoded frame
  carries the highest gsn at-or-below which all of its epoch's groups
  had resolved when it was cut. A short group AT-OR-BELOW any observed
  floor of its epoch was settled — its rounds were ACKED — so the
  shortfall is StripeDataLossError (quarantine-grade); a short group
  ABOVE every floor never settled (its producers were never acked, the
  torn-tail analogue) and is dropped with a log line. Short CATCH-UP
  groups drop too: their content is the prefix, redundantly covered by
  the other members' stripe streams the same rebuild collects.
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional

from ripplemq_tpu.stripes.codec import (
    RS_K,
    StripeFrame,
    StripeShortError,
    parse_frame,
    reconstruct_group,
)
from ripplemq_tpu.utils.logs import get_logger

log = get_logger("stripes")


class StripeRecoveryError(Exception):
    """Rebuild blocked TRANSIENTLY: a group is short of k stripes while
    at least one configured peer could not be consulted. Retryable."""


class StripeDataLossError(Exception):
    """Rebuild failed DEFINITIVELY: a non-tail group is short of k
    stripes with every peer consulted — acked data is unrecoverable
    (more than m holders lost). Quarantine-grade."""


def replay_order_key(frame: StripeFrame) -> tuple[int, int, int]:
    """Total replay order over groups: epochs ascend; within an epoch
    catch-up groups (the full-prefix stream) replay before live groups
    — a catch-up gsn is assigned while newer live gsns already exist,
    yet its content precedes them (see module docstring); gsns order
    the rest."""
    return (frame.epoch, 0 if frame.catchup else 1, frame.gsn)


def collect_stripe_groups(
    records: Iterable[tuple[int, int, int, bytes]],
    groups: Optional[dict] = None,
) -> tuple[dict, list[tuple[int, int, int, bytes]]]:
    """Split a store scan into stripe groups and pass-through records.

    Returns ({(epoch, gsn): {idx: StripeFrame}}, [non-stripe records in
    scan order]). Unparseable stripe payloads (CRC rot) count as
    missing, never as wrong bytes. `groups` merges into an existing
    collection (first valid frame per (key, idx) wins)."""
    from ripplemq_tpu.storage.segment import REC_STRIPE

    if groups is None:
        groups = {}
    passthrough: list[tuple[int, int, int, bytes]] = []
    for rec in records:
        rec_type = rec[0]
        if rec_type != REC_STRIPE:
            passthrough.append(rec)
            continue
        frame = parse_frame(bytes(rec[3]))
        if frame is None:
            continue  # rotted stripe: missing, handled by any-k rebuild
        slot = groups.setdefault(frame.key, {})
        # Tombstones live under negative keys so they can never shadow
        # (or be shadowed by) a real stripe index in the merge.
        key = -1 - frame.idx if frame.tombstone else frame.idx
        slot.setdefault(key, frame)
    return groups, passthrough


def merge_peer_frames(groups: dict, frames: Iterable[bytes]) -> int:
    """Merge raw peer-supplied stripe frames into a group collection;
    returns how many frames were adopted (CRC-validated first — a peer
    cannot inject bytes the frame CRC does not vouch for)."""
    adopted = 0
    for raw in frames:
        frame = parse_frame(bytes(raw))
        if frame is None:
            continue
        slot = groups.setdefault(frame.key, {})
        key = -1 - frame.idx if frame.tombstone else frame.idx
        if key not in slot:
            slot[key] = frame
            adopted += 1
    return adopted


def fetch_peer_stripes(groups: dict,
                       peer_fetchers: list[tuple[str, Callable]],
                       ) -> tuple[int, list[str]]:
    """Pull every reachable peer's stripe frames into `groups`.

    `peer_fetchers` is [(tag, callable(after: int) -> (frames, next))]
    — a paged scan of the peer's REC_STRIPE records (the stripe.fetch
    RPC). Returns (frames adopted, [tags of UNREACHABLE peers]) — the
    unreachable list decides transient-vs-definitive failure."""
    adopted = 0
    unreachable: list[str] = []
    for tag, fetch in peer_fetchers:
        cursor = -1  # opaque to this side: the peer interprets it
        try:
            while True:
                frames, nxt = fetch(cursor)
                adopted += merge_peer_frames(groups, frames)
                if nxt is None:
                    break
                cursor = nxt
        except Exception as e:  # peer down mid-scan: partial adopt OK
            log.warning("stripe fetch from %s failed: %s: %s",
                        tag, type(e).__name__, e)
            unreachable.append(tag)
    return adopted, unreachable


def rebuild_records(
    local_records: Iterable[tuple[int, int, int, bytes]],
    peer_fetchers: Optional[list[tuple[str, Callable]]] = None,
    peers_incomplete: bool = False,
    **reconstruct_kw,
) -> list[tuple[int, int, int, bytes]]:
    """The promotion rebuild: local scan (+ peer stripes) → the full
    committed-round record stream in replay order.

    Non-stripe records (a deposed ex-controller's own full prefix —
    chronologically older than every stripe it later received as a
    standby) pass through FIRST in scan order; stripe groups follow in
    replay_order_key order. Raises per the module-docstring ladder;
    `peers_incomplete` forces the transient classification even when
    every listed fetcher responded (caller knows some configured broker
    was not listed — e.g. known-crashed)."""
    groups, passthrough = collect_stripe_groups(local_records)
    unreachable: list[str] = []
    if peer_fetchers:
        _, unreachable = fetch_peer_stripes(groups, peer_fetchers)
    incomplete = peers_incomplete or bool(unreachable)

    out = list(passthrough)
    ordered = sorted(
        groups.items(),
        key=lambda kv: replay_order_key(next(iter(kv[1].values()))),
    )
    # Per-epoch settled-floor high-water marks across EVERY collected
    # frame: the authority on which groups were acked (module
    # docstring; stamped by the encoder's contiguous-settle tracker).
    floors: dict[int, int] = {}
    for _, frames in ordered:
        for f in frames.values():
            if f.settled_floor > floors.get(f.epoch, 0):
                floors[f.epoch] = f.settled_floor
    dropped: list = []
    for key, frames in ordered:
        if any(f.tombstone for f in frames.values()):
            # The group was terminally NACKED by its controller after
            # some stripes shipped (plane.py _fail_groups): its
            # producers saw a refusal, so the partial leftovers are
            # debris, never acked loss — drop regardless of the floor.
            dropped.append(key)
            continue
        frames = {i: f for i, f in frames.items() if i >= 0}
        try:
            out.extend(reconstruct_group(frames, **reconstruct_kw))
        except (StripeShortError, ValueError) as e:
            if incomplete:
                raise StripeRecoveryError(
                    f"group {key} unrecoverable ({e}) with peers "
                    f"unreachable: {unreachable or 'incomplete set'}"
                ) from e
            epoch, gsn = key
            any_f = next(iter(frames.values()))
            if not any_f.catchup and gsn <= floors.get(epoch, 0):
                raise StripeDataLossError(
                    f"settled group {key} unrecoverable ({e}; floor "
                    f"{floors.get(epoch, 0)}): acked data lost beyond "
                    f"the k={RS_K}-of-k+m contract"
                )
            dropped.append(key)
    if dropped:
        log.warning(
            "dropping %d unsettled stripe group(s) %s (above every "
            "settled floor / catch-up duplicates — never acked)",
            len(dropped), dropped[:8],
        )
    return out
