"""Stripe codec: the ONE Reed–Solomon geometry plus the wire/store frame
format of the striped replication plane.

A *group* is one sender group-commit's worth of committed-round records
(the exact (rec_type, slot, base, payload) tuples the segment store
persists), serialized into one blob and encoded into RS_K data + RS_M
parity stripes with ONE GF(2⁸) matmul through ops/rs.py — the Pallas
kernel on TPU, the bit-linear XLA fallback elsewhere. Any RS_K of the
RS_K+RS_M stripes reconstruct the blob byte-for-byte (extended-Cauchy
MDS property, ops/rs.py), so shipping DISTINCT stripes to distinct
standbys buys R=5-equivalent 2-loss durability at (k+m)/k ≈ 1.67×
replication bytes instead of full copies' (R−1)×.

The matmul is jit-compiled per shard length, so shard lengths are padded
up to a bounded ladder of SIZE CLASSES before encoding (`_shard_class`)
— compute pads, wire bytes do not: the GF matmul is per-byte-column
independent, so parity columns beyond the real shard length are zero and
are trimmed before framing (data stripes ship exactly their slice of the
blob). Replication byte cost therefore stays (k+m)/k × blob + k+m frame
headers, independent of the class ladder.

The sealed-segment protection plane (storage/erasure.py) imports RS_K /
RS_M from here: one geometry, two consumers — the off-path segment
shards and the hot-path stripes reconstruct with the same matrices.
"""

from __future__ import annotations

import struct
import zlib
from typing import Iterable, NamedTuple, Optional

import numpy as np

from ripplemq_tpu.ops.rs import gf_matmul, generator_matrix, rs_reconstruct

# The one RS geometry (storage/erasure.py aliases these as K / M).
RS_K = 3
RS_M = 2

_MAGIC = 0x53545250  # "STRP"
_VERSION = 1
# Flag bits (the `flags` byte of the frame header).
FLAG_CATCHUP = 0x01  # group carries the catch-up prefix stream, not a
#                      live round: replay orders it BEFORE same-epoch
#                      live groups (see recovery.replay_order_key)
FLAG_TOMBSTONE = 0x02  # the group was terminally NACKED after some of
#                        its stripes may have shipped: recovery must
#                        DROP the group (its producers saw a refusal)
#                        instead of reading its partial leftovers as
#                        acked loss once the settled floor passes it

# magic u32, version u8, flags u8, stripe idx u8, k u8, m u8,
# epoch u32, gsn u64, settled floor u64, blob length u64, blob crc u32,
# frame crc u32. The frame crc covers every header byte before it plus
# the stripe payload (the storage/segment.py header-covered-CRC
# discipline: a flipped bit in idx/gsn/orig_len must refuse exactly
# like payload rot). `settled floor` is the encoder's contiguous-settle
# watermark — the highest gsn below-or-at which every live group of
# this epoch had reached its k-ack quorum when this frame was encoded.
# Recovery uses it to discriminate acked loss from a torn tail: a group
# at-or-below any observed floor MUST reconstruct (its rounds were
# acked — shortfall is quarantine-grade), one above every floor may
# drop (it never settled; its producers were never acked).
_HEADER = struct.Struct("<IBBBBBIQQQII")
_HEADER_PREFIX_LEN = _HEADER.size - 4  # bytes the frame crc covers

# Per-record framing inside a group blob: type u8, slot u32, base u32,
# payload length u32 (the segment store's own field widths), payload.
_REC = struct.Struct("<BIII")
_BLOB_COUNT = struct.Struct("<I")


class StripeFrame(NamedTuple):
    """One parsed, CRC-validated stripe frame."""

    epoch: int
    gsn: int
    idx: int
    k: int
    m: int
    flags: int
    settled_floor: int  # encoder's contiguous-settle watermark (gsn)
    orig_len: int  # blob length before striping
    blob_crc: int
    payload: bytes

    @property
    def key(self) -> tuple[int, int]:
        """Group identity: (epoch, gsn). gsn restarts at 0 per
        controller generation; the epoch disambiguates."""
        return (self.epoch, self.gsn)

    @property
    def catchup(self) -> bool:
        return bool(self.flags & FLAG_CATCHUP)

    @property
    def tombstone(self) -> bool:
        return bool(self.flags & FLAG_TOMBSTONE)


def serialize_records(records: Iterable[tuple[int, int, int, bytes]]) -> bytes:
    """Records → one group blob (count header + framed records)."""
    parts = [b""]
    n = 0
    for rec_type, slot, base, payload in records:
        parts.append(_REC.pack(int(rec_type), int(slot) & 0xFFFFFFFF,
                               int(base) & 0xFFFFFFFF, len(payload)))
        parts.append(bytes(payload))
        n += 1
    parts[0] = _BLOB_COUNT.pack(n)
    return b"".join(parts)


def deserialize_records(blob: bytes) -> list[tuple[int, int, int, bytes]]:
    """Group blob → records. Raises ValueError on framing damage (the
    blob CRC already passed, so damage here is a codec bug, not rot)."""
    if len(blob) < _BLOB_COUNT.size:
        raise ValueError("stripe blob shorter than its count header")
    (n,) = _BLOB_COUNT.unpack_from(blob, 0)
    pos = _BLOB_COUNT.size
    out: list[tuple[int, int, int, bytes]] = []
    for _ in range(n):
        if pos + _REC.size > len(blob):
            raise ValueError("stripe blob truncated mid-record-header")
        t, slot, base, length = _REC.unpack_from(blob, pos)
        pos += _REC.size
        if pos + length > len(blob):
            raise ValueError("stripe blob truncated mid-payload")
        out.append((t, slot, base, blob[pos : pos + length]))
        pos += length
    return out


# --------------------------------------------------------------- size
# classes: the GF matmul compiles once per static shard length, so
# shard lengths round UP to a bounded ladder (512 B steps to 16 KiB,
# then ×1.25 geometric) — a handful of programs cover every blob size.
_PACK = 512  # ops/rs.py packing width (bytes per packed lane row)
_LINEAR_MAX = 16 << 10


def _shard_class(n: int) -> int:
    """Smallest ladder entry >= n (compute padding only — parity
    columns past the real shard length are zero and never shipped)."""
    n = max(n, 1)
    if n <= _LINEAR_MAX:
        return -(-n // _PACK) * _PACK
    c = _LINEAR_MAX
    while c < n:
        c = -(-(c * 5) // (4 * _PACK)) * _PACK  # ×1.25, snapped to _PACK
    return c


def stripe_assignment(standbys: Iterable[int]) -> tuple[int, ...]:
    """Deterministic stripe→member map: stripe i is held by
    sorted(standbys)[i % len]. Every apply derives the identical tuple
    from the replicated standby set, so 'who holds what' is itself
    replicated metadata (promotion consults it; recovery asks every
    live broker anyway, so the map is a routing fact, not a safety
    dependency). With fewer than RS_K+RS_M members the map wraps —
    distinct stripes still go to distinct standbys as far as the set
    allows, and ALL k+m stripes are always held somewhere in the set."""
    members = sorted(set(int(b) for b in standbys))
    if not members:
        return ()
    return tuple(members[i % len(members)] for i in range(RS_K + RS_M))


# ------------------------------------------------------------- encode

def encode_group(records: Iterable[tuple[int, int, int, bytes]],
                 epoch: int, gsn: int, *, catchup: bool = False,
                 tombstone: bool = False,
                 settled_floor: int = 0,
                 **kw) -> list[bytes]:
    """Encode one group of records into RS_K+RS_M stripe frames.

    ONE gf_matmul computes the parity block (data stripes are plain
    slices of the blob — the identity rows of the extended generator
    need no compute). `kw` routes to ops/rs.gf_matmul (use_pallas /
    platform / interpret); the default picks the Pallas kernel on a TPU
    backend and the XLA bit-linear fallback elsewhere."""
    blob = serialize_records(records)
    blob_crc = zlib.crc32(blob) & 0xFFFFFFFF
    n = -(-max(len(blob), 1) // RS_K)  # shard length (ceil; >=1)
    nc = _shard_class(n)
    # Shard the blob at width n (data stripe i IS blob[i*n:(i+1)*n]),
    # then zero-pad each shard to the class width for the matmul only:
    # the GF product is per-byte-column independent, so parity columns
    # past n are zero and the [:, :n] trim is exact.
    padded = np.zeros(RS_K * n, np.uint8)
    padded[: len(blob)] = np.frombuffer(blob, np.uint8)
    data = padded.reshape(RS_K, n)
    data_c = np.zeros((RS_K, nc), np.uint8)
    data_c[:, :n] = data
    parity = np.asarray(
        gf_matmul(generator_matrix(RS_K, RS_M), data_c, **kw)
    )[:, :n]
    flags = (FLAG_CATCHUP if catchup else 0) | (
        FLAG_TOMBSTONE if tombstone else 0
    )
    frames: list[bytes] = []
    for i in range(RS_K + RS_M):
        if i < RS_K:
            payload = data[i].tobytes()
        else:
            payload = parity[i - RS_K].tobytes()
        prefix = _HEADER.pack(
            _MAGIC, _VERSION, flags, i, RS_K, RS_M,
            int(epoch) & 0xFFFFFFFF, int(gsn), int(settled_floor),
            len(blob), blob_crc, 0,
        )[:_HEADER_PREFIX_LEN]
        crc = zlib.crc32(payload, zlib.crc32(prefix)) & 0xFFFFFFFF
        frames.append(prefix + struct.pack("<I", crc) + payload)
    return frames


def parse_frame(frame: bytes) -> Optional[StripeFrame]:
    """Parse + CRC-validate one stripe frame; None on ANY damage (a
    rotted stripe counts as missing, never as wrong bytes)."""
    if len(frame) < _HEADER.size:
        return None
    (magic, version, flags, idx, k, m, epoch, gsn, floor, orig_len,
     blob_crc, frame_crc) = _HEADER.unpack_from(frame, 0)
    if magic != _MAGIC or version != _VERSION:
        return None
    if (k, m) != (RS_K, RS_M) or idx >= k + m:
        return None
    payload = frame[_HEADER.size :]
    if len(payload) != -(-max(orig_len, 1) // k):
        return None
    if zlib.crc32(
        payload, zlib.crc32(frame[:_HEADER_PREFIX_LEN])
    ) & 0xFFFFFFFF != frame_crc:
        return None
    return StripeFrame(epoch=epoch, gsn=gsn, idx=idx, k=k, m=m,
                       flags=flags, settled_floor=floor,
                       orig_len=orig_len, blob_crc=blob_crc,
                       payload=payload)


class StripeShortError(Exception):
    """Fewer than RS_K valid stripes of a group survive: the blob is
    unrecoverable from what the caller supplied."""


def reconstruct_group(
    frames: dict[int, StripeFrame], **kw
) -> list[tuple[int, int, int, bytes]]:
    """Rebuild one group's records from any RS_K of its stripes
    (`frames` maps stripe idx → parsed frame). Raises StripeShortError
    below k, ValueError on mixed generations or a blob-CRC mismatch
    (bytes reconstructed but provably wrong — treat as damage)."""
    valid = {i: f for i, f in frames.items() if f is not None}
    if len(valid) < RS_K:
        raise StripeShortError(
            f"only {len(valid)} valid stripes, need {RS_K}"
        )
    metas = {(f.epoch, f.gsn, f.orig_len, f.blob_crc, len(f.payload))
             for f in valid.values()}
    if len(metas) != 1:
        raise ValueError(f"mixed stripe generations in group: {metas}")
    any_f = next(iter(valid.values()))
    n = len(any_f.payload)
    if all(i in valid for i in range(RS_K)):
        blob = b"".join(valid[i].payload for i in range(RS_K))
    else:
        present = {
            i: np.frombuffer(valid[i].payload, np.uint8)
            for i in sorted(valid)[:RS_K]
        }
        nc = _shard_class(n)
        padded = {
            i: np.pad(v, (0, nc - n)) for i, v in present.items()
        }
        data = np.asarray(
            rs_reconstruct(padded, k=RS_K, m=RS_M, **kw)
        )[:, :n]
        blob = data.reshape(-1).tobytes()
    blob = blob[: any_f.orig_len]
    if zlib.crc32(blob) & 0xFFFFFFFF != any_f.blob_crc:
        raise ValueError("reconstructed blob fails its recorded CRC")
    return deserialize_records(blob)
