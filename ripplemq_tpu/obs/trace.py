"""Flight recorder: a fixed-size ring of structured lifecycle events.

Always on (unlike the metrics registry there is no off switch): the
whole point of a flight recorder is that the events preceding a failure
were already captured when the failure is noticed — the PR 4 term-skew
wedge was diagnosed by re-running under probes precisely because nothing
had recorded the election/advert interleaving the first time. Elle's
lesson applies (arXiv:2003.10554): a checker verdict is most useful when
it points at the responsible window of the history, and the ring IS that
window.

Cost per append: one itertools.count tick (C-level, thread-safe slot
assignment), one clock read, one tuple + kwargs dict build, one list
store — ~a few hundred ns. Events are recorded per ROUND or per
control-plane transition, never per message, so even a saturated broker
appends a few thousand events/s against a default 4096-slot ring
(~the last second or two of life under full load; minutes when idle or
faulted — exactly when the history matters).

Ring writes are wait-free against each other (distinct slots via the
atomic counter); `snapshot()` reads racy-consistent — an entry being
overwritten mid-read can surface as a slightly out-of-window event,
never as a torn tuple (slot stores are single reference assignments).

Event timestamps are WALL CLOCK (`time.time()`), deliberately unlike
the metrics clock: traces from different processes (proc-backend
brokers, the nemesis fault log) merge into one timeline by `t`.
"""

from __future__ import annotations

import itertools
import time
from typing import Callable, Optional

_DEFAULT_CAPACITY = 4096

# The CLOSED event vocabulary: every `recorder.record("<type>", ...)`
# emit site in the library must name a member, every member must have
# a live emit site, and every member is documented in the README
# Observability section — all three machine-checked by ripplelint's
# trace_vocab rule (analysis/trace_vocab.py). Timeline tooling, chaos
# verdict readers, and postmortem walkthroughs key on these names;
# an undocumented event is a timeline entry nobody can interpret.
EVENT_TYPES = frozenset({
    # Round lifecycle (per ROUND, never per message).
    "dispatch", "commit", "settle_enter", "settle_release", "settle_fail",
    # Data-plane control transitions.
    "elect", "set_leader", "settled_gap", "stall_reset", "install",
    # Broker/controller lifecycle.
    "controller_boot", "boot_failed", "deposed", "abdicate",
    "standby_joined", "store_quarantine", "stripe_rebuild",
    # Multi-core host plane (parallel/hostplane.py): a worker
    # subprocess died / its respawn came up under a bumped generation.
    "host_worker_down", "host_worker_restart",
    # Consumer-group coordinator (manager applies + fencing).
    "group_join", "group_leave", "group_delete", "fence",
    # Control-plane wave batching (broker/server.py _batch_duty +
    # manager OP_BATCH apply): one wave of coalesced membership/pid
    # commands proposed; one wave-end deferred rebalance of a touched
    # group; one aggregated heartbeat frame relayed to the metadata
    # leader's liveness ledger.
    "meta_batch", "group_rebalance", "beats_relay",
    # SLO autopilot (slo/controller.py): one event per APPLIED knob
    # adjustment (the control timeline postmortems replay) and the
    # load-shedding state machine's transitions.
    "slo_adjust", "slo_shed_on", "slo_shed_off",
    # Shed-LADDER intermediate move (level 1↔2, slo/controller.py): the
    # shed stayed on but its tier bite escalated or stepped down.
    "slo_shed_level",
    # Follower reads (broker/server.py): the metadata leader committed
    # a follower-read lease table for the current controller epoch.
    "follower_lease",
    # Elastic partitions (broker/manager.py applies): a split opened
    # its dual-write handoff window, the reconfig duty closed it at
    # the settled watermark, a merge reabsorbed a child's range.
    "split_begin", "split_cutover", "merge_done",
})


class FlightRecorder:
    def __init__(self, capacity: int = _DEFAULT_CAPACITY,
                 clock: Optional[Callable[[], float]] = None) -> None:
        self._cap = max(16, int(capacity))
        self._buf: list = [None] * self._cap
        self._seq = itertools.count()
        self.clock: Callable[[], float] = (
            clock if clock is not None else time.time
        )

    def record(self, etype: str, **fields) -> None:
        """Append one event. `fields` must stay wire-primitive (str keys,
        int/float/str/bool/list values) — snapshots travel over
        `admin.trace` through the codec verbatim."""
        seq = next(self._seq)  # atomic slot assignment (C-level next)
        self._buf[seq % self._cap] = (seq, self.clock(), etype, fields)

    def snapshot(self, last: Optional[int] = None) -> list[dict]:
        """The ring's live window in seq order (oldest first), optionally
        clipped to the most recent `last` events. Wire-encodable."""
        entries = [e for e in self._buf if e is not None]
        entries.sort(key=lambda e: e[0])
        if last is not None and last >= 0:
            # last=0 must mean ZERO events ([-0:] would be the whole ring).
            entries = entries[-last:] if last > 0 else []
        # Reserved keys always win over same-named fields: `seq` is the
        # ring's ordering contract (snapshot is seq-sorted), and a field
        # shadowing it would silently break every timeline consumer.
        return [
            {**fields, "seq": seq, "t": t, "type": etype}
            for seq, t, etype, fields in entries
        ]
