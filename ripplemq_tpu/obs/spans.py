"""Causal tracing plane: cross-process span propagation + attribution.

The flight recorder (obs/trace.py) answers "what was this broker
doing"; the metrics registry answers "how fast on average". Neither
answers "where did THIS message's p99 go" — the question MegaScale
(arXiv:2402.15627) argues must be a built-in per-request capability.
This module is that capability for ripplemq: a trace CONTEXT (trace id
+ parent span id) stamped by the client on a sampled produce/consume,
carried as an optional `tctx` field in the ordinary request dicts on
both transports, and recorded by every layer that touches the request
into a per-process lock-cheap span ring.

Design rules, in priority order:

1. **No wall clocks.** Span timestamps are `time.perf_counter()` —
   monotonic, and the SAME clock the metrics plane stamps the engine's
   round-stage boundaries with, so the six settle-stage spans can reuse
   the round ctx timestamps verbatim — in the RECORDING process's clock
   domain; nothing ever compares timestamps from two processes
   directly. The assembler (obs/assemble.py)
   estimates per-process offsets NTP-style from matched parent/child
   RPC span pairs (request midpoint vs. serve midpoint) and maps every
   span into the root's domain before ordering anything. The chaos
   timeline learned this lesson the hard way: proc-backend wall clocks
   skew, and a skewed sort interleaves causally-ordered events
   backwards.
2. **Zero overhead when off.** Sampling is decided by the CLIENT
   (deterministically — see below); an unsampled request simply has no
   `tctx` key, and every server-side emit site goes through
   `ring.span(kind, ctx)` which returns the singleton `NULL_SPAN`
   without reading a clock or allocating when `ctx is None`. The
   `obs=False` / `trace_sample_n=0` path is therefore a dict-get plus
   one `is None` branch per hop.
3. **Deterministic sampling.** `trace_id = crc32(name) ⊕ mix(counter)`
   and the sampling predicate is `trace_id % trace_sample_n == 0` —
   same seed, same sampled set, no ambient randomness (the chaos
   schedules and the determinism lint stay pure).

Ring mechanics follow the flight recorder exactly: one atomic
`itertools.count` tick assigns the slot, stores are single reference
assignments (wait-free against each other, racy-consistent reads), and
spans are recorded AT END — a span that never ends (crashed process)
is simply absent, which the assembler treats as a partial trace, not
an error.

Span ids are globally unique without coordination: the top 31 bits are
crc32 of the ring's process label, the bottom 32 the local sequence.
Two processes can therefore parent each other's spans with nothing but
the integer that rode the wire.

The span-kind vocabulary (`SPAN_KINDS`) is CLOSED, like the flight
recorder's event vocabulary, and machine-checked by the same ripplelint
rule (analysis/trace_vocab.py): every `*.span("<kind>", ...)` emit site
must name a member, every member must have a live emit site, and every
member is documented in the README "Causal tracing" section.
"""

from __future__ import annotations

import itertools
import time
import zlib
from typing import Callable, Optional

_DEFAULT_SLOTS = 2048

# The CLOSED span-kind vocabulary — one name per distinct hop a sampled
# message can take. Checked by ripplelint trace_vocab (emit sites ↔
# vocabulary ↔ README "Causal tracing" section).
SPAN_KINDS = frozenset({
    # Client SDK roots (client/producer.py, client/consumer.py): the
    # whole sampled call, ack latency == duration. client.rpc is one
    # transport attempt inside the call (the requesting half of the
    # client↔broker skew pair — it parents the broker's rpc.recv, so
    # the pairing measures the wire round trip, not the retry loop's
    # bookkeeping; a retried call records one per attempt).
    "client.produce", "client.consume", "client.rpc",
    # Broker RPC surface: one span per inbound request that carried a
    # tctx (produce, consume, engine.append forward, ...). `op` field
    # names the request type. Pairs with its client/forwarder parent
    # for the cross-process skew estimate.
    "rpc.recv",
    # SLO admission decision on the produce front door.
    "admission",
    # Multi-core host plane: broker-side shm-ring round trip
    # (worker.hop) and the worker-subprocess side (worker.serve covers
    # the op; validate/stamp/pack are its children). hop/serve pair for
    # the worker-process skew estimate.
    "worker.hop", "worker.serve",
    "worker.validate", "worker.stamp", "worker.pack",
    # Engine round lifecycle, attributed to the sampled round: the PR 5
    # stage boundaries, now as spans (broker/dataplane.py emits all six
    # at settle release from the round ctx timestamps).
    "engine.dispatch", "settle.commit_wait", "settle.enter_wait",
    "settle.standby_ack", "settle.persist", "settle.release",
    # Replication fan-out: sender-side frame round trip and the
    # standby's apply+ack (full-copy and striped planes).
    "repl.send", "repl.apply", "stripe.send", "stripe.apply",
    # Follower reads: serve from replicated bytes, including a
    # stripe-reconstruct-on-read when the local copy is a stripe set.
    "follower.serve", "stripe.reconstruct",
    # Metadata plane: one coalesced control-plane wave, and an elastic
    # split/merge cutover.
    "meta.wave", "meta.cutover",
})


def derive_trace_id(name: str, counter: int) -> int:
    """Deterministic 63-bit trace id from a stable name (producer /
    consumer identity, or an op identity like "wave/broker0") and a
    per-name counter. splitmix-style finalizer so consecutive counters
    land uniformly across the sampling residues."""
    x = (zlib.crc32(name.encode()) << 32) ^ (counter & 0xFFFFFFFF)
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9 & 0x7FFFFFFFFFFFFFFF
    x = (x ^ (x >> 27)) * 0x94D049BB133111EB & 0x7FFFFFFFFFFFFFFF
    return (x ^ (x >> 31)) & 0x7FFFFFFFFFFFFFFF


def sampled(trace_id: int, sample_n: int) -> bool:
    """The deterministic sampling predicate: every `sample_n`-th trace
    id residue is sampled; 0 (or negative) disables sampling."""
    return sample_n > 0 and trace_id % sample_n == 0


class TraceContext:
    """The propagated half of a span: (trace id, parent span id).
    Wire form is the 2-list `[trace_id, span_id]` under the optional
    `tctx` request key — wire-primitive on both transports, absent
    entirely when unsampled."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: int, span_id: int) -> None:
        self.trace_id = int(trace_id)
        self.span_id = int(span_id)

    def wire(self) -> list[int]:
        return [self.trace_id, self.span_id]

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"TraceContext({self.trace_id:#x}, {self.span_id:#x})"


def ctx_from_wire(raw) -> Optional[TraceContext]:
    """Parse an inbound `tctx` field; None (not an error) on anything
    malformed — a bad context degrades to an unsampled request, never
    a refused one."""
    if (isinstance(raw, (list, tuple)) and len(raw) == 2
            and all(isinstance(v, int) for v in raw)):
        return TraceContext(raw[0], raw[1])
    return None


class Span:
    """One open span: `end()` computes the duration and stores the
    record in the ring; `ctx` is the context CHILDREN of this span
    propagate (trace id + THIS span's id). Usable as a context manager.
    Fields passed to `end` must stay wire-primitive (admin.spans serves
    records verbatim)."""

    __slots__ = ("_ring", "kind", "ctx", "parent", "t0", "_fields")

    def __init__(self, ring: "SpanRing", kind: str, ctx: TraceContext,
                 parent: int, t0: float, fields: Optional[dict]) -> None:
        self._ring = ring
        self.kind = kind
        self.ctx = ctx
        self.parent = parent
        self.t0 = t0
        self._fields = fields

    def end(self, **fields) -> None:
        if fields:
            merged = dict(self._fields or ())
            merged.update(fields)
        else:
            merged = self._fields
        self._ring._store(self.kind, self.ctx, self.parent, self.t0,
                          self._ring.clock() - self.t0, merged)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.end()


class _NullSpan:
    """The unsampled twin: a singleton with the Span surface and no
    behavior. `ctx` is None, so a hop that threads `span.ctx` onward
    propagates "unsampled" for free."""

    __slots__ = ()
    kind = ""
    ctx = None
    t0 = 0.0

    def end(self, **fields) -> None:
        pass

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


NULL_SPAN = _NullSpan()


class SpanRing:
    """Per-process span ring (one per broker, one per host worker, one
    per tracing client). Lock-cheap like the flight recorder: slot via
    atomic counter, single-reference stores, racy-consistent snapshot."""

    def __init__(self, proc: str, capacity: int = _DEFAULT_SLOTS,
                 clock: Optional[Callable[[], float]] = None) -> None:
        self.proc = str(proc)
        self._cap = max(16, int(capacity))
        self._buf: list = [None] * self._cap
        self._seq = itertools.count()
        self._ids = itertools.count(1)
        # 31 bits of proc hash (not 32: ids must stay inside the wire
        # codec's signed-64 range) over 32 bits of local sequence.
        self._id_base = (zlib.crc32(self.proc.encode()) & 0x7FFFFFFF) << 32
        self.clock: Callable[[], float] = (
            clock if clock is not None else time.perf_counter
        )

    # ------------------------------------------------------------ emit

    def span(self, kind: str, ctx: Optional[TraceContext],
             fields: Optional[dict] = None) -> Span:
        """Open a span under `ctx`. THE hot-path entry: `ctx is None`
        (unsampled request) returns the NULL_SPAN singleton without a
        clock read or any allocation."""
        if ctx is None:
            return NULL_SPAN
        child = TraceContext(ctx.trace_id, self._id_base | next(self._ids))
        return Span(self, kind, child, ctx.span_id, self.clock(), fields)

    def span_at(self, kind: str, ctx: Optional[TraceContext],
                t0: float, dur_s: float,
                fields: Optional[dict] = None) -> Optional[TraceContext]:
        """Record a span from timestamps measured elsewhere in THIS
        process's monotonic domain (the engine's round ctx stamps its
        stage boundaries itself). Returns the recorded span's context
        (for parenting follow-on stages), None when unsampled."""
        if ctx is None:
            return None
        child = TraceContext(ctx.trace_id, self._id_base | next(self._ids))
        self._store(kind, child, ctx.span_id, t0, dur_s, fields)
        return child

    def _store(self, kind: str, ctx: TraceContext, parent: int, t0: float,
               dur_s: float, fields: Optional[dict]) -> None:
        seq = next(self._seq)  # atomic slot assignment
        self._buf[seq % self._cap] = (
            seq, kind, ctx.trace_id, ctx.span_id, parent, t0,
            max(0, int(dur_s * 1e6)), self.proc, fields,
        )

    def ingest(self, records: list[dict]) -> None:
        """Adopt already-built span records from another process (the
        host workers ship theirs back inside the existing shm-ring
        response frames; the broker ring is the one admin.spans serves).
        Records keep their ORIGIN proc label and clock domain."""
        for r in records:
            try:
                seq = next(self._seq)
                self._buf[seq % self._cap] = (
                    seq, str(r["kind"]), int(r["trace"]), int(r["span"]),
                    int(r["parent"]), float(r["t0"]), int(r["dur_us"]),
                    str(r["proc"]),
                    {k: v for k, v in r.items()
                     if k not in ("seq", "kind", "trace", "span", "parent",
                                  "t0", "dur_us", "proc")} or None,
                )
            except (KeyError, TypeError, ValueError):
                continue  # a malformed record is dropped, never fatal

    # ------------------------------------------------------------ read

    def snapshot(self, after: int = -1,
                 max_spans: Optional[int] = None) -> list[dict]:
        """The ring's live window in seq order, clipped to seq > `after`
        and at most `max_spans` records — the paging contract behind
        admin.spans (cursor = last record's `seq`). Wire-encodable;
        parent ids live in each record's span context fields."""
        entries = [e for e in self._buf if e is not None and e[0] > after]
        entries.sort(key=lambda e: e[0])
        if max_spans is not None and max_spans >= 0:
            entries = entries[:max_spans]
        out = []
        for seq, kind, trace, span, parent, t0, dur_us, proc, fields \
                in entries:
            rec = dict(fields) if fields else {}
            rec.update(seq=seq, kind=kind, trace=trace, span=span,
                       parent=parent, t0=t0, dur_us=dur_us, proc=proc)
            out.append(rec)
        return out
