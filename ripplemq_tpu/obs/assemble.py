"""Trace assembler: span records → per-message critical-path trees.

Input is any bag of span records (the dicts `SpanRing.snapshot` /
`admin.spans` serve) from any number of processes. Output is one tree
per trace id with every span mapped into the ROOT span's monotonic
clock domain — at no point are raw timestamps from two processes
compared.

The skew model: each process records spans against its own
`time.monotonic()`, so a trace that crossed N processes arrives in N
unrelated clock domains. But every cross-process hop left a matched
pair behind — the requesting side's span (client.produce wrapping the
RPC, worker.hop wrapping the shm round trip, repl.send wrapping the
frame) PARENTS the serving side's span (rpc.recv, worker.serve,
repl.apply). Assuming the serve sits at the midpoint of the request
(the classic NTP symmetric-delay assumption), the midpoint difference
IS the offset between the two domains:

    offset[child_proc] = (mid_parent + offset[parent_proc]) - mid_child

BFS from the root's process over parent→child edges propagates offsets
to every reachable process; multiple edges into the same process are
averaged. Spans in processes no edge reaches (orphaned subtrees — a
ring overwrote the parent, a process died mid-span) stay un-normalized
and are reported in `orphans` rather than silently mis-placed.

Coverage is the fraction of the root span's window the attributed
segments actually explain: union length of all normalized child
intervals clipped to the root window, over the root duration. The
acceptance bar for the tracing plane is ≥ 0.9 on a proc-backend
produce — if a hop's time went missing, this number says so.
"""

from __future__ import annotations

from typing import Optional

_RESERVED = ("seq", "kind", "trace", "span", "parent", "t0", "dur_us",
             "proc")


def _mid(rec: dict) -> float:
    return rec["t0"] + rec["dur_us"] / 2e6


def _union_len(ivals: list[tuple[float, float]]) -> float:
    """Total length of a union of [a, b] intervals."""
    total = 0.0
    end: Optional[float] = None
    for a, b in sorted(ivals):
        if end is None or a > end:
            total += b - a
            end = b
        elif b > end:
            total += b - end
            end = b
    return total


def assemble(spans: list[dict]) -> list[dict]:
    """Join span records by trace id into trees (see module docstring).
    Tolerant by construction: duplicate records (the same ring paged
    twice) collapse on span id, missing parents demote a subtree to an
    orphan, a trace with no recognizable root is still returned (with
    `coverage` None). Returns one wire-encodable dict per trace,
    largest root duration first."""
    by_trace: dict[int, dict[int, dict]] = {}
    for rec in spans:
        try:
            by_trace.setdefault(int(rec["trace"]), {})[int(rec["span"])] \
                = rec
        except (KeyError, TypeError, ValueError):
            continue
    trees = [_assemble_one(t, idx) for t, idx in by_trace.items()]
    trees.sort(key=lambda tr: -(tr["ack_us"] or 0))
    return trees


def _assemble_one(trace_id: int, idx: dict[int, dict]) -> dict:
    children: dict[int, list[dict]] = {}
    roots: list[dict] = []
    for rec in idx.values():
        if rec.get("parent") in idx:
            children.setdefault(rec["parent"], []).append(rec)
        else:
            roots.append(rec)
    # The trace root: prefer the client span (parent id 0 by contract);
    # otherwise the longest parentless span anchors the clock domain.
    roots.sort(key=lambda r: (0 if str(r.get("kind", "")).startswith(
        "client.") else 1, -int(r.get("dur_us", 0))))
    root = roots[0] if roots else None

    # ---- per-process offsets into the root domain (midpoint pairing)
    offsets: dict[str, float] = {}
    if root is not None:
        offsets[root["proc"]] = 0.0
        acc: dict[str, list[float]] = {}
        frontier = [root]
        while frontier:
            nxt: list[dict] = []
            for parent in frontier:
                poff = offsets.get(parent["proc"])
                for ch in children.get(parent["span"], ()):
                    if poff is not None and ch["proc"] not in offsets:
                        if ch["proc"] == parent["proc"]:
                            offsets[ch["proc"]] = poff
                        else:
                            est = (_mid(parent) + poff) - _mid(ch)
                            acc.setdefault(ch["proc"], []).append(est)
                    nxt.append(ch)
            # Commit a BFS level's averaged estimates before descending:
            # deeper edges then chain off already-normalized parents.
            for proc, ests in acc.items():
                if proc not in offsets:
                    offsets[proc] = sum(ests) / len(ests)
            acc.clear()
            frontier = nxt

    # ---- normalize + coverage
    out_spans: list[dict] = []
    orphans = 0
    ivals: list[tuple[float, float]] = []
    for rec in idx.values():
        off = offsets.get(rec["proc"])
        norm = dict(rec)
        if off is None:
            orphans += 1
            norm["t0n"] = None
        else:
            norm["t0n"] = rec["t0"] + off
            if root is not None and rec is not root:
                a = norm["t0n"]
                ivals.append((a, a + rec["dur_us"] / 1e6))
        out_spans.append(norm)
    out_spans.sort(key=lambda r: (r["t0n"] is None, r["t0n"] or 0.0))

    coverage = None
    ack_us = None
    if root is not None:
        ack_us = int(root["dur_us"])
        if ack_us > 0:
            lo, hi = root["t0"], root["t0"] + ack_us / 1e6
            clipped = [(max(a, lo), min(b, hi))
                       for a, b in ivals if b > lo and a < hi]
            coverage = _union_len(clipped) / (ack_us / 1e6)

    # ---- critical path: from the root, follow the child whose
    # normalized END is latest (the hop still holding the ack open).
    path: list[dict] = []
    node = root
    while node is not None:
        path.append({"kind": node["kind"], "proc": node["proc"],
                     "dur_us": int(node["dur_us"])})
        kids = [c for c in children.get(node["span"], ())
                if offsets.get(c["proc"]) is not None]
        node = max(
            kids,
            key=lambda c: c["t0"] + offsets[c["proc"]] + c["dur_us"] / 1e6,
        ) if kids else None

    return {
        "trace": trace_id,
        "root_kind": None if root is None else root["kind"],
        "root_proc": None if root is None else root["proc"],
        "ack_us": ack_us,
        "coverage": coverage,
        "hops": sorted({r["kind"] for r in idx.values()}),
        "procs": sorted({r["proc"] for r in idx.values()}),
        "orphans": orphans,
        "critical_path": path,
        "spans": out_spans,
    }


def render(tree: dict, indent: str = "  ") -> str:
    """Human-readable one-trace decomposition (profiles/trace_view.py
    and chaos postmortem walkthroughs)."""
    cov = tree["coverage"]
    head = (f"trace {tree['trace']:#x} root={tree['root_kind']} "
            f"ack={_fmt_us(tree['ack_us'])} "
            f"coverage={'?' if cov is None else format(cov, '.0%')} "
            f"procs={','.join(tree['procs'])}")
    lines = [head]
    root_t0n = None
    for rec in tree["spans"]:
        if rec["kind"] == tree["root_kind"] and rec["t0n"] is not None:
            root_t0n = rec["t0n"]
            break
    for rec in tree["spans"]:
        if rec["t0n"] is None:
            at = "orphan"
        elif root_t0n is None:
            at = "?"
        else:
            at = f"+{(rec['t0n'] - root_t0n) * 1e3:.3f}ms"
        lines.append(f"{indent}{at:>12} {rec['kind']:<20} "
                     f"{_fmt_us(rec['dur_us']):>10}  [{rec['proc']}]")
    lines.append(f"{indent}critical: "
                 + " -> ".join(f"{p['kind']}({_fmt_us(p['dur_us'])})"
                               for p in tree["critical_path"]))
    return "\n".join(lines)


def _fmt_us(us) -> str:
    if us is None:
        return "?"
    return f"{us / 1000:.3f}ms" if us >= 1000 else f"{us}us"
