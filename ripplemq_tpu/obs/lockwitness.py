"""Runtime lock witness: record ACTUAL per-thread lock-acquisition
orderings, off the hot path unless asked for.

The static lock-order graph (`analysis/lock_graph.py`) derives "lock A
is held while lock B is acquired" edges from the AST — but the AST
cannot see through function-valued indirection (`replicate_wait_fn`,
duck-typed replicator planes) or runtime dispatch. This module closes
that static/dynamic gap the same way `stats_schema` closes
emit-site/doc drift: every host-path lock is created through the named
factories below, and when the witness is ENABLED each acquisition is
recorded against the acquiring thread's currently-held set. The chaos
smokes then assert two things about the witnessed graph:

- it is ACYCLIC (a witnessed cycle is a deadlock that simply has not
  scheduled yet), and
- it is CONTAINED in the static graph's transitive closure — a
  witnessed edge the AST missed means the static analysis lost
  coverage through an indirection, and the run FAILS so the edge gets
  derived or declared (lock_graph.DECLARED_EDGES) rather than silently
  unchecked.

Gating: `enabled()` is a process-global flag. The factories return RAW
`threading.Lock`/`RLock`/`Condition` objects while disabled — zero
wrapper, zero overhead, nothing to reason about in production. Enabling
(`enable()`, or `ClusterConfig.lock_witness: true` at broker boot)
affects locks created AFTER the call, so harnesses enable before
constructing the cluster (chaos `run_chaos(lock_witness=True)`,
`profiles/chaos_soak.py --witness`). Names passed to the factories are
the static graph's node ids (`ClassName.attr`); `analysis/lock_graph.py`
lints that every factory call site's name literal matches the attribute
it is assigned to, so the two planes cannot drift apart.
"""

from __future__ import annotations

import threading
from typing import Optional

_enabled = False
# (held_name, acquired_name) -> count of distinct observations. Guarded
# by _REG_LOCK on first insertion; reads ride the GIL (dict membership
# is atomic) so the recording fast path takes no lock once an edge is
# known.
_edges: dict[tuple[str, str], int] = {}
_names_seen: set[str] = set()
_REG_LOCK = threading.Lock()
_tls = threading.local()


def enable() -> None:
    """Turn the witness on for locks created from now on."""
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


def reset() -> None:
    """Drop every recorded edge (harnesses call between runs)."""
    with _REG_LOCK:
        _edges.clear()
        _names_seen.clear()


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _note_acquire(name: str) -> None:
    held = _held()
    if held:
        for h in held:
            if h == name:
                # Same NAME already held: either RLock depth (handled
                # by the wrapper) or a sibling instance of the same
                # class lock — instance-blind by design, so no
                # self-edge (a name-level self-edge would read every
                # cross-broker in-proc acquisition as a deadlock).
                continue
            key = (h, name)
            # The count is part of the verdict: exact, under the
            # registry lock (the witness polices unguarded RMWs — it
            # does not get to commit one; debug-mode cost, measured in
            # PROFILE.md).
            with _REG_LOCK:
                _edges[key] = _edges.get(key, 0) + 1
    if name not in _names_seen:
        with _REG_LOCK:
            _names_seen.add(name)
    held.append(name)


def _note_release(name: str) -> None:
    held = _held()
    # Locks release out of acquisition order legitimately (hand-over-
    # hand), so drop the LAST occurrence of this name, not the top.
    for i in range(len(held) - 1, -1, -1):
        if held[i] == name:
            del held[i]
            return


class WitnessLock:
    """threading.Lock wrapper recording acquisition-order edges. Also a
    valid Condition(lock): `_release_save`/`_acquire_restore`/`_is_owned`
    mirror CPython's plain-lock fallbacks so Condition.wait() correctly
    pops the held entry for the wait window (wait RELEASES the lock —
    orderings observed inside the window must not claim it was held)."""

    __slots__ = ("_inner", "name")

    def __init__(self, name: str, inner=None) -> None:
        self._inner = inner if inner is not None else threading.Lock()
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            _note_acquire(self.name)
        return got

    def release(self) -> None:
        _note_release(self.name)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    # -- Condition(lock) protocol (CPython fallback semantics) --

    def _release_save(self):
        _note_release(self.name)
        self._inner.release()

    def _acquire_restore(self, _saved) -> None:
        self._inner.acquire()
        _note_acquire(self.name)

    def _is_owned(self) -> bool:
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True


class WitnessRLock:
    """threading.RLock wrapper; reentrant depth tracked so nested
    acquisitions by the owner record one held entry, no self-edges.
    Implements the Condition(lock) protocol by delegating to the inner
    RLock's own `_release_save`/`_acquire_restore`/`_is_owned` (which
    fully release/restore the recursion count) so a witnessed
    Condition keeps raw `threading.Condition()` semantics — including
    REENTRANCY of the condition's mutex."""

    __slots__ = ("_inner", "name", "_owner", "_depth")

    def __init__(self, name: str) -> None:
        self._inner = threading.RLock()
        self.name = name
        self._owner: Optional[int] = None
        self._depth = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            me = threading.get_ident()
            if self._owner == me:
                self._depth += 1
            else:
                self._owner = me
                self._depth = 1
                _note_acquire(self.name)
        return got

    def release(self) -> None:
        if self._depth > 1:
            self._depth -= 1
        else:
            self._depth = 0
            self._owner = None
            _note_release(self.name)
        self._inner.release()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    # -- Condition(lock) protocol: wait() fully releases the recursion
    # count; the held entry pops for the whole wait window.

    def _release_save(self):
        state = self._inner._release_save()
        depth, self._depth = self._depth, 0
        self._owner = None
        _note_release(self.name)
        return (state, depth)

    def _acquire_restore(self, saved) -> None:
        state, depth = saved
        self._inner._acquire_restore(state)
        self._owner = threading.get_ident()
        self._depth = depth
        _note_acquire(self.name)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()


def make_lock(name: str):
    """A mutex named for the static lock graph (`ClassName.attr`).
    Disabled: a raw threading.Lock."""
    if not _enabled:
        return threading.Lock()
    return WitnessLock(name)


def make_rlock(name: str):
    if not _enabled:
        return threading.RLock()
    return WitnessRLock(name)


def make_condition(name: str, lock=None):
    """A condition variable whose underlying mutex is witnessed under
    `name`. Pass `lock` to share an existing (witnessed or raw) mutex —
    the Condition-aliases-its-lock idiom (`analysis/lock_graph.py`
    models the alias the same way). The standalone form wraps an RLOCK,
    because raw `threading.Condition()` defaults to one — the witness
    must never make a legal reentrant path deadlock only in debug
    mode."""
    if lock is not None:
        return threading.Condition(lock)
    if not _enabled:
        return threading.Condition()
    return threading.Condition(WitnessRLock(name))


# ------------------------------------------------------------- reporting


def edges() -> dict[tuple[str, str], int]:
    with _REG_LOCK:
        return dict(_edges)


def report(static_closure: Optional[set] = None) -> dict:
    """JSON-able witness verdict: the observed edges, acyclicity, and —
    when the static graph's transitive closure is supplied — the
    witnessed edges the AST never derived (each one is a coverage hole
    that must become a derived or declared static edge)."""
    from ripplemq_tpu.utils.graphs import cycles as _cycles

    obs = edges()
    found = _cycles(obs.keys())
    out = {
        "enabled": _enabled,
        "locks": sorted(_names_seen),
        "edges": sorted([a, b, n] for (a, b), n in obs.items()),
        "acyclic": not found,
        "cycles": found,
    }
    if static_closure is not None:
        out["uncovered_edges"] = sorted(
            [a, b] for (a, b) in obs if (a, b) not in static_closure
        )
    return out
