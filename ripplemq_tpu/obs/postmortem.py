"""One-shot postmortem bundles: everything the PR 4 term-skew diagnosis
needed, collected in one RPC instead of a hand-rolled probe session.

The PR 4 wedge was identified by noticing `ctrl_table_term=[5,5]` vs
`device_current_terms=[8,8]` with thousands of dispatches and zero
commits — each number pulled through a different ad-hoc reach-in. This
module packages that exact cross-section (control tables vs device
scalars, log ends, stall streaks, settled gaps, settle-window occupancy,
degraded/quarantine flags, retry budgets) plus the recent flight-
recorder window into a single wire-encodable dict, served by every
broker as `admin.postmortem` (frontends return the broker-level slice
with `engine: None`).

The engine section costs one device-lock hold spanning three
state-leaf fetches (terms, commits, log ends) — a deliberate price for
a ONE-SHOT diagnosis RPC, not a polling surface; `admin.stats` remains
the cheap periodic poll.
"""

from __future__ import annotations

import time


def collect_postmortem(broker, trace_last: int = 256) -> dict:
    """Build one broker's postmortem bundle. `broker` is a BrokerServer;
    the bundle is wire-encodable (served verbatim by admin.postmortem)."""
    node = broker.runner.node
    dp = broker._local_engine()
    bundle = {
        "ok": True,
        "broker": broker.broker_id,
        "address": broker.addr,
        "t": time.time(),
        "boot_failures": broker._boot_failures,
        "store_quarantined": broker._store_quarantined,
        "metadata": {
            "role": node.role,
            "term": node.term,
            "leader_hint": node.leader_hint,
        },
        "controller": {
            "id": broker.manager.current_controller(),
            "epoch": broker.manager.current_epoch(),
            "standbys": list(broker.manager.current_standbys()),
            "is_self": broker.is_controller,
        },
        "live": list(broker.manager.live),
        "duty_errors": list(broker.duty_errors),
        "engine": dp.postmortem() if dp is not None else None,
        "metrics": broker.metrics.snapshot(),
        "trace": broker.recorder.snapshot(last=trace_last),
        # The causal-tracing ring (obs/spans.py), empty when sampling is
        # off — a postmortem's sampled traces reassemble into critical-
        # path trees with obs/assemble.py (chaos verdicts attach them).
        "spans": (broker.spans.snapshot() if broker.spans is not None
                  else []),
    }
    if dp is not None and dp.recorder is not broker.recorder:
        # An externally-injected plane keeps its own recorder; its round
        # lifecycle is part of the story, so ship both windows.
        bundle["engine_trace"] = dp.recorder.snapshot(last=trace_last)
    if dp is not None and dp.metrics is not broker.metrics:
        bundle["engine_metrics"] = dp.metrics.snapshot()
    return bundle
