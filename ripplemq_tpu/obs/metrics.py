"""Lock-cheap metrics registry: counters, gauges, log-bucketed histograms.

Design constraints, in order:

1. The hot path must stay plain-int python — one attribute add for a
   counter increment, one `bit_length()` bucket lookup plus two adds for
   a histogram observation. No locks on the write path: CPython's `+=`
   on an int attribute can lose an increment under thread interleaving,
   and that is ACCEPTED — these are monitoring counters read as rates
   and distributions, not accounting ledgers (the accounting counters —
   committed_entries, acks — live in their subsystems under their own
   locks). Snapshots are likewise racy-consistent: each value is read
   atomically, the set is not a point-in-time cut.
2. Histograms are FIXED log2 bins over integer microseconds (bucket i
   holds observations with `us.bit_length() == i`, i.e. [2^(i-1), 2^i)),
   so an observation is O(1) with no allocation and the full
   distribution is 40 small ints. Quantiles are read off the bucket
   upper bounds — good to a factor of 2, which is what stage-level
   latency attribution needs (is the settle stall in fsync or in the
   standby RPC?), not benchmarking precision.
3. The clock is injectable (`Metrics(clock=...)`) so timing-dependent
   tests run on a fake clock with zero real sleeps, and the overhead
   smoke can measure pure bookkeeping cost without perf_counter noise.
4. `Metrics(enabled=False)` hands out no-op metric objects with the
   same API, so instrumented code needs no `if obs:` branches and the
   A/B knob (`ClusterConfig.obs`) costs one no-op method call per site.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

# 40 log2 bins over integer microseconds: bin 39 tops out past 2^39 us
# (~6.4 days) — everything above clips into the last bin.
_NBINS = 40


class Counter:
    """Monotonic count. `inc()` is one plain-int add (see module doc for
    the accepted-race contract)."""

    __slots__ = ("n",)

    def __init__(self) -> None:
        self.n = 0

    def inc(self, k: int = 1) -> None:
        self.n += k


class Gauge:
    """Last-write-wins scalar."""

    __slots__ = ("v",)

    def __init__(self) -> None:
        self.v = 0

    def set(self, v) -> None:
        self.v = v


class Histogram:
    """Log2-bucketed distribution over integer microseconds (or any
    non-negative int — `observe_int` takes the value verbatim, e.g.
    group-commit sizes). `observe(seconds)` converts once."""

    __slots__ = ("bins", "count", "total", "max")

    def __init__(self) -> None:
        self.bins = [0] * _NBINS
        self.count = 0
        self.total = 0
        self.max = 0

    def observe(self, seconds: float) -> None:
        self.observe_int(int(seconds * 1e6))

    def observe_int(self, v: int) -> None:
        if v < 0:
            v = 0
        i = v.bit_length()
        self.bins[i if i < _NBINS else _NBINS - 1] += 1
        self.count += 1
        self.total += v
        if v > self.max:
            self.max = v

    def quantile(self, q: float) -> int:
        """Upper bound (2^i) of the bucket holding the q-quantile —
        factor-of-2 resolution by construction."""
        count = self.count
        if count == 0:
            return 0
        target = q * count
        seen = 0
        for i, b in enumerate(self.bins):
            seen += b
            if seen >= target:
                return 1 << i
        return self.max

    def summary(self) -> dict:
        count = self.count
        return {
            "count": count,
            "mean": round(self.total / count, 1) if count else 0,
            "p50": self.quantile(0.50),
            "p90": self.quantile(0.90),
            "p99": self.quantile(0.99),
            "max": self.max,
        }


class _NullCounter:
    __slots__ = ()

    def inc(self, k: int = 1) -> None:
        pass


class _NullGauge:
    __slots__ = ()

    def set(self, v) -> None:
        pass


class _NullHistogram:
    __slots__ = ()

    def observe(self, seconds: float) -> None:
        pass

    def observe_int(self, v: int) -> None:
        pass


_NULL_COUNTER = _NullCounter()
_NULL_GAUGE = _NullGauge()
_NULL_HISTOGRAM = _NullHistogram()


class Metrics:
    """Named-metric registry. Metric OBJECTS are memoized and returned
    by reference — instrumented code resolves its metrics once (at
    construction) and the hot path touches only the object. Creation
    takes a lock (cold path); snapshot takes the same lock only to copy
    the name tables, never blocking writers (writers don't lock)."""

    def __init__(self, enabled: bool = True,
                 clock: Optional[Callable[[], float]] = None) -> None:
        self.enabled = enabled
        # The stage-timing clock. perf_counter, not time.time: stage
        # deltas must not jump with wall-clock adjustments. Tests inject
        # a fake to run timing assertions with zero real sleeps. A
        # DISABLED registry's clock is a constant: every observation it
        # could feed is a no-op anyway, and the obs=False A/B arm must
        # shed the clock syscalls too, not just the bookkeeping.
        if clock is not None:
            self.clock: Callable[[], float] = clock
        elif enabled:
            self.clock = time.perf_counter
        else:
            self.clock = lambda: 0.0
        from ripplemq_tpu.obs.lockwitness import make_lock

        self._lock = make_lock("Metrics._lock")
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        if not self.enabled:
            return _NULL_COUNTER  # type: ignore[return-value]
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter()
            return c

    def gauge(self, name: str) -> Gauge:
        if not self.enabled:
            return _NULL_GAUGE  # type: ignore[return-value]
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge()
            return g

    def histogram(self, name: str) -> Histogram:
        if not self.enabled:
            return _NULL_HISTOGRAM  # type: ignore[return-value]
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram()
            return h

    def snapshot(self) -> dict:
        """Wire-encodable summary: counters/gauges verbatim, histograms
        as {count, mean, p50, p90, p99, max} (all integer microseconds
        for the `*_us` stage timers)."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        return {
            "enabled": self.enabled,
            "counters": {k: c.n for k, c in sorted(counters.items())},
            "gauges": {k: g.v for k, g in sorted(gauges.items())},
            "histograms": {
                k: h.summary() for k, h in sorted(histograms.items())
            },
        }


def _prom_name(name: str) -> str:
    """Registry name → Prometheus metric name: the `ripplemq_` prefix
    plus the name with every non-[a-zA-Z0-9_] collapsed to `_` (the
    registry's dotted names are not legal exposition identifiers)."""
    return "ripplemq_" + "".join(
        ch if ch.isalnum() or ch == "_" else "_" for ch in name
    )


def render_prometheus(metrics: Metrics) -> str:
    """Prometheus text exposition of a live registry — the
    admin.metrics_text surface (broker/server.py). GENERIC over the
    registry by construction: every counter renders as `<name>_total`,
    every gauge bare, every histogram as its cumulative log2 buckets
    (`le` = each bin's inclusive upper bound 2^i - 1) plus `_sum` and
    `_count` — so a metric added anywhere in the codebase shows up here
    with no schema to update, and the exposition can never drift from
    the registry (locked by tests/test_observability.py's exposition
    test the way stats_schema locks admin.stats)."""
    with metrics._lock:
        counters = sorted(metrics._counters.items())
        gauges = sorted(metrics._gauges.items())
        histograms = sorted(metrics._histograms.items())
    lines: list[str] = []
    for name, c in counters:
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn}_total counter")
        lines.append(f"{pn}_total {c.n}")
    for name, g in gauges:
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {g.v}")
    for name, h in histograms:
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} histogram")
        cum = 0
        for i, b in enumerate(h.bins):
            if b == 0:
                continue  # sparse: 40 bins/metric would dominate bytes
            cum += b
            lines.append(
                f'{pn}_bucket{{le="{(1 << i) - 1}"}} {cum}'
            )
        lines.append(f'{pn}_bucket{{le="+Inf"}} {h.count}')
        lines.append(f"{pn}_sum {h.total}")
        lines.append(f"{pn}_count {h.count}")
    return "\n".join(lines) + ("\n" if lines else "")
