"""Telemetry plane: metrics registry, flight recorder, postmortem bundles.

Three surfaces, one package (the observability layer MegaScale argues
must be built into the system rather than bolted on per-incident,
arXiv:2402.15627):

- `obs.metrics` — a lock-cheap registry of counters, gauges, and
  log-bucketed histograms instrumenting every host-path stage (produce,
  dispatch, the settle pipeline, replication group-commit, store
  append/fsync, wire codec). On by default; `ClusterConfig.obs = False`
  swaps in no-op metrics for A/B.
- `obs.trace` — a fixed-size ring flight recorder of per-round
  lifecycle events and control-plane transitions, always on.
- `obs.postmortem` — the one-shot diagnosis bundle (control-table vs
  device terms, log ends, stall streaks, settled gaps, the recent trace
  ring) served as `admin.postmortem` by every broker.
- `obs.lockwitness` — the runtime lock witness (PR 11): named lock
  factories that are raw `threading` primitives by default and, when
  enabled (`ClusterConfig.lock_witness`, chaos `--witness`), record
  per-thread acquisition orderings for the cross-check against the
  static lock-order graph (`analysis/lock_graph.py`). Not imported
  here: the factories must stay import-light so every lock-owning
  module can use them without cycles.
"""

from ripplemq_tpu.obs.metrics import Metrics
from ripplemq_tpu.obs.postmortem import collect_postmortem
from ripplemq_tpu.obs.trace import FlightRecorder

__all__ = ["Metrics", "FlightRecorder", "collect_postmortem"]
