"""Logging: named loggers under one "ripplemq" root + console config.

The reference ships a configured log4j2 console stack (reference:
mq-broker/src/main/resources/log4j2.xml:10-14 — pattern
"%d{HH:mm:ss.SSS} [%t] %-5level %logger{36} - %msg%n"); this is the
equivalent: every subsystem logs through `get_logger(<subsystem>)`
("ripplemq.broker", "ripplemq.dataplane", "ripplemq.hostraft",
"ripplemq.replication", "ripplemq.storage"), and the process entry point
calls `configure_logging()` once. Library code NEVER configures handlers
itself (embedders own the root config), so imports stay side-effect
free; unconfigured loggers follow stdlib defaults (warnings+ to stderr).
"""

from __future__ import annotations

import logging
import sys
from typing import Optional, TextIO

_ROOT = "ripplemq"

# Mirrors the reference's log4j2 console pattern (thread, level, logger).
_PATTERN = "%(asctime)s.%(msecs)03d [%(threadName)s] %(levelname)-5s %(name)s - %(message)s"
_DATEFMT = "%H:%M:%S"


def get_logger(subsystem: str) -> logging.Logger:
    """Logger for one subsystem, namespaced under the ripplemq root."""
    return logging.getLogger(f"{_ROOT}.{subsystem}")


def configure_logging(level: str | int = "INFO",
                      stream: Optional[TextIO] = None) -> logging.Logger:
    """Attach one console handler to the ripplemq root logger (idempotent:
    reconfiguring replaces the previous handler, so tests and re-entrant
    mains don't stack duplicates). Returns the root logger."""
    root = logging.getLogger(_ROOT)
    if isinstance(level, str):
        level = getattr(logging, level.upper(), logging.INFO)
    root.setLevel(level)
    handler = logging.StreamHandler(stream if stream is not None else sys.stderr)
    handler.setFormatter(logging.Formatter(_PATTERN, datefmt=_DATEFMT))
    for h in list(root.handlers):
        root.removeHandler(h)
    root.addHandler(handler)
    root.propagate = False
    return root
