"""Logging: named loggers under one "ripplemq" root + console config.

The reference ships a configured log4j2 console stack (reference:
mq-broker/src/main/resources/log4j2.xml:10-14 — pattern
"%d{HH:mm:ss.SSS} [%t] %-5level %logger{36} - %msg%n"); this is the
equivalent: every subsystem logs through `get_logger(<subsystem>)`
("ripplemq.broker", "ripplemq.dataplane", "ripplemq.hostraft",
"ripplemq.replication", "ripplemq.storage"), and the process entry point
calls `configure_logging()` once. Library code NEVER configures handlers
itself (embedders own the root config), so imports stay side-effect
free; unconfigured loggers follow stdlib defaults (warnings+ to stderr).
"""

from __future__ import annotations

import json
import logging
import sys
from typing import Optional, TextIO

_ROOT = "ripplemq"

# Mirrors the reference's log4j2 console pattern (thread, level, logger).
_PATTERN = "%(asctime)s.%(msecs)03d [%(threadName)s] %(levelname)-5s %(name)s - %(message)s"
_DATEFMT = "%H:%M:%S"


def get_logger(subsystem: str) -> logging.Logger:
    """Logger for one subsystem, namespaced under the ripplemq root."""
    return logging.getLogger(f"{_ROOT}.{subsystem}")


class _JsonLinesFormatter(logging.Formatter):
    """One JSON object per log record: machine-greppable broker logs
    that merge cleanly with the telemetry plane's event timeline (the
    proc chaos backend launches its subprocess brokers with this, so a
    soak's broker-N.log sits `jq`-able next to the trace ring). Fields:
    ts (epoch seconds), level, subsystem (the logger name under the
    ripplemq root), broker (the launching process's id, if known),
    thread, msg; exceptions land in `exc`."""

    def __init__(self, broker_id: Optional[int] = None) -> None:
        super().__init__()
        self._broker_id = broker_id

    def format(self, record: logging.LogRecord) -> str:
        name = record.name
        doc = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "subsystem": name[len(_ROOT) + 1:] if
            name.startswith(_ROOT + ".") else name,
            "broker": self._broker_id,
            "thread": record.threadName,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            doc["exc"] = self.formatException(record.exc_info)
        return json.dumps(doc, ensure_ascii=False)


def configure_logging(level: str | int = "INFO",
                      stream: Optional[TextIO] = None,
                      json_lines: bool = False,
                      broker_id: Optional[int] = None) -> logging.Logger:
    """Attach one console handler to the ripplemq root logger (idempotent:
    reconfiguring replaces the previous handler, so tests and re-entrant
    mains don't stack duplicates). `json_lines=True` swaps the log4j2-
    style pattern for one JSON object per record (`_JsonLinesFormatter`),
    with `broker_id` stamped into every line. Returns the root logger."""
    root = logging.getLogger(_ROOT)
    if isinstance(level, str):
        level = getattr(logging, level.upper(), logging.INFO)
    root.setLevel(level)
    handler = logging.StreamHandler(stream if stream is not None else sys.stderr)
    if json_lines:
        handler.setFormatter(_JsonLinesFormatter(broker_id=broker_id))
    else:
        handler.setFormatter(logging.Formatter(_PATTERN, datefmt=_DATEFMT))
    for h in list(root.handlers):
        root.removeHandler(h)
    root.addHandler(handler)
    root.propagate = False
    return root
