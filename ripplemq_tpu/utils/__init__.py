"""Cross-cutting utilities (logging)."""

from ripplemq_tpu.utils.logs import configure_logging, get_logger

__all__ = ["configure_logging", "get_logger"]
