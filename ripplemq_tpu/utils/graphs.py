"""Tiny dependency-free graph helpers shared by the static lock-order
checker (`analysis/lock_graph.py`) and the runtime lock witness
(`obs/lockwitness.py`) — one Tarjan, two callers, no drift."""

from __future__ import annotations

from typing import Iterable


def strongly_connected(
        edges: Iterable[tuple[str, str]]) -> list[list[str]]:
    """All strongly connected components (every node appears in exactly
    one, sorted within and across components) — iterative Tarjan, so a
    long chain cannot hit the recursion limit. Callers apply their own
    cycle policy (|SCC| > 1, self-edges, reentrancy exemptions)."""
    adj: dict[str, list[str]] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
        adj.setdefault(b, [])
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on: set[str] = set()
    stack: list[str] = []
    out: list[list[str]] = []
    counter = [0]

    for root in sorted(adj):
        if root in index:
            continue
        # Iterative DFS: (node, iterator position) frames.
        work = [(root, 0)]
        while work:
            v, i = work.pop()
            if i == 0:
                index[v] = low[v] = counter[0]
                counter[0] += 1
                stack.append(v)
                on.add(v)
            recurse = False
            children = adj[v]
            while i < len(children):
                w = children[i]
                i += 1
                if w not in index:
                    work.append((v, i))
                    work.append((w, 0))
                    recurse = True
                    break
                if w in on:
                    low[v] = min(low[v], index[w])
            if recurse:
                continue
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                out.append(sorted(comp))
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[v])
    return sorted(out)


def cycles(edges: Iterable[tuple[str, str]],
           self_edge_counts: bool = True) -> list[list[str]]:
    """The deadlock-relevant components: SCCs with more than one node,
    plus single nodes with a self-edge when `self_edge_counts`."""
    edge_set = set(edges)
    selfed = {a for a, b in edge_set if a == b}
    out = []
    for comp in strongly_connected(edge_set):
        if len(comp) > 1:
            out.append(comp)
        elif self_edge_counts and comp[0] in selfed:
            out.append(comp)
    return out
