"""Append-only CRC-framed segment store (ctypes ↔ native/segstore.cpp).

One record per committed replication round (or offset-commit batch or
metadata blob). The native C++ library owns the hot write path; a pure
-Python implementation writes the byte-identical format (shared CRC-32 /
framing), so files are interchangeable and CPU-only environments need no
toolchain. See native/segstore.cpp for the frame layout and the torn-tail
crash contract.

The library is compiled on demand from the checked-in source (no network,
just g++) and cached next to it.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading
import zlib
from typing import Iterator, Optional

from ripplemq_tpu.obs.lockwitness import make_lock
from ripplemq_tpu.utils.logs import get_logger

_log = get_logger("storage")

REC_APPEND = 1
REC_OFFSETS = 2
REC_META = 3
# Idempotent-producer dedup entries: one record per committed round and
# slot, written immediately AFTER that slot's REC_APPEND (a torn tail
# may drop the pid record but never leave it without its rows — the
# reverse order would let a dedup-ack point at rows that were never
# persisted). Payload: packed (pid u32, seq i64, rows u32, base i64)
# per producer batch; `base` in the header carries the entry count.
REC_PIDSEQ = 4
# Striped replication (ripplemq_tpu/stripes/): a standby in
# replication="striped" mode persists Reed–Solomon stripe FRAMES of the
# committed-round stream instead of full rows. Header fields: slot =
# stripe index, base = gsn & 0x7FFFFFFF (display/filtering only — the
# self-describing frame header inside the payload is the authority);
# payload = one stripes/codec.py frame (its own header-covered CRC on
# top of this store frame's). Promotion/boot replay reconstructs the
# record stream from any k of the k+m stripes (stripes/recovery.py).
REC_STRIPE = 5

_MAGIC = 0x474C5152
_HEADER = struct.Struct("<IBIIII")  # magic, type, slot, base, len, crc
_HEADER_PREFIX = struct.Struct("<IBIII")  # the 17 bytes the crc covers
_CRC = struct.Struct("<I")


def _frame_crc(header17: bytes, payload: bytes) -> int:
    """CRC-32 of a record frame: the 17 header bytes BEFORE the crc
    field, chained with the payload. Header corruption (a flipped bit
    in type/slot/base/len) must fail verification exactly like payload
    rot — a payload-only crc let a bit-flipped `base` pass the boot
    health walk and replay acked rows at the wrong offsets (the chaos
    disk_flip matrix; sealed+erasure-encoded segments were covered by
    the shard-level whole-file crc, but the active and not-yet-encoded
    segments were not).

    FORMAT BREAK (PR 4): frames written by the pre-PR-4 payload-only
    crc fail this check — deliberately unversioned, because a legacy
    fallback would accept exactly the header damage this closes (a
    flipped header byte passes the payload-only check by construction).
    No store artifacts cross versions in this repo (data dirs are
    ephemeral test/drill state); a deployment upgrading live stores
    would need a one-shot rewrite migration first."""
    return zlib.crc32(payload, zlib.crc32(header17)) & 0xFFFFFFFF

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_LIB_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_LIB_TRIED = False


def _load_native() -> Optional[ctypes.CDLL]:
    global _LIB, _LIB_TRIED
    with _LIB_LOCK:
        if _LIB_TRIED:
            return _LIB
        _LIB_TRIED = True
        src_dir = os.path.abspath(_NATIVE_DIR)
        so_path = os.path.join(src_dir, "libsegstore.so")
        src_path = os.path.join(src_dir, "segstore.cpp")
        def compile_and_load(force: bool) -> ctypes.CDLL:
            if force or not os.path.exists(so_path) or (
                os.path.getmtime(so_path) < os.path.getmtime(src_path)
            ):
                subprocess.run(
                    ["g++", "-O2", "-fPIC", "-std=c++17", "-shared",
                     "-o", so_path, src_path],
                    check=True, capture_output=True, timeout=120,
                )
            return ctypes.CDLL(so_path)

        try:
            if not os.path.exists(src_path):
                return None
            lib = compile_and_load(force=False)
            try:
                _bind(lib)
            except AttributeError:
                # A cached .so from older source can carry a fresher
                # mtime (copied artifacts, clock skew) yet lack newer
                # symbols: rebuild once from the checked-in source.
                lib = compile_and_load(force=True)
                _bind(lib)
        except (OSError, subprocess.SubprocessError, AttributeError):
            return None
        _LIB = lib
        return _LIB


def _bind(lib) -> None:
    """Declare every exported symbol's signature — inside the loader's
    try so a stale library missing a symbol degrades to the Python path
    instead of crashing boot."""
    lib.segstore_open.restype = ctypes.c_void_p
    lib.segstore_open.argtypes = [ctypes.c_char_p, ctypes.c_long]
    lib.segstore_append.restype = ctypes.c_int
    lib.segstore_append.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_int,
    ]
    lib.segstore_append_at.restype = ctypes.c_int
    lib.segstore_append_at.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_long),
    ]
    lib.segstore_append_blob.restype = ctypes.c_int
    lib.segstore_append_blob.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_long),
    ]
    lib.segstore_flush.restype = ctypes.c_int
    lib.segstore_flush.argtypes = [ctypes.c_void_p]
    lib.segstore_close.restype = None
    lib.segstore_close.argtypes = [ctypes.c_void_p]
    lib.segscan_open.restype = ctypes.c_void_p
    lib.segscan_open.argtypes = [ctypes.c_char_p]
    lib.segscan_next.restype = ctypes.c_int
    lib.segscan_next.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int),
        ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int),
    ]
    lib.segscan_next_at.restype = ctypes.c_int
    lib.segscan_next_at.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int),
        ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_long),
    ]
    lib.segscan_close.restype = None
    lib.segscan_close.argtypes = [ctypes.c_void_p]


def native_available() -> bool:
    return _load_native() is not None


class CorruptStoreError(Exception):
    """CRC/framing failure in the middle of the store (not a torn tail)."""


def list_segment_files(directory: str) -> list[str]:
    """Sorted segment file names in a store directory (with
    segment_index/segment_name below, the one place the naming scheme is
    interpreted on the Python side; the native scanner mirrors it in
    segstore.cpp list_segments)."""
    if not os.path.isdir(directory):
        return []
    return sorted(
        f for f in os.listdir(directory)
        if f.startswith("segment-") and f.endswith(".log")
    )


def segment_index(name: str) -> int:
    """segment-XXXXXXXX.log (or a derived shard name's stem) → index."""
    return int(name[8:16])


def segment_name(index: int) -> str:
    return f"segment-{index:08d}.log"


class SegmentStore:
    """Writer. `use_native=None` auto-selects the C++ library.

    `erasure=True` additionally RS(3,2)-encodes sealed segments from a
    background thread kicked by flush(): any 3 of the 5 shards rebuild a
    lost/corrupt sealed segment on recovery (see storage/erasure.py;
    repair runs in recover_image before replay). The encode runs OFF the
    flush path — flush is the replication step thread's durability
    barrier and must not stall for a whole segment's GF matmul — and an
    unencoded sealed segment is simply picked up by a later kick."""

    def __init__(self, directory: str, segment_bytes: int = 64 << 20,
                 use_native: Optional[bool] = None,
                 erasure: bool = False,
                 retention_bytes: Optional[int] = None,
                 metrics=None) -> None:
        self.directory = directory
        # Telemetry (obs.Metrics registry, usually the owning broker's):
        # append latency/bytes and fsync latency are the disk half of the
        # settle-path decomposition. None or a DISABLED registry → the
        # handles stay None and the hot paths skip even the clock reads
        # (the obs=False A/B arm must actually shed the cost).
        self.metrics = metrics
        if metrics is not None and getattr(metrics, "enabled", True):
            self._h_append = metrics.histogram("store.append_us")
            self._h_fsync = metrics.histogram("store.fsync_us")
            self._c_append_bytes = metrics.counter("store.append_bytes")
            self._c_records = metrics.counter("store.append_records")
            self._clock = metrics.clock
        else:
            self._h_append = self._h_fsync = None
            self._c_append_bytes = self._c_records = None
            self._clock = None
        self.segment_bytes = segment_bytes
        self.erasure = erasure
        # Size-capped disk retention: gc() deletes the OLDEST sealed
        # segments (and their local shards) while the sealed total
        # exceeds this. None = unlimited (the default; the reference
        # grows without bound too — in JVM heap).
        self.retention_bytes = retention_bytes
        self._erasure_thread: Optional[threading.Thread] = None
        self._erasure_check_t = 0.0
        self.erasure_errors: list[str] = []
        # Deferred-fsync machinery (flush_async): one flusher thread per
        # store, started on first use.
        self._flusher: Optional[threading.Thread] = None
        self._flush_event = threading.Event()
        self._flush_stop = threading.Event()
        self.flush_errors: list[str] = []
        # Active segment index shadow for the flusher (avoids a listdir
        # per sync tick); updated by append() on both writer paths.
        self._active_seg = -1
        self._last_synced_seg = -1
        os.makedirs(directory, exist_ok=True)
        lib = _load_native() if use_native in (None, True) else None
        if use_native is True and lib is None:
            raise RuntimeError("native segstore requested but unavailable")
        self._lib = lib
        self._lock = make_lock("SegmentStore._lock")
        if lib is not None:
            self._handle = lib.segstore_open(
                directory.encode(), ctypes.c_long(segment_bytes)
            )
            if not self._handle:
                raise OSError(f"segstore_open failed for {directory}")
            self._file = None
        else:
            self._handle = None
            self._seg_index = self._next_index()
            self._file = open(self._seg_path(self._seg_index), "ab")

    # -- python fallback helpers --
    def _seg_path(self, index: int) -> str:
        return os.path.join(self.directory, f"segment-{index:08d}.log")

    def _next_index(self) -> int:
        existing = list_segment_files(self.directory)
        if not existing:
            return 0
        return int(existing[-1][8:16]) + 1

    # -- API --
    @property
    def is_native(self) -> bool:
        return self._handle is not None

    def append(self, rec_type: int, slot: int, base: int,
               payload: bytes) -> tuple[int, int]:
        """Append one framed record; returns its locator
        (segment_index, payload_byte_offset) — the position the retention
        read path (storage.logindex) serves lagging consumers from."""
        if len(payload) > (1 << 30):
            # The scanners reject length fields above 1 GiB as corruption;
            # writing one would be an acked-but-unreadable record.
            raise ValueError(
                f"record payload of {len(payload)} bytes exceeds the "
                f"1 GiB store record cap"
            )
        t0 = self._clock() if self._h_append is not None else 0.0
        try:
            return self._append_locked(rec_type, slot, base, payload)
        finally:
            if self._h_append is not None:
                self._h_append.observe(self._clock() - t0)
                self._c_append_bytes.inc(len(payload))
                self._c_records.inc()

    def _append_locked(self, rec_type: int, slot: int, base: int,
                       payload: bytes) -> tuple[int, int]:
        with self._lock:
            if self._handle is not None:
                seg = ctypes.c_int()
                off = ctypes.c_long()
                rc = self._lib.segstore_append_at(
                    self._handle, rec_type, slot, base, payload, len(payload),
                    ctypes.byref(seg), ctypes.byref(off),
                )
                if rc != 0:
                    raise OSError("segstore_append failed")
                self._active_seg = seg.value
                return seg.value, off.value
            hdr = _HEADER_PREFIX.pack(
                _MAGIC, rec_type, slot, base, len(payload)
            )
            frame = hdr + _CRC.pack(_frame_crc(hdr, payload)) + payload
            if (
                self._file.tell() + len(frame) > self.segment_bytes
                and self._file.tell() > 0
            ):
                self._file.close()
                self._seg_index += 1
                self._file = open(self._seg_path(self._seg_index), "ab")
            locator = (self._seg_index, self._file.tell() + _HEADER.size)
            self._file.write(frame)
            self._file.flush()
            self._active_seg = self._seg_index
            return locator

    def append_many(
        self, records: list[tuple[int, int, int, bytes]]
    ) -> list[tuple[int, int]]:
        """Append a batch of records as ONE framed blob + ONE store
        write; returns each record's locator in order. Per-record
        append() calls pay a ctypes marshal + GIL round-trip each —
        under load that per-call overhead, not bandwidth, was the
        persist stage's capacity (PROFILE.md "host path"). The blob is
        framed identically to append(), so scan/recovery see the same
        stream. Batches are bounded by the callers (a settle window's
        records, a repl.rounds frame) — far under segment_bytes, so a
        blob never straddles segments."""
        if not records:
            return []
        frames: list[bytes] = []
        rel: list[int] = []  # payload offset of each record in the blob
        pos = 0
        payload_total = 0  # append_bytes counts PAYLOAD bytes (both paths)
        for rec_type, slot, base, payload in records:
            if len(payload) > (1 << 30):
                raise ValueError(
                    f"record payload of {len(payload)} bytes exceeds the "
                    f"1 GiB store record cap"
                )
            hdr = _HEADER_PREFIX.pack(
                _MAGIC, rec_type, slot, base, len(payload)
            )
            frames.append(hdr + _CRC.pack(_frame_crc(hdr, payload)))
            frames.append(payload)
            rel.append(pos + _HEADER.size)
            pos += _HEADER.size + len(payload)
            payload_total += len(payload)
        blob = b"".join(frames)
        t0 = self._clock() if self._h_append is not None else 0.0
        try:
            return self._append_blob_locked(blob, rel)
        finally:
            if self._h_append is not None:
                self._h_append.observe(self._clock() - t0)
                self._c_append_bytes.inc(payload_total)
                self._c_records.inc(len(records))

    def _append_blob_locked(self, blob: bytes,
                            rel: list[int]) -> list[tuple[int, int]]:
        with self._lock:
            if self._handle is not None:
                seg = ctypes.c_int()
                off = ctypes.c_long()
                rc = self._lib.segstore_append_blob(
                    self._handle, blob, len(blob),
                    ctypes.byref(seg), ctypes.byref(off),
                )
                if rc != 0:
                    raise OSError("segstore_append_blob failed")
                self._active_seg = seg.value
                return [(seg.value, off.value + r) for r in rel]
            if (
                self._file.tell() + len(blob) > self.segment_bytes
                and self._file.tell() > 0
            ):
                self._file.close()
                self._seg_index += 1
                self._file = open(self._seg_path(self._seg_index), "ab")
            start = self._file.tell()
            self._file.write(blob)
            self._file.flush()
            self._active_seg = self._seg_index
            return [(self._seg_index, start + r) for r in rel]

    def flush(self) -> None:
        """fsync the active segment (the durability barrier)."""
        t0 = self._clock() if self._h_fsync is not None else 0.0
        with self._lock:
            if self._handle is not None:
                if self._lib.segstore_flush(self._handle) != 0:
                    raise OSError("segstore_flush failed")
            elif self._file is not None:
                self._file.flush()
                os.fsync(self._file.fileno())
            else:
                return  # closed: close()'s final fsync was the barrier
        if self._h_fsync is not None:
            self._h_fsync.observe(self._clock() - t0)
        if self.erasure:
            self._kick_erasure()

    def flush_async(self) -> None:
        """Schedule an fsync on the store's flusher thread and return
        immediately. Same durability contract as the callers' periodic
        flush() cadence — disk lags the buffered append stream by at
        most one flush interval (plus one in-flight fsync) — but the
        HOT PATH no longer waits out the device's fsync latency, which
        on a networked filesystem is tens to hundreds of ms per call
        (measured p50 47 ms / p99 163 ms on a 9p mount: inline, that
        single syscall WAS the settle pipeline's and the standby ack
        path's capacity). Barrier call sites — boot replay, promotion,
        stop — keep calling flush() directly."""
        if self._flush_stop.is_set():
            return
        if self._flusher is None:
            with self._lock:
                if self._flusher is None and not self._flush_stop.is_set():
                    self._flusher = threading.Thread(
                        target=self._flush_loop, daemon=True,
                        name="segstore-flush",
                    )
                    self._flusher.start()
        self._flush_event.set()

    def _flush_loop(self) -> None:
        while not self._flush_stop.is_set():
            if not self._flush_event.wait(timeout=0.2):
                continue
            self._flush_event.clear()
            try:
                self._sync_active_segment()
                if self.erasure:
                    self._kick_erasure()
            except Exception as e:  # surfaced via stats, not a dead thread
                self.flush_errors.append(f"{type(e).__name__}: {e}")
                del self.flush_errors[:-20]

    def _sync_active_segment(self) -> None:
        """fsync the active segment through an INDEPENDENT fd: fsync
        syncs the inode, not the fd, so the flusher never holds the
        store lock across the device sync — appends keep flowing while
        the filesystem catches up (holding the lock instead re-created
        the inline stall on a different thread: appenders queue on the
        lock for the fsync's full latency). If the store rotated between
        the name lookup and the sync, the sealed segment gets (a useful)
        sync and the fresh active one is covered by the next tick —
        within the same one-interval durability lag. The user-space
        buffer is already drained: the python writer flush()es per
        append, the native writer write()s unbuffered. Rotation between
        two ticks must not orphan the SEALED segment's unsynced tail —
        every index from the last synced segment up to the active one
        is covered, so the one-interval lag holds across rotations."""
        seg = self._active_seg
        if seg < 0:
            return  # nothing appended yet
        t0 = self._clock() if self._h_fsync is not None else 0.0
        first = self._last_synced_seg if self._last_synced_seg >= 0 else seg
        for idx in range(first, seg + 1):
            try:
                fd = os.open(self._seg_path(idx), os.O_RDONLY)
            except OSError:
                continue  # GC'd away: nothing left to sync
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        self._last_synced_seg = seg
        if self._h_fsync is not None:
            self._h_fsync.observe(self._clock() - t0)

    def _kick_erasure(self) -> None:
        """Start (or skip, if one is running) the background shard
        encoder; rate-limited so rotation-free flushes don't pay even a
        listdir. Check-and-start runs under the store lock: the kick is
        reachable from the settle path's flush, barrier flushes, and
        the flusher thread, and the unguarded alive-check let two
        concurrent kicks both start a worker (ownership lint, PR 11;
        harmless output, doubled encode I/O). Callers never hold _lock
        here — flush() releases it before kicking."""
        import time

        now = time.monotonic()
        with self._lock:
            if now - self._erasure_check_t < 1.0:
                return
            self._erasure_check_t = now
            t = self._erasure_thread
            if t is not None and t.is_alive():
                return
            t = threading.Thread(
                target=self._erasure_worker, daemon=True,
                name="segstore-erasure",
            )
            self._erasure_thread = t
            t.start()

    def _erasure_worker(self) -> None:
        from ripplemq_tpu.storage.erasure import protect_store

        try:
            protect_store(self.directory)
        except Exception as e:  # derived data: never take the store down
            _log.warning("erasure encode failed for %s: %s: %s",
                         self.directory, type(e).__name__, e)
            # append + del-slice trim must not interleave with another
            # writer (ownership lint, PR 11): error path, lock is free.
            with self._lock:
                self.erasure_errors.append(f"{type(e).__name__}: {e}")
                del self.erasure_errors[:-20]

    def gc(self) -> list[int]:
        """Delete the oldest sealed segments while their total size
        exceeds retention_bytes; returns the deleted segment INDICES.
        Records in deleted segments are gone — consumers below the new
        floor jump forward to the earliest retained record (the
        documented earliest-reset semantics); callers must prune any
        (segment, offset) indexes they hold (DataPlane.drop_index_segments).
        The persisted gc floor (`gc_floor` file) distinguishes deliberate
        head-of-store deletion from disk loss, so boot-time peer-shard
        refill is not triggered by GC gaps."""
        if self.retention_bytes is None:
            return []
        with self._lock:
            sealed = list_segment_files(self.directory)[:-1]
            sizes = {
                n: os.path.getsize(os.path.join(self.directory, n))
                for n in sealed
            }
            total = sum(sizes.values())
            deleted: list[int] = []
            for n in sealed:
                if total <= self.retention_bytes:
                    break
                idx = int(n[8:16])
                os.remove(os.path.join(self.directory, n))
                rs_dir = os.path.join(self.directory, "rs")
                if os.path.isdir(rs_dir):
                    for f in os.listdir(rs_dir):
                        if f.startswith(n + ".shard"):
                            try:
                                os.remove(os.path.join(rs_dir, f))
                            except OSError:
                                pass
                total -= sizes[n]
                deleted.append(idx)
            if deleted:
                floor = max(deleted) + 1
                tmp = os.path.join(self.directory, "gc_floor.tmp")
                with open(tmp, "w") as f:
                    f.write(str(floor))
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, os.path.join(self.directory, "gc_floor"))
            return deleted

    def protect_async(self) -> None:
        """Kick the background sealed-segment encoder. Duty loops call
        this periodically: flush() also kicks it, but flushes stop with
        write traffic, and a burst's final sealed segments must not stay
        unprotected until the next burst."""
        if self.erasure:
            self._kick_erasure()

    def wait_erasure(self, timeout: Optional[float] = None) -> None:
        """Join an in-flight background encode (tests / orderly shutdown)."""
        t = self._erasure_thread
        if t is not None and t.is_alive():
            t.join(timeout)

    def scan(self) -> Iterator[tuple[int, int, int, bytes]]:
        """Records in write order (see scan_store). Safe to call while the
        store is open for append: records written after the scan reaches
        the tail may be missed (a concurrently-written tail record reads
        as torn and ends the scan), never misread — callers that need a
        consistent prefix must order themselves against append (see
        broker/replication.py catch-up protocol)."""
        return scan_store(self.directory)

    def scan_indexed(self) -> Iterator[tuple[int, int, int, bytes, tuple[int, int]]]:
        """Like scan(), plus each record's locator (boot-time index build
        for the retention read path). Uses the native scanner's position-
        reporting walk when this store runs natively (the boot scan of a
        multi-GB store is C-speed, not Python framing); a store built
        with use_native=False keeps its opt-out here too."""
        return scan_store_indexed(
            self.directory,
            use_native=None if self._lib is not None else False,
        )

    def read_payload(self, locator: tuple[int, int], byte_start: int,
                     nbytes: int) -> bytes:
        """Read `nbytes` of a record's payload starting `byte_start` bytes
        in, by seek — no framing walk. The caller (storage.logindex) got
        `locator` from append()/scan_indexed() and knows the payload
        length; a short read means the store was truncated under us and
        raises."""
        seg_idx, off = locator
        with open(self._seg_path(seg_idx), "rb") as f:
            f.seek(off + byte_start)
            data = f.read(nbytes)
        if len(data) != nbytes:
            raise OSError(
                f"short payload read in segment {seg_idx} at {off}+{byte_start}"
            )
        return data

    def close(self) -> None:
        # Stop the async flusher first: close's own fsync below is the
        # final barrier, and a flusher fsyncing a closed file would race.
        self._flush_stop.set()
        self._flush_event.set()
        t = self._flusher
        if t is not None and t.ident is not None:
            t.join(timeout=10)
        with self._lock:
            if self._handle is not None:
                self._lib.segstore_close(self._handle)
                self._handle = None
            elif self._file is not None:
                self._file.flush()
                os.fsync(self._file.fileno())
                self._file.close()
                self._file = None
        if self.erasure:
            # Orderly shutdown: finish protection synchronously (the
            # background worker may be mid-encode or rate-limited out).
            # If the worker is STILL alive after the join timeout, skip
            # the synchronous run — two unsynchronized encoders would
            # race on the same shard .tmp paths; the straggler finishes
            # the job (or the next boot's repair pass does).
            self.wait_erasure(timeout=30)
            t = self._erasure_thread
            if t is None or not t.is_alive():
                self._erasure_worker()


def verify_store(directory: str, repair_torn_tail: bool = False) -> int:
    """Full CRC framing walk of a store directory; returns the record
    count. Raises CorruptStoreError on any damage the torn-tail crash
    contract does not cover:

    - a corrupt record in a non-final segment (what the scanners refuse
      at replay time), and
    - a corrupt record in the FINAL segment that is FOLLOWED by valid
      frames. The plain scanners cannot tell bit rot mid-file from a
      torn tail — they stop and silently drop every acked record after
      the damage; the look-ahead here upgrades that to quarantine-grade
      corruption so recovery re-replicates instead of serving a
      silently shortened history.

    `repair_torn_tail=True` additionally TRUNCATES a tolerated torn
    tail off the final segment (fsync'd). Both writers open a NEW
    segment after the highest existing index, so an un-truncated torn
    tail becomes the tail of a SEALED segment the moment the store
    reopens — and every later scan refuses it as mid-store corruption
    (the chaos proc drills hit exactly this: a phase-0 torn tail read
    clean at that boot, then crash-looped the broker's next promotion).
    The boot health gate must therefore repair what it tolerates.

    This is the boot-time health gate behind quarantine: a broker must
    know its store is fully servable BEFORE claiming any role that
    serves from it, instead of crash-looping at its next promotion
    (chaos disk-fault drills, ISSUE 4). Python framing by design — the
    walk must analyze the damage, not just refuse at it."""
    n = 0
    files = list_segment_files(directory)
    for fi, name in enumerate(files):
        last_file = fi + 1 == len(files)
        with open(os.path.join(directory, name), "rb") as f:
            blob = f.read()
        pos = 0
        bad_at = None
        while True:
            if pos == len(blob):
                break
            if pos + _HEADER.size > len(blob):
                bad_at = pos  # trailing partial header
                break
            magic, _t, _s, _b, length, crc = _HEADER.unpack(
                blob[pos : pos + _HEADER.size]
            )
            if magic != _MAGIC or length > (1 << 30):
                bad_at = pos
                break
            payload = blob[pos + _HEADER.size : pos + _HEADER.size + length]
            if (len(payload) < length
                    or _frame_crc(
                        blob[pos : pos + _HEADER_PREFIX.size], payload
                    ) != crc):
                bad_at = pos
                break
            pos += _HEADER.size + length
            n += 1
        if bad_at is None:
            continue
        if not last_file:
            raise CorruptStoreError(
                f"corrupt record in sealed segment {name}"
            )
        if _valid_frame_after(blob, bad_at + 1):
            raise CorruptStoreError(
                f"corrupt record mid-{name}: valid records follow the "
                f"damage at byte {bad_at} — bit rot, not a torn tail"
            )
        # True torn tail: tolerated (replay drops it).
        if repair_torn_tail:
            path = os.path.join(directory, name)
            with open(path, "r+b") as f:
                f.truncate(bad_at)
                f.flush()
                os.fsync(f.fileno())
            _log.info("truncated torn tail of %s at byte %d", name, bad_at)
    return n


def _valid_frame_after(blob: bytes, start: int) -> bool:
    """Whether any CRC-valid record frame begins at-or-after `start` —
    the discriminator between a torn tail (nothing follows) and mid-file
    corruption (acked records follow the damage)."""
    magic = struct.pack("<I", _MAGIC)
    pos = blob.find(magic, start)
    while pos != -1:
        if pos + _HEADER.size <= len(blob):
            _m, _t, _s, _b, length, crc = _HEADER.unpack(
                blob[pos : pos + _HEADER.size]
            )
            if (length <= (1 << 30)
                    and pos + _HEADER.size + length <= len(blob)):
                payload = blob[pos + _HEADER.size : pos + _HEADER.size + length]
                if _frame_crc(
                    blob[pos : pos + _HEADER_PREFIX.size], payload
                ) == crc:
                    return True
        pos = blob.find(magic, pos + 1)
    return False


def quarantine_store(directory: str) -> str:
    """Move a damaged store directory aside (`<dir>.quarantine-N`,
    lowest unused N) and return the new path. The caller reopens a
    fresh, empty store at `directory` and re-replicates through the
    standby catch-up protocol; the damaged bytes are preserved for
    forensics rather than deleted."""
    n = 0
    while True:
        target = f"{directory}.quarantine-{n}"
        if not os.path.exists(target):
            break
        n += 1
    os.replace(directory, target)
    return target


def gc_floor(directory: str) -> int:
    """Lowest segment index deliberately retained after GC (0 if the
    store was never GC'd). Segments below this were DELETED on purpose,
    not lost — disaster tooling (erasure.segment_index_gaps, peer-shard
    refill) must not try to resurrect them."""
    try:
        with open(os.path.join(directory, "gc_floor")) as f:
            return int(f.read().strip() or 0)
    except (OSError, ValueError):
        return 0


def scan_store(
    directory: str, use_native: Optional[bool] = None
) -> Iterator[tuple[int, int, int, bytes]]:
    """Yield (type, slot, base, payload) records in write order. A torn
    tail record is silently dropped (crash contract); corruption anywhere
    else raises CorruptStoreError."""
    for rec_type, slot, base, payload, _loc in scan_store_indexed(
        directory, use_native
    ):
        yield rec_type, slot, base, payload


def scan_store_indexed(
    directory: str, use_native: Optional[bool] = None
) -> Iterator[tuple[int, int, int, bytes, tuple[int, int]]]:
    """Yield (type, slot, base, payload, (segment_index, payload_offset))
    in write order — scan_store plus each record's locator. Same torn-
    tail/corruption contract."""
    if not os.path.isdir(directory):
        return
    lib = _load_native() if use_native in (None, True) else None
    if use_native is True and lib is None:
        raise RuntimeError("native segstore requested but unavailable")
    if lib is not None:
        yield from _scan_native_indexed(lib, directory)
    else:
        for seg_idx, off, rec in _scan_python_indexed(directory):
            rec_type, slot, base, payload = rec
            yield rec_type, slot, base, payload, (seg_idx, off)


def _scan_native_indexed(lib, directory: str):
    handle = lib.segscan_open(directory.encode())
    if not handle:
        return
    t = ctypes.c_int()
    slot = ctypes.c_int()
    base = ctypes.c_int()
    need = ctypes.c_int()
    seg = ctypes.c_int()
    off = ctypes.c_long()
    buflen = 1 << 20
    buf = ctypes.create_string_buffer(buflen)
    try:
        while True:
            rc = lib.segscan_next_at(
                handle, ctypes.byref(t), ctypes.byref(slot),
                ctypes.byref(base), buf, buflen, ctypes.byref(need),
                ctypes.byref(seg), ctypes.byref(off),
            )
            if rc == -3:  # grow the buffer and retry
                buflen = max(buflen * 2, need.value)
                buf = ctypes.create_string_buffer(buflen)
                continue
            if rc == -1:
                return
            if rc == -2:
                raise CorruptStoreError(f"corrupt record in {directory}")
            # string_at copies exactly rc bytes (buf.raw would first
            # materialize the whole — possibly grown — buffer per record).
            yield (t.value, slot.value, base.value,
                   ctypes.string_at(buf, rc), (seg.value, off.value))
    finally:
        lib.segscan_close(handle)




def _scan_python_indexed(directory: str):
    """Python framing walk yielding (segment_index, payload_offset,
    (type, slot, base, payload)) — same torn-tail/corruption contract as
    scan_store."""
    files = list_segment_files(directory)
    for fi, name in enumerate(files):
        last_file = fi + 1 == len(files)
        seg_idx = int(name[8:16])
        with open(os.path.join(directory, name), "rb") as f:
            while True:
                hdr = f.read(_HEADER.size)
                if not hdr:
                    break
                if len(hdr) < _HEADER.size:
                    if last_file:
                        return  # torn tail
                    raise CorruptStoreError(f"short header in {name}")
                magic, rec_type, slot, base, length, crc = _HEADER.unpack(hdr)
                if magic != _MAGIC:
                    if last_file:
                        return
                    raise CorruptStoreError(f"bad magic in {name}")
                if length > (1 << 30):
                    # Corrupt length field: reject BEFORE allocating a
                    # read of that size (mirrors the native scanner).
                    if last_file:
                        return
                    raise CorruptStoreError(f"absurd record length in {name}")
                payload_off = f.tell()
                payload = f.read(length)
                if (len(payload) < length
                        or _frame_crc(hdr[:_HEADER_PREFIX.size], payload)
                        != crc):
                    if last_file:
                        return  # torn/corrupt tail record
                    raise CorruptStoreError(f"bad record in {name}")
                yield seg_idx, payload_off, (rec_type, slot, base, payload)
