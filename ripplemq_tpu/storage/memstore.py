"""In-memory committed-round record store.

Same record interface as the durable SegmentStore (append/flush/close/
scan over (rec_type, slot, base, payload) tuples) with no disk behind
it. Used by single-process clusters (tests, in-proc deployments) so the
controller-failover machinery — committed-round replication to standby
brokers and standby takeover (broker/replication.py) — works without a
data dir: a standby's copy of the stream lives in its process memory,
which is exactly the durability the reference's in-memory state machines
have (reference: mq-broker/src/main/java/metadata/raft/
PartitionStateMachine.java:26-27 — messages/offsets are JVM-heap only,
surviving broker loss through replication, not disk).
"""

from __future__ import annotations

import threading

from ripplemq_tpu.obs.lockwitness import make_lock
from typing import Iterator


class MemoryRoundStore:
    """Thread-safe append-only list of committed-round records."""

    def __init__(self) -> None:
        self._records: list[tuple[int, int, int, bytes]] = []
        self._lock = make_lock("MemoryRoundStore._lock")

    def append(self, rec_type: int, slot: int, base: int,
               payload: bytes) -> bytes:
        """Append one record; the returned locator is the payload itself
        (same append→locator contract as SegmentStore.append — the
        retention read path is storage-agnostic)."""
        payload = bytes(payload)
        with self._lock:
            self._records.append((int(rec_type), int(slot), int(base),
                                  payload))
        return payload

    def flush(self) -> None:  # no durability tier to flush to
        pass

    def close(self) -> None:
        pass

    def scan(self) -> Iterator[tuple[int, int, int, bytes]]:
        """Records in write order (snapshot: safe against concurrent
        appends; records appended after the call may or may not appear)."""
        with self._lock:
            snap = list(self._records)
        return iter(snap)

    def scan_indexed(self) -> Iterator[
        tuple[int, int, int, bytes, bytes]
    ]:
        """scan() plus each record's locator (the payload bytes)."""
        for rec_type, slot, base, payload in self.scan():
            yield rec_type, slot, base, payload, payload

    def read_payload(self, locator: bytes, byte_start: int,
                     nbytes: int) -> bytes:
        return locator[byte_start : byte_start + nbytes]

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)
