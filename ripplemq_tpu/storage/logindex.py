"""Per-partition index of committed append records in the round store.

The device ring (core.state) only holds the last `slots` rows per
partition; rows trimmed off the ring live on in the round store — the
log of record. This index maps (partition slot, absolute storage offset)
to the record that holds the row, so the broker can serve lagging or
newly-attached consumers from disk with one seek instead of a framing
walk (the reference never needs this path because it retains everything
in JVM heap, PartitionStateMachine.java:26-27 — and grows without bound
for it; SURVEY.md §5 long-axis scaling).

One entry per committed append round: (base, nrows, locator). `locator`
is whatever the store's append()/scan_indexed() returned — a
(segment_index, payload_offset) pair for SegmentStore, the payload bytes
for MemoryRoundStore; this module never interprets it.

Later records win, matching replay_records: a controller-failover standby
can persist a round whose base regresses below an earlier record's end
(re-covering rows whose producers were never acked), so add() drops any
entries the new record's range supersedes.
"""

from __future__ import annotations

import bisect
import threading

from ripplemq_tpu.obs.lockwitness import make_lock
from typing import Any, Iterable, Optional


class LogIndex:
    """Thread-safe (slot, offset) → append-record lookup.

    Memory is bounded: each slot keeps at most `max_entries_per_slot`
    recent entries (one per committed round); older entries are dropped
    and `floor(slot)` reports the lowest still-indexed base. Readers
    below the floor fall back to a store scan (DataPlane._read_store) —
    correct, just slow, and only reachable for consumers lagging by more
    than max_entries_per_slot rounds."""

    def __init__(self, max_entries_per_slot: int = 1024) -> None:
        # slot -> parallel lists: bases (sorted ascending) and entries
        self._bases: dict[int, list[int]] = {}
        self._entries: dict[int, list[tuple[int, int, Any]]] = {}
        self._max = max(2, max_entries_per_slot)
        self._lock = make_lock("LogIndex._lock")

    def add(self, slot: int, base: int, nrows: int, locator: Any) -> None:
        """Record one committed append round. Drops previously-indexed
        entries with base >= the new base (later records win)."""
        with self._lock:
            bases = self._bases.setdefault(slot, [])
            entries = self._entries.setdefault(slot, [])
            while bases and bases[-1] >= base:
                bases.pop()
                entries.pop()
            bases.append(base)
            entries.append((base, nrows, locator))
            if len(bases) > self._max:
                del bases[: len(bases) - self._max]
                del entries[: len(entries) - self._max]

    def prune(self, drop) -> int:
        """Drop entries whose locator matches `drop(locator)` (store GC
        deleted their backing records). Returns the number dropped."""
        removed = 0
        with self._lock:
            for slot in list(self._entries):
                entries = self._entries[slot]
                keep = [e for e in entries if not drop(e[2])]
                if len(keep) != len(entries):
                    removed += len(entries) - len(keep)
                    self._entries[slot] = keep
                    self._bases[slot] = [e[0] for e in keep]
        return removed

    def floor(self, slot: int) -> Optional[int]:
        """Lowest indexed base for `slot` (None if nothing indexed).
        Offsets below this may still exist in the store — only a store
        scan can tell."""
        with self._lock:
            bases = self._bases.get(slot)
            return bases[0] if bases else None

    def load(self, records: Iterable[tuple[int, int, int, bytes, Any]],
             slot_bytes: int, rec_append: int) -> None:
        """Boot-time build from a store's scan_indexed() stream."""
        for rec_type, slot, base, payload, locator in records:
            if rec_type != rec_append:
                continue
            self.add(slot, base, len(payload) // slot_bytes, locator)

    def find(self, slot: int, offset: int) -> Optional[tuple[int, int, Any]]:
        """The entry covering `offset`, or the next entry after it (a
        consumer below the earliest retained record jumps forward — the
        same semantics as Kafka's earliest reset), or None when nothing
        at-or-after `offset` is indexed (the caller falls through to the
        device ring). Callers must check floor() first: an offset below
        the floor would otherwise "jump" over records that exist in the
        store but fell out of the bounded index."""
        with self._lock:
            bases = self._bases.get(slot)
            if not bases:
                return None
            return locate(bases, self._entries[slot], offset)


def locate(bases: list[int], entries: list[tuple[int, int, Any]],
           offset: int) -> Optional[tuple[int, int, Any]]:
    """Covering-or-next lookup over parallel sorted (bases, entries)
    lists — shared by the in-memory index and the store-scan slow path."""
    i = bisect.bisect_right(bases, offset) - 1
    if i >= 0:
        base, nrows, _ = entries[i]
        if offset < base + nrows:
            return entries[i]
        i += 1
    else:
        i = 0
    if i < len(entries):
        return entries[i]
    return None
