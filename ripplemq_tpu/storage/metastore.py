"""Durable metadata-Raft state: atomic file persistence for hostraft.

Persists the node's hard state (term, vote, log, snapshot) on every
mutation via RaftNode's persist_fn hook, and restores it on boot — the
role JRaft's raft_meta/raft_log storage plays for the reference
(TopicsRaftServer.java:134-136). Atomicity: write to a temp file, fsync,
rename (POSIX atomic replace); a crash mid-write leaves the previous
image intact. Serialization is the wire codec (commands are wire-shaped
dicts already).
"""

from __future__ import annotations

import os
from typing import Optional

from ripplemq_tpu.wire import codec


class MetaStore:
    def __init__(self, path: str) -> None:
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def save(self, state: dict) -> None:
        blob = codec.encode(state)
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def load(self) -> Optional[dict]:
        """The persisted image, or None if absent/unreadable (a torn temp
        file never shadows the last good image)."""
        try:
            with open(self.path, "rb") as f:
                return codec.decode(f.read())
        except (OSError, ValueError):
            return None
