"""Host-side durability tier.

- `segment` — append-only CRC-framed segment store (native C++ via
  ctypes with a pure-Python fallback writing the identical format) for
  the committed data-plane log; replay rebuilds device state on restart.
- `metastore` — atomic file persistence for the metadata Raft's
  term/vote/log (hostraft persist_fn/restore wiring).
"""

from ripplemq_tpu.storage.segment import (
    REC_APPEND,
    REC_META,
    REC_OFFSETS,
    SegmentStore,
    native_available,
    scan_store,
)
from ripplemq_tpu.storage.metastore import MetaStore

__all__ = [
    "REC_APPEND",
    "REC_META",
    "REC_OFFSETS",
    "SegmentStore",
    "native_available",
    "scan_store",
    "MetaStore",
]
