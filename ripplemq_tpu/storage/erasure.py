"""Erasure-coded protection for sealed log segments (RS(3,2) over GF(2⁸)).

The reference's only durability story is JRaft's full replication — every
broker stores every byte of every partition it replicates (reference:
mq-broker/src/main/java/metadata/raft/PartitionRaftServer.java:88-90
storage URIs; SURVEY.md §2.4). Here, sealed (rotated, immutable) segment
files additionally get k+m = 5 Reed–Solomon shards at 5/3× overhead; any
k = 3 surviving shards rebuild the segment byte-for-byte, so a corrupt or
lost sealed segment no longer costs the data (the torn-tail contract only
protects the ACTIVE segment's tail). Encoding runs the Pallas GF(2⁸)
matmul kernel on TPU (ripplemq_tpu.ops.rs) and the XLA fallback
elsewhere.

Layout: shards of `segment-XXXXXXXX.log` live in `<store>/rs/` as
`segment-XXXXXXXX.log.shard{0..4}`. Shard i < k is data quarter i; shard
k+i is parity i. Each shard file carries its own CRC plus the CRC of the
whole original segment, so repair can tell a stale shard set from a
usable one.

Protection window note: protect_store treats shard-file PRESENCE of a
complete set as protected without re-reading shard CRCs (a full CRC scrub
per flush would defeat the off-path design), so a shard that rots on disk
silently lowers that segment's loss tolerance below m until the next
boot. The window CLOSES at boot: repair_store validates every shard's
CRC and rewrites any set short of k+m valid shards — including a fully
rotted or mixed-generation set over a healthy segment, which is
re-encoded fresh (directed coverage: tests/test_storage.py shard-rot
repair tests).
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Optional

import numpy as np

from ripplemq_tpu.ops.rs import rs_encode, rs_reconstruct

# ONE RS geometry for the whole repo: the sealed-segment shards here and
# the hot-path replication stripes (ripplemq_tpu/stripes/) share the
# codec constants, so both reconstruct with the same extended-Cauchy
# matrices and a deployment reasons about a single k-of-k+m contract.
from ripplemq_tpu.stripes.codec import RS_K as K, RS_M as M

_MAGIC = 0x52535348  # "RSSH"
_VERSION = 1
# magic, version, shard index, k, m, original segment length, crc of the
# original segment bytes, crc of this shard's payload
_HEADER = struct.Struct("<IBBBBQII")


class ShardError(Exception):
    pass


def _rs_dir(store_dir: str) -> str:
    return os.path.join(store_dir, "rs")


def shard_paths(store_dir: str, seg_name: str) -> list[str]:
    return [
        os.path.join(_rs_dir(store_dir), f"{seg_name}.shard{i}")
        for i in range(K + M)
    ]


def _shard_length(orig_len: int) -> int:
    return -(-orig_len // K)  # ceil; last data shard is zero-padded


def encode_segment(store_dir: str, seg_name: str, **kw) -> list[str]:
    """Write the K+M shard files for one sealed segment. Atomic per shard
    (tmp + rename); returns the shard paths.

    The GF matmul defaults to the HOST CPU backend here: the storage
    plane must not ride the accelerator link — a segment-scale parity
    fetch over a network-tunneled chip (~2-5 MB/s device→host) clogs
    the link the data plane's quorum rounds depend on for ~10 s per
    seal. Pass platform=None/use_pallas to route it to the TPU kernel
    on PCIe-attached deployments (ops/rs.py gf_matmul)."""
    kw.setdefault("platform", "cpu")
    seg_path = os.path.join(store_dir, seg_name)
    with open(seg_path, "rb") as f:
        raw = f.read()
    data_crc = zlib.crc32(raw) & 0xFFFFFFFF
    n = _shard_length(len(raw))
    padded = np.zeros(K * n, np.uint8)
    padded[: len(raw)] = np.frombuffer(raw, np.uint8)
    data = padded.reshape(K, n)
    parity = np.asarray(rs_encode(data, k=K, m=M, **kw))
    shards = np.concatenate([data, parity], axis=0)
    os.makedirs(_rs_dir(store_dir), exist_ok=True)
    paths = shard_paths(store_dir, seg_name)
    for i, path in enumerate(paths):
        payload = shards[i].tobytes()
        header = _HEADER.pack(
            _MAGIC, _VERSION, i, K, M, len(raw), data_crc,
            zlib.crc32(payload) & 0xFFFFFFFF,
        )
        tmp = path + ".tmp"
        try:
            with open(tmp, "wb") as f:
                f.write(header + payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except FileNotFoundError:
            # The rs/ directory vanished under us (disaster-recovery
            # teardown racing a still-draining encode worker). Shards
            # are DERIVED data: skip — the next protect pass re-encodes
            # from the sealed segment instead of crashing the worker.
            return []
    return paths


def _read_shard(path: str) -> Optional[tuple[int, int, int, np.ndarray]]:
    """→ (index, orig_len, data_crc, payload) or None if missing/corrupt."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        return None
    if len(blob) < _HEADER.size:
        return None
    magic, version, idx, k, m, orig_len, data_crc, shard_crc = _HEADER.unpack(
        blob[: _HEADER.size]
    )
    if magic != _MAGIC or version != _VERSION or (k, m) != (K, M):
        return None
    payload = blob[_HEADER.size :]
    if len(payload) != _shard_length(orig_len):
        return None
    if (zlib.crc32(payload) & 0xFFFFFFFF) != shard_crc:
        return None
    return idx, orig_len, data_crc, np.frombuffer(payload, np.uint8)


def reconstruct_segment(store_dir: str, seg_name: str, **kw) -> bytes:
    """Rebuild one segment's bytes from any K valid shards. Raises
    ShardError if fewer than K shards survive or the rebuilt bytes fail
    the recorded segment CRC."""
    present: dict[int, np.ndarray] = {}
    meta: Optional[tuple[int, int]] = None
    for path in shard_paths(store_dir, seg_name):
        got = _read_shard(path)
        if got is None:
            continue
        idx, orig_len, data_crc, payload = got
        if meta is None:
            meta = (orig_len, data_crc)
        elif meta != (orig_len, data_crc):
            raise ShardError(f"mixed shard generations for {seg_name}")
        present[idx] = payload
    if meta is None or len(present) < K:
        raise ShardError(
            f"{seg_name}: only {len(present)} valid shards, need {K}"
        )
    orig_len, data_crc = meta
    if all(i in present for i in range(K)):
        data = np.stack([present[i] for i in range(K)])
    else:
        kw.setdefault("platform", "cpu")  # see encode_segment
        data = np.asarray(rs_reconstruct(present, k=K, m=M, **kw))
    raw = data.reshape(-1).tobytes()[:orig_len]
    if (zlib.crc32(raw) & 0xFFFFFFFF) != data_crc:
        raise ShardError(f"{seg_name}: reconstructed bytes fail segment CRC")
    return raw


def _segment_names(store_dir: str) -> list[str]:
    if not os.path.isdir(store_dir):
        return []
    return sorted(
        f for f in os.listdir(store_dir)
        if f.startswith("segment-") and f.endswith(".log")
    )


def _shard_counts(store_dir: str) -> dict[str, int]:
    rs_dir = _rs_dir(store_dir)
    if not os.path.isdir(rs_dir):
        return {}
    counts: dict[str, int] = {}
    for f in os.listdir(rs_dir):
        stem, _, suffix = f.rpartition(".shard")
        if stem and suffix.isdigit():
            counts[stem] = counts.get(stem, 0) + 1
    return counts


def _protected_names(store_dir: str) -> set[str]:
    """Segment names with at least one shard file present (repair decides
    usability from shard CONTENTS — presence of any shard is enough to
    consider the set, since up to M shards may themselves be lost)."""
    return set(_shard_counts(store_dir))


def protect_store(store_dir: str, limit: Optional[int] = None,
                  **kw) -> list[str]:
    """Encode shards for sealed segments (every segment but the highest-
    numbered, which is still being appended) that lack a COMPLETE shard
    set — a crash mid-encode leaves a partial set, which must not count
    as protected (it may tolerate fewer than M losses, or none). Empty
    segments (a restart artifact: both store backends open a fresh index
    on boot) carry no data and are skipped. `limit` bounds work per call
    so callers can amortize. Returns the segment names encoded."""
    names = _segment_names(store_dir)[:-1]
    counts = _shard_counts(store_dir)
    done = []
    for name in names:
        if counts.get(name, 0) >= K + M:
            continue
        if os.path.getsize(os.path.join(store_dir, name)) == 0:
            continue
        encode_segment(store_dir, name, **kw)
        done.append(name)
        if limit is not None and len(done) >= limit:
            break
    return done


def shard_file_names(store_dir: str) -> list[str]:
    """Names of every shard file in the store's rs/ dir (push duty)."""
    rs_dir = _rs_dir(store_dir)
    if not os.path.isdir(rs_dir):
        return []
    return sorted(
        f for f in os.listdir(rs_dir)
        if ".shard" in f and not f.endswith(".tmp")
    )


def valid_shard_name(name: str) -> bool:
    """Guard for wire-supplied shard file names (path-traversal safety +
    exact shape check — segment-XXXXXXXX.log.shardN — before anything
    touches the filesystem or parses the index digits)."""
    stem, _, suffix = name.rpartition(".shard")
    return (
        len(stem) == 20
        and suffix.isdigit()
        and int(suffix) < K + M
        and stem.startswith("segment-")
        and stem.endswith(".log")
        and stem[8:16].isdigit()
        and "/" not in name
        and "\\" not in name
        and ".." not in name
    )


def refill_from_peers(store_dir: str, list_fns, get_fn) -> list[str]:
    """Re-populate rs/ with peer-held shard copies for sealed segments
    MISSING from this store, so the ordinary repair_store pass can
    rebuild them — the disaster path when a broker lost both a segment
    and its local shards (the reference survives this only because every
    broker fully replicates every partition it hosts,
    PartitionRaftServer.java:88-90; here any K of the K+M distributed
    shards suffice at (K+M)/K x overhead).

    `list_fns` is [(peer_tag, callable() -> shard file names held for
    this owner)], `get_fn(peer_tag, name) -> bytes | None`. Fetched blobs
    are CRC-validated by the shard reader before being trusted; invalid
    or unsafe names are skipped. Best-effort: unreachable peers are the
    caller's problem to log. Returns the segment names refilled."""
    # Which shard sets do peers hold that we cannot reconstruct locally?
    # Keyed on local shard count < K, NOT on segment-file presence: a
    # present-but-corrupt segment whose local shards were also lost is
    # exactly as dead as a missing one, and only peer shards can save it
    # (a present-and-healthy file costs at most K redundant fetches —
    # repair validates health before rewriting anything). Segments below
    # the persisted GC floor were deleted deliberately — never refill
    # them.
    from ripplemq_tpu.storage.segment import gc_floor, segment_index

    floor = gc_floor(store_dir)
    remote: dict[str, list[tuple[str, str]]] = {}  # seg -> [(peer, fname)]
    for peer, list_fn in list_fns:
        try:
            names = list_fn()
        except Exception:
            continue
        for fname in names:
            if not valid_shard_name(fname):
                continue
            stem = fname.rpartition(".shard")[0]
            if segment_index(stem) < floor:
                continue
            remote.setdefault(stem, []).append((peer, fname))
    refilled = []
    rs_dir = _rs_dir(store_dir)
    for stem, sources in sorted(remote.items()):
        # VALID local shards only — a corrupt shard file present on disk
        # must not count toward reconstructability, and must not block
        # its index from being refilled (it gets overwritten below).
        valid_idx = {
            i for i, p in enumerate(shard_paths(store_dir, stem))
            if _read_shard(p) is not None
        }
        have = len(valid_idx)
        if have >= K:
            continue  # locally reconstructable already
        got = 0
        seen_idx: set[int] = set(valid_idx)
        for peer, fname in sources:
            if have + got >= K:
                break  # K shards reconstruct; repair re-encodes the rest
            idx = int(fname.rpartition(".shard")[2])
            if idx in seen_idx:
                continue
            try:
                blob = get_fn(peer, fname)
            except Exception:
                continue
            if not blob:
                continue
            os.makedirs(rs_dir, exist_ok=True)
            tmp = os.path.join(rs_dir, fname + ".tmp")
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            if _read_shard(tmp) is None:  # CRC/shape reject
                os.remove(tmp)
                continue
            os.replace(tmp, os.path.join(rs_dir, fname))
            seen_idx.add(idx)
            got += 1
        if got:
            refilled.append(stem)
    return refilled


def segment_index_gaps(store_dir: str) -> bool:
    """True when the store's segment numbering has holes (indices rotate
    contiguously, so a hole means a sealed segment FILE was lost) — the
    cheap local evidence that gates boot-time peer refill. Indices below
    the persisted GC floor were deleted deliberately and are not
    holes."""
    from ripplemq_tpu.storage.segment import gc_floor

    names = _segment_names(store_dir)
    if not names:
        return False
    indices = {int(n[8:16]) for n in names}
    floor = gc_floor(store_dir)
    return indices != set(range(floor, max(indices) + 1))


def repair_store(store_dir: str, **kw) -> list[str]:
    """Rebuild sealed segment files that are missing or fail their shard-
    recorded CRC. Called before replay (recover_image). Best-effort by
    design: segments without shard sets — and ones whose shard sets are
    too damaged to reconstruct (> M losses) — are left to the scanner's
    own corruption handling, so a half-dead shard set degrades exactly
    like a dead one instead of blocking broker boot. Returns the segment
    names repaired."""
    repaired = []
    for name in sorted(_protected_names(store_dir)):
        seg_path = os.path.join(store_dir, name)
        # The health check must use a CONSISTENT shard generation: a stale
        # straggler shard must not mark a healthy segment unhealthy
        # (reconstruct_segment refuses mixed generations anyway), so
        # require every valid shard to agree on (orig_len, data_crc).
        gens: set[tuple[int, int]] = set()
        valid_shards = 0
        for path in shard_paths(store_dir, name):
            got = _read_shard(path)
            if got is not None:
                _, o, c, _ = got
                gens.add((o, c))
                valid_shards += 1
        if len(gens) != 1:
            # No single consistent generation survives: every shard
            # rotted, or stale stragglers disagree. protect_store counts
            # shard-file PRESENCE (the documented protection window), so
            # without this branch such a set would stay "protected"
            # while protecting nothing. If the segment file itself is
            # readable, re-encode a fresh consistent set from it; an
            # unreadable segment with no usable shards stays the
            # scanner's problem, as before.
            if os.path.isfile(seg_path):
                try:
                    encode_segment(store_dir, name, **kw)
                except Exception:
                    pass  # derived data: never block recovery/boot
            continue
        orig_len, data_crc = next(iter(gens))
        try:
            with open(seg_path, "rb") as f:
                raw = f.read()
            healthy = (
                len(raw) == orig_len
                and (zlib.crc32(raw) & 0xFFFFFFFF) == data_crc
            )
        except OSError:
            healthy = False
        if not healthy:
            try:
                raw = reconstruct_segment(store_dir, name, **kw)
            except ShardError:
                continue  # > M losses: fall through to the scanner
            tmp = seg_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(raw)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, seg_path)
            repaired.append(name)
        if valid_shards < K + M:
            # Restore full m-loss tolerance: re-derive the lost/corrupt
            # shards from the (now healthy) segment bytes. Best-effort —
            # shards are derived data; failing to rewrite them must not
            # block recovery.
            try:
                encode_segment(store_dir, name, **kw)
            except Exception:
                # encode runs device kernels (rs_encode), so non-OSError
                # failures (JAX/XLA runtime errors) are possible too —
                # never let derived data block recovery/boot.
                pass
    return repaired
