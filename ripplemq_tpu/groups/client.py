"""GroupConsumer: the member-side consumer-group SDK.

One GroupConsumer is one MEMBER of one group: it joins (learning its
generation + assigned partitions from the replicated coordinator
state), polls its assignment round-robin through the ordinary consume
path, heartbeats the metadata leader so the coordinator can evict dead
members, commits offsets under the group's SHARED consumer name with
generation fencing, and leaves on close. Rebalances are learned from
heartbeat/join responses (poll-based — no server push): a member whose
partition moved simply stops being assigned it next heartbeat, and a
commit raced past its own rebalance is refused with
`fenced_generation` (the member rejoins and resumes on its new
assignment). Works over both transports — the in-proc fake network and
real TCP — like every other client.
"""

from __future__ import annotations

import time
import uuid
from typing import Optional

from ripplemq_tpu.client.consumer import ConsumerClient
from ripplemq_tpu.groups.state import group_consumer_name
from ripplemq_tpu.metadata.models import GroupKey
from ripplemq_tpu.wire.retry import RetryPolicy, fatal_response_error
from ripplemq_tpu.wire.transport import RpcError, Transport


class GroupError(Exception):
    pass


class FencedError(GroupError):
    """A commit carried a stale generation (or a membership this
    coordinator no longer recognizes): the member must rejoin and
    resume on its NEW assignment — the refused offset is not lost, the
    partition's new owner re-reads from the last acked commit."""


class GroupConsumer:
    def __init__(
        self,
        bootstrap: list[str],
        group: str,
        topics: tuple[str, ...] | list[str],
        member_id: Optional[str] = None,
        transport: Optional[Transport] = None,
        heartbeat_s: float = 0.5,
        max_messages: Optional[int] = None,
        metadata_refresh_s: float = 5.0,
        rpc_timeout_s: float = 5.0,
        retries: int = 3,
        retry_backoff_s: float = 0.1,
        deadline_s: Optional[float] = None,
    ) -> None:
        self.group = group
        self.topics = tuple(topics)
        self.member_id = member_id or f"{group}-m-{uuid.uuid4().hex[:8]}"
        self._bootstrap = list(bootstrap)
        self._timeout = rpc_timeout_s
        self.heartbeat_s = heartbeat_s
        self._last_beat = 0.0
        # Learned coordinator state.
        self.generation = -1
        self.assignment: tuple[GroupKey, ...] = ()
        self._rr = 0  # round-robin cursor over the assignment
        self._retry = RetryPolicy(
            max_attempts=retries, base_backoff_s=retry_backoff_s,
            deadline_s=deadline_s,
        )
        # All reads/commits ride the group's SHARED consumer name: the
        # committed offset is group state, so a partition moving to
        # another member resumes where the group left off.
        self._consumer = ConsumerClient(
            bootstrap, group_consumer_name(group), transport=transport,
            auto_commit=False, metadata_refresh_s=metadata_refresh_s,
            rpc_timeout_s=rpc_timeout_s, retries=retries,
            retry_backoff_s=retry_backoff_s, deadline_s=deadline_s,
            max_messages=max_messages if max_messages else 10,
        )
        self._transport = self._consumer._transport
        self._closed = False

    # ---------------------------------------------------------- membership

    def _call_group(self, req: dict) -> dict:
        """One group.* RPC against any reachable broker (group ops are
        forwarded broker-side: join/leave to the metadata raft,
        heartbeats to the metadata leader's liveness ledger)."""
        run = self._retry.begin()
        i = 0
        while run.attempt():
            addr = self._bootstrap[i % len(self._bootstrap)]
            i += 1
            try:
                resp = self._transport.call(
                    addr, req, timeout=run.clip(self._timeout)
                )
            except RpcError as e:
                run.note(str(e))
                continue
            if resp.get("ok"):
                return resp
            err = str(resp.get("error", ""))
            run.note(err)
            if err.startswith("unknown_member"):
                return resp  # caller rejoins — retrying cannot fix it
            if fatal_response_error(err):
                raise GroupError(err)
        raise GroupError(
            f"group rpc {req.get('type')} failed: {run.summary()}"
        )

    def _adopt(self, resp: dict) -> None:
        gen = int(resp.get("generation", -1))
        assignment = tuple(
            (str(t), int(p)) for t, p in resp.get("assignment", [])
        )
        if gen != self.generation or assignment != self.assignment:
            self.generation = gen
            self.assignment = assignment
            self._rr = 0

    def join(self) -> tuple[GroupKey, ...]:
        """Join (or re-confirm) membership; returns the assignment."""
        resp = self._call_group({
            "type": "group.join", "group": self.group,
            "member": self.member_id, "topics": list(self.topics),
        })
        self._adopt(resp)
        self._last_beat = time.monotonic()
        return self.assignment

    def heartbeat(self, force: bool = False) -> bool:
        """Beat if the interval elapsed (or `force`); adopts any
        rebalance the response reveals. Returns True if a beat was
        sent. An `unknown_member` answer means this member was evicted
        (session lapsed, e.g. a stalled process): rejoin transparently —
        the next poll runs on the fresh assignment."""
        now = time.monotonic()
        if not force and now - self._last_beat < self.heartbeat_s:
            return False
        self._last_beat = now
        resp = self._call_group({
            "type": "group.heartbeat", "group": self.group,
            "member": self.member_id, "generation": self.generation,
        })
        if not resp.get("ok"):
            # unknown_member: evicted — rejoin under the same id.
            self.join()
            return True
        self._adopt(resp)
        return True

    def leave(self) -> None:
        self._call_group({
            "type": "group.leave", "group": self.group,
            "member": self.member_id,
        })
        self.generation = -1
        self.assignment = ()

    # ---------------------------------------------------------------- data

    def poll(
        self, max_messages: Optional[int] = None
    ) -> tuple[Optional[GroupKey], list[bytes]]:
        """Heartbeat if due, then read one assigned partition (round-
        robin) and commit the advance under the current generation
        BEFORE delivering (the at-most-once contract of auto-commit,
        group edition). Returns ((topic, partition), messages) —
        (None, []) when nothing is assigned. A commit fenced by a
        concurrent rebalance rejoins and delivers NOTHING: the rows
        belong to the partition's new owner."""
        key, msgs, _, _ = self.poll_with_position(max_messages)
        return key, msgs

    def poll_with_position(
        self, max_messages: Optional[int] = None
    ) -> tuple[Optional[GroupKey], list[bytes], int, int]:
        """poll(), also returning (key, messages, offset, next_offset)
        — the positions harnesses record into operation histories."""
        self.heartbeat()
        if not self.assignment:
            return None, [], 0, 0
        key = self.assignment[self._rr % len(self.assignment)]
        self._rr += 1
        topic, pid = key
        msgs, _, off, nxt = self._consumer.consume_with_position(
            topic, partition=pid, max_messages=max_messages
        )
        if not msgs:
            return key, [], off, nxt
        try:
            self.commit(topic, pid, nxt)
        except FencedError:
            # Rebalanced under us: the partition (possibly) moved — the
            # new owner re-reads from the group's last acked commit, so
            # delivering these rows here would double-deliver them.
            self.join()
            return key, [], off, off
        return key, msgs, off, nxt

    def commit(self, topic: str, partition: int, offset: int,
               generation: Optional[int] = None) -> None:
        """Commit under the group's shared consumer name, fenced by
        `generation` (defaults to the member's current one). A
        `fenced_generation` refusal raises FencedError — typed, never a
        silent overwrite. `generation` is overridable so harnesses can
        prove the fence (a deposed member committing at a stale
        generation MUST be refused)."""
        gen = self.generation if generation is None else int(generation)
        run = self._retry.begin()
        while run.attempt():
            addr = self._consumer._meta.leader_addr(topic, partition)
            if addr is None:
                run.note(f"no leader known for {topic}[{partition}]")
                self._consumer._refresh_quietly()
                continue
            try:
                resp = self._transport.call(
                    addr,
                    {"type": "offset.commit", "topic": topic,
                     "partition": partition,
                     "consumer": group_consumer_name(self.group),
                     "group": self.group, "member": self.member_id,
                     "generation": gen, "offset": int(offset)},
                    timeout=run.clip(self._timeout),
                )
            except RpcError as e:
                run.note(str(e))
                self._consumer._refresh_quietly()
                continue
            if resp.get("ok"):
                return
            err = str(resp.get("error", ""))
            run.note(err)
            if err.startswith("fenced_generation"):
                raise FencedError(err)
            if err == "not_leader":
                self._consumer._refresh_quietly()
                continue
            if fatal_response_error(err):
                raise GroupError(err)
        raise GroupError(
            f"group commit {topic}[{partition}]={offset} failed: "
            f"{run.summary()}"
        )

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            if self.generation >= 0:
                self.leave()
        except Exception:
            pass  # best-effort: close must not raise over a dead broker
        self._consumer.close()
