"""Coordinator state machine + liveness: the broker side of groups.

GroupTable is the metadata state machine's group section — mutated ONLY
by replicated OP_GROUP_JOIN / OP_GROUP_LEAVE applies (broker/manager.py)
so every broker holds the identical generation/assignment picture, and
generation fencing on offset commits can be checked wherever the commit
lands. GroupLiveness is the metadata leader's VOLATILE heartbeat ledger:
members beat against the current leader, the leader's duty evicts
members whose session lapsed by proposing OP_GROUP_LEAVE (reason
"evicted") — a leader change simply restarts every member's grace
window, the standard cost of volatile liveness.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from ripplemq_tpu.groups.state import (
    GroupState,
    compute_assignment,
    compute_assignment_delta,
)


class GroupTable:
    """All groups' replicated state. NOT internally locked: the owner
    (PartitionManager) serializes applies and reads under its own lock.

    Two mutation modes: the per-op path (`join`/`leave`) rebalances the
    touched group IMMEDIATELY — one generation bump per membership
    event, the pre-wave shape that standalone OP_GROUP_JOIN /
    OP_GROUP_LEAVE applies still use. The WAVE path
    (`join_deferred`/`leave_deferred` + `finish_wave`) applies every
    membership mutation of one OP_BATCH wave first and rebalances each
    TOUCHED group exactly once at the end: N joins to one group cost
    one generation bump and one assignment compute, and a replayed
    duplicate wave (leader retry straddling a failover) finds every
    sub-op a no-op and bumps nothing."""

    def __init__(self) -> None:
        self.groups: dict[str, GroupState] = {}
        # Transient wave bookkeeping, alive only inside one OP_BATCH
        # apply (the manager holds its lock across the whole wave):
        # group → (pre-wave members snapshot, changed member ids).
        self._wave: dict[str, tuple[dict[str, tuple[str, ...]],
                                    set[str]]] = {}

    def join(self, group: str, member: str, topics: tuple[str, ...],
             topic_partitions: dict[str, int]) -> tuple[GroupState, bool]:
        """Apply one member join. Returns (state, changed): re-joining
        with an unchanged subscription is a no-op (join proposals are
        retried/duplicated by clients; idempotence keeps the generation
        from churning under replays)."""
        st, changed = self._join_members(group, member, topics)
        if changed:
            self._rebalance(st, topic_partitions)
        return st, changed

    def _join_members(self, group: str, member: str,
                      topics: tuple[str, ...]) -> tuple[GroupState, bool]:
        """Membership half of a join (no rebalance)."""
        st = self.groups.get(group)
        if st is None:
            st = self.groups[group] = GroupState(name=group)
        topics = tuple(sorted(set(topics)))
        if st.members.get(member) == topics:
            return st, False
        st.members[member] = topics
        return st, True

    # ------------------------------------------------------ wave deferral

    def join_deferred(self, group: str, member: str,
                      topics: tuple[str, ...]) -> tuple[GroupState, bool]:
        """Wave-mode join: mutate membership now, rebalance at
        `finish_wave`. Returns (state, changed) with the same
        idempotence as `join`."""
        self._wave_touch(group)
        st, changed = self._join_members(group, member, topics)
        if changed:
            self._wave[group][1].add(member)
        return st, changed

    def leave_deferred(self, group: str, member: str
                       ) -> tuple[Optional[GroupState], bool, bool]:
        """Wave-mode leave: mutate membership now, rebalance at
        `finish_wave`. Returns (state, changed, emptied) like `leave`."""
        st = self.groups.get(group)
        if st is None or member not in st.members:
            return st, False, False
        self._wave_touch(group)
        del st.members[member]
        self._wave[group][1].add(member)
        return st, True, not st.members

    def _wave_touch(self, group: str) -> None:
        if group not in self._wave:
            st = self.groups.get(group)
            snapshot = dict(st.members) if st is not None else {}
            self._wave[group] = (snapshot, set())

    def finish_wave(self, topic_partitions: dict[str, int]
                    ) -> list[tuple[str, GroupState]]:
        """Rebalance every group the wave CHANGED — one generation bump
        and one (incremental) assignment compute per touched group, in
        sorted group order (deterministic across brokers). Groups whose
        sub-ops all no-opped (a duplicate wave) are skipped: their
        generation does not move, so a replayed wave fences nothing.
        Returns the rebalanced (name, state) pairs for event
        recording."""
        out: list[tuple[str, GroupState]] = []
        for group in sorted(self._wave):
            prev_members, changed = self._wave[group]
            st = self.groups.get(group)
            if st is None or not changed:
                continue
            st.generation += 1
            st.assignment = dict(compute_assignment_delta(
                st.members, topic_partitions, st.assignment,
                prev_members, changed,
            ))
            out.append((group, st))
        self._wave.clear()
        return out

    def leave(self, group: str, member: str,
              topic_partitions: dict[str, int]
              ) -> tuple[Optional[GroupState], bool, bool]:
        """Apply one member leave/eviction. Returns (state, changed,
        emptied). An EMPTIED group is RETAINED — generation monotone,
        shared offsets intact — not dropped: a rebalance storm (or a
        partition separating every member from the heartbeat path) can
        empty a group TRANSIENTLY, and dropping it would restart
        generations at 1 and recycle the offset slot mid-life, so the
        re-formed group re-consumes the whole log from 0 (caught by the
        randomized storm soak as group-commit regressions + redelivery).
        Truly dead groups are reaped by `delete()` after the metadata
        leader's retention window (`group_retention_s`)."""
        st = self.groups.get(group)
        if st is None or member not in st.members:
            return st, False, False
        del st.members[member]
        self._rebalance(st, topic_partitions)
        return st, True, not st.members

    def delete(self, group: str) -> bool:
        """Reap one group iff it is (still) EMPTY — the deterministic
        apply of OP_GROUP_DELETE (a join racing the reap proposal keeps
        the group: membership wins). Returns whether it was dropped;
        the caller releases the shared consumer slot."""
        st = self.groups.get(group)
        if st is None or st.members:
            return False
        del self.groups[group]
        return True

    def empty_groups(self) -> list[str]:
        return sorted(n for n, st in self.groups.items() if not st.members)

    def _rebalance(self, st: GroupState,
                   topic_partitions: dict[str, int]) -> None:
        st.generation += 1
        st.assignment = dict(compute_assignment(
            st.members, topic_partitions, previous=st.assignment
        ))

    # ------------------------------------------------------------- queries

    def state(self, group: str) -> Optional[GroupState]:
        return self.groups.get(group)

    def summary(self) -> dict:
        """admin.stats surface: per-group generation + membership."""
        return {
            name: {
                "generation": st.generation,
                "members": sorted(st.members),
                "partitions": sum(len(k) for k in st.assignment.values()),
            }
            for name, st in self.groups.items()
        }

    # ---------------------------------------------------------- wire state

    def to_wire(self) -> dict:
        return {name: st.to_wire() for name, st in self.groups.items()}

    @staticmethod
    def from_wire(d: dict) -> "GroupTable":
        t = GroupTable()
        for name, st in (d or {}).items():
            t.groups[str(name)] = GroupState.from_wire(st)
        return t


class GroupLiveness:
    """Volatile heartbeat ledger (metadata leader only). A member is
    evictable once `session_timeout_s` passes with no beat — measured
    from its FIRST SIGHTING on this leader, so a fresh leader (or a
    just-joined member that has not beaten yet) grants a full grace
    window instead of evicting on day-zero silence."""

    def __init__(self, clock: Callable[[], float] = time.monotonic) -> None:
        self._clock = clock
        self._last: dict[tuple[str, str], float] = {}

    def beat(self, group: str, member: str) -> None:
        self._last[(group, member)] = self._clock()

    def forget(self, group: str, member: str) -> None:
        self._last.pop((group, member), None)

    def clear(self) -> None:
        """Drop every stamp — called when the owning broker LOSES the
        metadata lease. Stamps from a previous tenure are stale (members
        beat the new leader meanwhile); keeping them would let a
        re-elected leader's first duty tick mass-evict healthy members."""
        self._last.clear()

    def plan_evictions(self, table: GroupTable,
                       session_timeout_s: float) -> list[tuple[str, str]]:
        """Members of `table` whose session lapsed. Also seeds the grace
        window for members never seen on this leader, and prunes stamps
        for members no longer in the table."""
        now = self._clock()
        live_keys = {
            (name, m)
            for name, st in table.groups.items()
            for m in st.members
        }
        for key in list(self._last):
            if key not in live_keys:
                del self._last[key]
        out = []
        for key in live_keys:
            t = self._last.setdefault(key, now)  # first sighting = grace
            if now - t > session_timeout_s:
                out.append(key)
        return sorted(out)
