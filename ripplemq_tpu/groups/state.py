"""Replicated consumer-group state + the deterministic assignment rule.

Everything here is applied inside the metadata Raft's state machine
(broker/manager.py), so it must be a PURE function of replicated inputs:
the member set (with subscriptions), the static topic table, and the
previous assignment. Every broker's apply computes the identical
assignment for the identical generation — there is no separate
"assignment proposal" round trip, and a member learns its partitions
from any broker's replicated view (join response / heartbeat).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ripplemq_tpu.metadata.models import GroupKey


def group_consumer_name(group: str) -> str:
    """The group's SHARED offset-tracking consumer name: all members
    commit under it, so a partition moving between members resumes from
    the group's last acked commit (one engine consumer slot per group,
    not per member)."""
    return f"g/{group}"


@dataclasses.dataclass
class GroupState:
    """One group's replicated state. `members` maps member id → its
    subscribed topics; `assignment` maps member id → assigned
    (topic, partition) tuples, recomputed on every membership change
    under a bumped `generation` (the fencing epoch)."""

    name: str
    generation: int = 0
    members: dict[str, tuple[str, ...]] = dataclasses.field(
        default_factory=dict
    )
    assignment: dict[str, tuple[GroupKey, ...]] = dataclasses.field(
        default_factory=dict
    )

    def owner_of(self, key: GroupKey) -> Optional[str]:
        for member, keys in self.assignment.items():
            if key in keys:
                return member
        return None

    def to_wire(self) -> dict:
        return {
            "name": self.name,
            "generation": self.generation,
            "members": {m: list(ts) for m, ts in self.members.items()},
            "assignment": {
                m: [[t, p] for t, p in keys]
                for m, keys in self.assignment.items()
            },
        }

    @staticmethod
    def from_wire(d: dict) -> "GroupState":
        return GroupState(
            name=str(d["name"]),
            generation=int(d["generation"]),
            members={
                str(m): tuple(str(t) for t in ts)
                for m, ts in d.get("members", {}).items()
            },
            assignment={
                str(m): tuple((str(t), int(p)) for t, p in keys)
                for m, keys in d.get("assignment", {}).items()
            },
        )


def compute_assignment(
    members: dict[str, tuple[str, ...]],
    topic_partitions: dict[str, int],
    previous: Optional[dict[str, tuple[GroupKey, ...]]] = None,
) -> dict[str, tuple[GroupKey, ...]]:
    """Deterministic STICKY assignment: per topic, partitions spread
    evenly over the subscribing members (sorted by id), and a partition
    stays with its previous owner whenever that owner is still
    subscribed and under its even-split quota — the cooperative half of
    a rebalance (membership churn moves the minimum number of
    partitions, so an N-member storm does not reshuffle the world on
    every join/leave). Pure function of its arguments: every broker's
    metadata apply computes the identical map."""
    previous = previous or {}
    out: dict[str, list[GroupKey]] = {m: [] for m in members}
    for topic in sorted(topic_partitions):
        subs = sorted(m for m, ts in members.items() if topic in ts)
        if not subs:
            continue
        nparts = topic_partitions[topic]
        base, extra = divmod(nparts, len(subs))
        # Even-split quota per member for THIS topic: the first `extra`
        # members (sorted order) take one more.
        quota = {m: base + (1 if i < extra else 0)
                 for i, m in enumerate(subs)}
        taken: dict[str, int] = {m: 0 for m in subs}
        assigned: dict[GroupKey, str] = {}
        # Sticky pass: keep previous owners under quota.
        prev_owner = {
            key: m
            for m, keys in previous.items()
            for key in keys
            if key[0] == topic
        }
        for pid in range(nparts):
            key = (topic, pid)
            owner = prev_owner.get(key)
            if owner in quota and taken[owner] < quota[owner]:
                assigned[key] = owner
                taken[owner] += 1
        # Fill pass: orphaned partitions go to members under quota, in
        # sorted order (deterministic).
        for pid in range(nparts):
            key = (topic, pid)
            if key in assigned:
                continue
            for m in subs:
                if taken[m] < quota[m]:
                    assigned[key] = m
                    taken[m] += 1
                    break
        for key, m in assigned.items():
            out[m].append(key)
    return {m: tuple(sorted(keys)) for m, keys in out.items()}
