"""Replicated consumer-group state + the deterministic assignment rule.

Everything here is applied inside the metadata Raft's state machine
(broker/manager.py), so it must be a PURE function of replicated inputs:
the member set (with subscriptions), the static topic table, and the
previous assignment. Every broker's apply computes the identical
assignment for the identical generation — there is no separate
"assignment proposal" round trip, and a member learns its partitions
from any broker's replicated view (join response / heartbeat).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ripplemq_tpu.metadata.models import GroupKey


def group_consumer_name(group: str) -> str:
    """The group's SHARED offset-tracking consumer name: all members
    commit under it, so a partition moving between members resumes from
    the group's last acked commit (one engine consumer slot per group,
    not per member)."""
    return f"g/{group}"


@dataclasses.dataclass
class GroupState:
    """One group's replicated state. `members` maps member id → its
    subscribed topics; `assignment` maps member id → assigned
    (topic, partition) tuples, recomputed on every membership change
    under a bumped `generation` (the fencing epoch)."""

    name: str
    generation: int = 0
    members: dict[str, tuple[str, ...]] = dataclasses.field(
        default_factory=dict
    )
    assignment: dict[str, tuple[GroupKey, ...]] = dataclasses.field(
        default_factory=dict
    )

    def owner_of(self, key: GroupKey) -> Optional[str]:
        for member, keys in self.assignment.items():
            if key in keys:
                return member
        return None

    def to_wire(self) -> dict:
        return {
            "name": self.name,
            "generation": self.generation,
            "members": {m: list(ts) for m, ts in self.members.items()},
            "assignment": {
                m: [[t, p] for t, p in keys]
                for m, keys in self.assignment.items()
            },
        }

    @staticmethod
    def from_wire(d: dict) -> "GroupState":
        return GroupState(
            name=str(d["name"]),
            generation=int(d["generation"]),
            members={
                str(m): tuple(str(t) for t in ts)
                for m, ts in d.get("members", {}).items()
            },
            assignment={
                str(m): tuple((str(t), int(p)) for t, p in keys)
                for m, keys in d.get("assignment", {}).items()
            },
        )


def _topic_quota(subs: list[str], nparts: int) -> dict[str, int]:
    """Even-split quota per member for one topic: the first `extra`
    members (sorted order) take one more."""
    base, extra = divmod(nparts, len(subs))
    return {m: base + (1 if i < extra else 0) for i, m in enumerate(subs)}


def _assign_topic(subs: list[str], nparts: int,
                  prev_owner: dict[GroupKey, str],
                  topic: str) -> dict[GroupKey, str]:
    """One topic's sticky rule: previous owners keep their partitions
    while still subscribed and under quota; orphans fill to members
    under quota in sorted order. Deterministic in its arguments."""
    quota = _topic_quota(subs, nparts)
    taken: dict[str, int] = {m: 0 for m in subs}
    assigned: dict[GroupKey, str] = {}
    # Sticky pass: keep previous owners under quota.
    for pid in range(nparts):
        key = (topic, pid)
        owner = prev_owner.get(key)
        if owner in quota and taken[owner] < quota[owner]:
            assigned[key] = owner
            taken[owner] += 1
    # Fill pass: orphaned partitions go to members under quota, in
    # sorted order (deterministic).
    for pid in range(nparts):
        key = (topic, pid)
        if key in assigned:
            continue
        for m in subs:
            if taken[m] < quota[m]:
                assigned[key] = m
                taken[m] += 1
                break
    return assigned


def compute_assignment(
    members: dict[str, tuple[str, ...]],
    topic_partitions: dict[str, int],
    previous: Optional[dict[str, tuple[GroupKey, ...]]] = None,
) -> dict[str, tuple[GroupKey, ...]]:
    """Deterministic STICKY assignment: per topic, partitions spread
    evenly over the subscribing members (sorted by id), and a partition
    stays with its previous owner whenever that owner is still
    subscribed and under its even-split quota — the cooperative half of
    a rebalance (membership churn moves the minimum number of
    partitions, so an N-member storm does not reshuffle the world on
    every join/leave). Pure function of its arguments: every broker's
    metadata apply computes the identical map."""
    previous = previous or {}
    out: dict[str, list[GroupKey]] = {m: [] for m in members}
    for topic in sorted(topic_partitions):
        subs = sorted(m for m, ts in members.items() if topic in ts)
        if not subs:
            continue
        prev_owner = {
            key: m
            for m, keys in previous.items()
            for key in keys
            if key[0] == topic
        }
        assigned = _assign_topic(subs, topic_partitions[topic],
                                 prev_owner, topic)
        for key, m in assigned.items():
            out[m].append(key)
    return {m: tuple(sorted(keys)) for m, keys in out.items()}


def compute_assignment_delta(
    members: dict[str, tuple[str, ...]],
    topic_partitions: dict[str, int],
    previous: Optional[dict[str, tuple[GroupKey, ...]]],
    prev_members: dict[str, tuple[str, ...]],
    changed: set[str],
) -> dict[str, tuple[GroupKey, ...]]:
    """Incremental sticky assignment for a wave that touched only the
    members in `changed` (joined, left, or re-subscribed between
    `prev_members` and `members`). Topics no changed member subscribes
    to — now or before — keep their previous per-topic slice VERBATIM:
    the per-topic rule is a fixpoint on an unchanged subscriber set
    (every owner sits exactly at quota, so the sticky pass keeps
    everything and the fill pass is empty), so recomputing would return
    the same bytes. Affected topics rerun the full per-topic rule,
    which moves only the minimum member set by stickiness. Falls back
    to the full rule per topic whenever the fast path's preconditions
    fail (partition count changed under a split/merge, or the previous
    slice is not a quota-exact cover). Output is IDENTICAL to
    `compute_assignment(members, topic_partitions, previous)` — the
    directed equivalence test in tests/test_group_waves.py holds this
    over randomized churn."""
    previous = previous or {}
    affected: set[str] = set()
    for m in changed:
        affected.update(prev_members.get(m, ()))
        affected.update(members.get(m, ()))
    out: dict[str, list[GroupKey]] = {m: [] for m in members}
    for topic in sorted(topic_partitions):
        subs = sorted(m for m, ts in members.items() if topic in ts)
        nparts = topic_partitions[topic]
        prev_slice = [
            (m, key)
            for m, keys in previous.items()
            for key in keys
            if key[0] == topic
        ]
        if topic not in affected and subs:
            # Fast path: reuse the previous slice if it is a
            # quota-exact cover of [0, nparts) owned by current subs —
            # exactly the states the full rule emits, on which it is
            # idempotent.
            quota = _topic_quota(subs, nparts)
            counts: dict[str, int] = {m: 0 for m in subs}
            pids = []
            valid = True
            for m, key in prev_slice:
                if m not in counts:
                    valid = False
                    break
                counts[m] += 1
                pids.append(key[1])
            if valid and sorted(pids) == list(range(nparts)) \
                    and counts == quota:
                for m, key in prev_slice:
                    out[m].append(key)
                continue
        if not subs:
            continue
        prev_owner = {key: m for m, key in prev_slice}
        assigned = _assign_topic(subs, nparts, prev_owner, topic)
        for key, m in assigned.items():
            out[m].append(key)
    return {m: tuple(sorted(keys)) for m, keys in out.items()}
