"""Consumer groups: membership, cooperative assignment, generation
fencing — the "partition assignment" half of the reference's second
advertised service (PAPER.md; the first half, offset management, has
been per-consumer since the seed).

Layout:
- `state.py` — replicated group state (GroupState) and the
  deterministic sticky assignment function every broker's apply runs.
- `coordinator.py` — GroupTable (the metadata state machine's group
  section) and GroupLiveness (the metadata leader's volatile heartbeat
  ledger driving evictions).
- `client.py` — GroupConsumer, the member-side SDK: join/poll/
  heartbeat/commit-with-fencing/leave over both transports.

Offsets are tracked per GROUP, not per member: every member commits
under the group's shared consumer name (`group_consumer_name`), so a
partition moving between members resumes from the group's last acked
commit. Generation fencing keeps that sound: a commit stamped with a
stale generation — a deposed member racing its own rebalance — is a
typed `fenced_generation` refusal, never a silent overwrite.
"""

from ripplemq_tpu.groups.coordinator import GroupLiveness, GroupTable
from ripplemq_tpu.groups.state import (
    GroupState,
    compute_assignment,
    group_consumer_name,
)

__all__ = [
    "FencedError",
    "GroupConsumer",
    "GroupLiveness",
    "GroupState",
    "GroupTable",
    "compute_assignment",
    "group_consumer_name",
]


def __getattr__(name):
    # GroupConsumer/FencedError import the client SDK, which imports
    # this package's state module in turn — resolved lazily so broker-
    # side imports (manager → coordinator) never drag the client stack
    # in (and never cycle through ripplemq_tpu.client's re-export).
    if name in ("GroupConsumer", "FencedError"):
        from ripplemq_tpu.groups import client

        return getattr(client, name)
    raise AttributeError(name)
