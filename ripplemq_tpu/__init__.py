"""ripplemq_tpu — a TPU-native distributed message queue framework.

A ground-up JAX/XLA/Pallas re-design of the capability surface of the
reference RippleMQ system (a Kafka-style queue with two tiers of Raft:
a cluster metadata group and one Raft group per topic-partition).

Architecture (TPU-first, not a translation):

- **Data plane** (`ripplemq_tpu.core`, `ripplemq_tpu.parallel`): all
  partitions of all topics live in ONE SPMD tensor program. Partitions are
  a vmapped leading axis; replicas are a `jax.sharding.Mesh` axis; an
  AppendEntries round is a jitted step function; quorum commit is a
  `lax.psum` of acks over the replica axis. This replaces the reference's
  object-per-partition JRaft groups (reference:
  mq-broker/src/main/java/metadata/raft/PartitionRaftServer.java).

- **Metadata plane** (`ripplemq_tpu.broker.hostraft`): a deterministic,
  tick-driven Raft on the host for the low-rate replicated topic/assignment
  table (reference: metadata/raft/TopicsRaftServer.java +
  TopicsStateMachine.java).

- **Host runtime** (`ripplemq_tpu.broker`): request server, append
  batcher, device-step driver loop, membership monitor, sticky
  least-loaded partition assigner.

- **Client SDK** (`ripplemq_tpu.client`): ProducerClient / ConsumerClient
  with cached metadata, round-robin partition selection and
  auto-commit-after-read semantics (reference: mq-common client/).

- **Kernels** (`ripplemq_tpu.ops`): GF(2^8) matmul Pallas kernel for
  Reed-Solomon erasure coding of sealed log segments.
"""

__version__ = "0.1.0"

__all__ = [
    "EngineConfig",
    "ReplicaState",
    "StepInput",
    "StepOutput",
    "build_step_input",
    "decode_entries",
    "init_state",
]


def __getattr__(name):
    # Lazy re-exports (PEP 562): importing the package must not pull
    # jax. The multi-core host plane SPAWNS worker subprocesses whose
    # import chain runs through this module — an eager `from
    # ripplemq_tpu.core import ...` charged every worker boot (and
    # every client-only import) the full ~4 s jax initialization for
    # symbols the worker never touches.
    if name in __all__:
        from ripplemq_tpu import core

        return getattr(core, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
