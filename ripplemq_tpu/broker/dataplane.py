"""DataPlane: the device-round driver + append batcher on the controller.

This is the host component that turns many small producer requests into
few large device rounds — the exact inversion of the reference's hot
path, where every message is its own Raft task and RPC
(reference: mq-common/.../PartitionClient.java:39 one message per RPC;
MessageAppendRequestProcessor.java:59 one Raft task per request). Batching
is where the TPU wins or loses (SURVEY.md §7 "hard parts": host↔device
overhead vs tiny appends).

One DataPlane owns: the engine state (all partitions × replicas), the
per-partition leader/term tables, the per-partition replica liveness
mask, pending-append/offset queues, and the step thread that drains them.
All device interaction happens on the step thread or under its lock —
`step` donates its input state, so a concurrent read against the old
buffer would be use-after-donate.

Elections ride the same device: `elect()` batches RequestVote rounds for
many partitions into ONE vote_step call (the reference runs an
independent JRaft ballot per group).
"""

from __future__ import annotations

import bisect
import queue
import threading
from concurrent.futures import Future
from typing import Optional

import numpy as np

import struct
import time

from ripplemq_tpu.core.config import ALIGN, ROW_HEADER as _HDR, EngineConfig
from ripplemq_tpu.obs.lockwitness import make_condition, make_lock
from ripplemq_tpu.core.encode import (
    decode_entries_with_pos,
    pack_payload_rows,
    row_extents,
    stamp_term,
)
from ripplemq_tpu.core.state import ReplicaState, StepInput, row_lens
from ripplemq_tpu.parallel.engine import make_local_fns, make_spmd_fns
from ripplemq_tpu.parallel.mesh import make_mesh
from ripplemq_tpu.storage.segment import (
    REC_APPEND,
    REC_OFFSETS,
    REC_PIDSEQ,
    SegmentStore,
    scan_store,
)
from ripplemq_tpu.utils.logs import get_logger

log = get_logger("dataplane")


class NotCommittedError(Exception):
    """The round(s) carrying this request never reached quorum."""


class StoreReadRaceError(NotCommittedError):
    """A store read kept colliding with concurrent segment GC. Transient:
    the records exist (or existed); retry rather than treating the window
    as absent — absence triggers an earliest-reset that would silently
    skip retained rows. Subclasses NotCommittedError so the broker's
    dispatch surfaces it as a retryable `not_committed` refusal, not an
    internal error."""


class PartitionFullError(NotCommittedError):
    """The partition's log has no room for the batch (backpressure).

    Only reachable in store-less (pure in-memory) deployments: with a
    round store attached, the device ring recycles rows below the trim
    watermark (everything committed is already persisted — the store is
    the log of record) and appends never wedge; lagging consumers are
    served from the store via the log index."""


def _fetch_global(x) -> np.ndarray:
    """np.asarray that also works for arrays sharded across PROCESSES
    (multi-host spmd mode): a device-local shard set can't materialize
    the full value, so gather it through the coordination service. Step/
    vote/read outputs never need this — the engine replicates them onto
    every device (parallel.engine._gather_part); only raw state fetches
    (log ends, terms, commit) do."""
    if getattr(x, "is_fully_addressable", True) or getattr(
        x, "is_fully_replicated", False
    ):
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


# Device offsets (log_end/commit/trim) are int32 — the TPU-native scalar
# width (int64 is emulated). A partition appending past 2^31 rows would
# wrap negative and silently corrupt capacity/commit/read arithmetic, so
# submits are refused with a clean error well before the edge (at
# slot_bytes=128 the horizon is 256 GiB through ONE partition; spread
# load over more partitions to go past it).
_OFFSET_HORIZON = (1 << 31) - (1 << 20)

# Sentinel: a host-cache read lost the trim race mid-copy (see
# DataPlane._read_cache).
_CACHE_LAPPED = object()
# Distinct from the dirty-shadow None: the offset sits in a MIRROR-GAP
# window (resolve failure disabled the cache for the slot), where the
# rows are settled — hence persisted and log-indexed — and the store can
# serve them without a device dispatch (see read()'s gap-generation
# probe discipline).
_CACHE_GAP = object()

# Settled batches remembered per (pid, slot) for producer-sequence
# dedup. The producer only ever replays sequences it never saw acked —
# at most one batch deep per partition under the SDK's ack-gated
# sequence advance — so a small window covers every legal replay;
# anything older still refuses to re-append (acked as a duplicate with
# base -1: present in the log, position no longer remembered).
_PID_WINDOW = 8


class _Pending:
    __slots__ = ("payloads", "rows", "future", "rounds_left", "pid", "seq",
                 "tctx")

    def __init__(self, payloads: list[bytes], future: Future,
                 rounds_left: int, rows=None, pid: int = 0, seq: int = -1,
                 tctx=None):
        self.payloads = payloads
        # Appends carry their rows PRE-PACKED (pack_payload_rows on the
        # submitting thread); the drain only memcpys blocks and stamps
        # the round term — per-message packing inside the batcher lock
        # serialized the whole plane under deep backlogs.
        self.rows = rows
        self.future = future
        self.rounds_left = rounds_left
        # Idempotent-producer identity: pid > 0 marks this batch as
        # dedup-tracked — (pid, seq) survives requeues, so a retried
        # round re-appends under the SAME identity.
        self.pid = pid
        self.seq = seq
        # Causal-tracing context (obs/spans.py TraceContext) of a
        # SAMPLED produce, else None: the settle release emits the six
        # round-stage spans attributed to it.
        self.tctx = tctx


class _PendingOffsets(_Pending):
    pass


class DataPlane:
    """See module docstring.

    `mode` is "local" (replicas vmapped on one device — single-chip) or
    "spmd" (replica × part device mesh). Semantics are identical; tests
    assert it (tests/test_spmd.py).
    """

    def __init__(
        self,
        cfg: EngineConfig,
        mode: str = "local",
        mesh=None,
        part_shards: Optional[int] = None,
        max_retry_rounds: int = 8,
        store: Optional[SegmentStore] = None,
        flush_interval_s: float = 0.05,
        pipeline_depth: int = 8,
        coalesce_s: float = 0.002,
        replicate_fn=None,
        workers: Optional[list[str]] = None,
        worker_client=None,
        resolver_threads: int = 4,
        chain_depth: int = 4,
        read_q: int = 16,
        host_read_cache: bool = True,
        settle_window: Optional[int] = None,
        read_coalesce_s: float = 0.001,
        durability: str = "async",
        obs: bool = True,
        metrics=None,
        recorder=None,
        spans=None,
    ) -> None:
        self.cfg = cfg
        # --- telemetry plane (obs/) ---------------------------------------
        # `metrics`/`recorder` are normally the OWNING BrokerServer's (one
        # registry + one flight-recorder ring per broker, wired through at
        # boot); a bare plane (tests, benches) builds its own. `obs=False`
        # swaps in no-op metrics — the A/B knob — while the flight
        # recorder stays on (its per-ROUND cost is a few hundred ns and
        # its whole value is being on when nobody expected to need it).
        from ripplemq_tpu.obs.metrics import Metrics
        from ripplemq_tpu.obs.trace import FlightRecorder

        self.metrics = metrics if metrics is not None else Metrics(enabled=obs)
        self.recorder = recorder if recorder is not None else FlightRecorder()
        # Causal-tracing span ring (obs/spans.py), normally the owning
        # broker's — and only handed over when tracing is CONFIGURED
        # (trace_sample_n > 0): `spans is None` gates every per-round
        # tctx scan below to zero when the plane is untraced.
        self.spans = spans
        m = self.metrics
        # Hot-path metric handles resolved ONCE (registry lookups lock).
        self._m_submits = m.counter("produce.submits")
        self._m_messages = m.counter("produce.messages")
        self._m_offsets = m.counter("produce.offset_commits")
        self._m_dispatch_us = m.histogram("engine.dispatch_us")
        self._m_chain_rounds = m.histogram("engine.chain_rounds")
        self._m_commit_wait_us = m.histogram("settle.commit_wait_us")
        self._m_enter_wait_us = m.histogram("settle.enter_wait_us")
        self._m_standby_ack_us = m.histogram("settle.standby_ack_us")
        self._m_persist_us = m.histogram("settle.persist_us")
        self._m_release_us = m.histogram("settle.release_us")
        self._m_retries = m.counter("produce.round_retries")
        self._m_retry_exhausted = m.counter("produce.retry_exhausted")
        self._m_read_calls = m.counter("read.calls")
        self._m_read_msgs = m.counter("read.messages")
        # Durability mode for the settle-path persist: "async" defers
        # fsync to the store's flusher thread at flush_interval_s cadence
        # (disk lags acks by at most one interval — the PR 3 contract);
        # "strict" fsyncs synchronously before every settled round's acks
        # release, so acked data never lags disk at all (the standby ack
        # path honors the same knob in broker/server._handle_repl_rounds).
        if durability not in ("async", "strict"):
            raise ValueError(
                f"durability must be 'async' or 'strict', got {durability!r}"
            )
        self.durability = durability
        # Durability tier: committed rounds are framed into the segment
        # store from the step thread; fsync happens at most every
        # `flush_interval_s` (0 = every round). "Committed" therefore
        # means quorum-replicated on the mesh; durable-on-disk lags by at
        # most the flush interval (SURVEY.md §7 durability story).
        self.store = store
        self.flush_interval_s = flush_interval_s
        self._last_flush = 0.0
        # Retention (see core.state ring doc): `trim[p]` is the absolute
        # watermark below which device ring rows are reclaimable — raised
        # lazily by _drain when a partition needs room, never above the
        # persisted prefix. `_log_end[p]` is the host's shadow of the
        # leader's absolute log end (exact while the slot is not busy:
        # one in-flight round per slot, advanced at resolve time).
        # `log_index` maps (slot, offset) → store record so reads below
        # trim are served from the store (storage/logindex.py).
        P0 = cfg.partitions
        self.trim = np.zeros((P0,), np.int64)
        self._log_end = np.zeros((P0,), np.int64)
        # Read-visibility horizon: rows below this are DURABLY SETTLED
        # (device-committed + persisted + standby-acked). Device-ring
        # reads clamp to it — device commit alone includes rounds whose
        # replication later failed, and serving those leaks state that a
        # controller failover rolls back (see _resolve_one).
        self._settled_end = np.zeros((P0,), np.int64)
        # Host mirror of the committed device ring: every committed
        # round's rows pass through this host (the resolver holds them
        # to persist/replicate), so hot reads — above the trim
        # watermark — can be served from host RAM with ZERO device
        # involvement (the reference serves a consume as a leader-local
        # list slice, PartitionStateMachine.java:85-110; behind a
        # network tunnel a device read dispatch costs a full RTT).
        # `_cache_end[p]` is the CONTIGUOUS mirrored prefix: it only
        # advances when a round lands adjacent to it, so a resolve
        # failure (round outcome unknown, rows never mirrored) leaves a
        # gap that reads fall through to the device for, instead of
        # serving stale rows. Memory = partitions x slots x slot_bytes
        # (1/replicas of the device state); zero pages until written.
        self._host_ring = (
            np.zeros((P0, cfg.slots, cfg.slot_bytes), np.uint8)
            if host_read_cache else None
        )
        self._cache_end = np.zeros((P0,), np.int64)
        # Post-gap mirrored run per slot: after a resolve failure leaves
        # a mirror gap, later rounds still write their rows physically —
        # only `_cache_end` stops advancing. `slot → [run_base, run_end]`
        # tracks that contiguous post-gap run so the cache can HEAL once
        # the trim watermark passes run_base (everything unmirrored below
        # it is then store-served and never consults the mirror), rather
        # than staying disabled for the slot's lifetime.
        self._mirror_gap: dict[int, list[int]] = {}
        # Monotone per-slot gap GENERATION: bumped each time a fresh
        # mirror gap opens. The read path device-probes a gap window
        # once per generation (the probe validates the window against
        # the device commit bound) and then serves the store path
        # directly for the rest of that gap's lifetime — settled rows
        # are always persisted+indexed before they are mirrored
        # (_release_one order), so the store is a valid authority
        # inside the gap and the per-call device round-trip was pure
        # overhead.
        self._mirror_gap_gen: dict[int, int] = {}
        self._gap_probed_gen: dict[int, int] = {}
        # Per-slot SETTLED GAPS (the mirror-gap analogue for the read
        # horizon): sorted disjoint [begin, end) absolute row ranges that
        # are device-committed but whose standby replication FAILED —
        # nacked to their producers, so they must stay invisible even
        # after the slot settles NEWER rounds and `_settled_end` passes
        # them. Every read path (device ring, host mirror, store) skips
        # these ranges; promotion/boot replay rebuilds them from the
        # recovered store's coverage holes (replay_records gaps_out).
        # Ranges are never re-covered within a controller lifetime
        # (bases only advance), so entries are permanent until the next
        # install(); memory is two ints per failed round.
        self._settled_gaps: dict[int, list[list[int]]] = {}
        # Persisted prefix per partition: rows below this are in the
        # ROUND STORE (appended; flush may lag by flush_interval_s).
        # Advanced by _persist_round only after the store append
        # succeeded — NOT by the shadow-dirty device re-derivation,
        # which can cover committed-but-never-persisted rounds after a
        # persist failure. The drain-time trim raise clamps against
        # THIS, so everything below trim is always store-servable.
        self._persisted = np.zeros((P0,), np.int64)
        self.log_index = None
        self._scan_index = None  # lazy full-history index (_scan_store_for)
        if store is not None and hasattr(store, "scan_indexed"):
            from ripplemq_tpu.storage.logindex import LogIndex

            self.log_index = LogIndex()
            self.log_index.load(store.scan_indexed(), cfg.slot_bytes,
                                REC_APPEND)
        # Controller-failover hook: called with each round's committed
        # records BEFORE local persistence and BEFORE settling futures —
        # the resolver blocks until the standby set acked, so a settled
        # append provably exists on every replication standby (zero
        # committed-entry loss across controller death; see
        # broker/replication.py), and the local store only ever holds
        # standby-acked records (a crash between the two steps must not
        # leave a record recovery would serve but promotion would
        # forget — see _resolve_one). Raising fails the round's futures
        # (FencedError ⊂ NotCommittedError → producers retry at the new
        # controller).
        self.replicate_fn = replicate_fn
        # Host-plane settled-mirror hook (parallel/hostplane.py): when
        # the broker runs worker subprocesses, the settle thread
        # publishes each durably-settled round's REC_APPEND rows to the
        # owning worker so consume reads for that slice are served off
        # this process's GIL. Fire-and-forget BY CONTRACT — the hook
        # must never block settle (HostPlane.publish drops on a full
        # ring; the worker's contiguity check turns drops into clean
        # engine-read fallbacks).
        self.mirror_fn = None
        # Pipelined-settle split of replicate_fn (RoundReplicator.begin/
        # wait): `begin` enqueues a round's records on every standby
        # stream without blocking; `wait` blocks until all member acks.
        # When set (the broker wires them beside replicate_fn), a window
        # of up to `settle_window` rounds streams to the standbys while
        # the device advances; acks still release strictly in round
        # order (see _settle_loop). When only replicate_fn is set (tests,
        # custom replicators), the settle thread calls it synchronously —
        # same in-order release, no standby-stream overlap.
        self.replicate_begin_fn = None
        self.replicate_wait_fn = None
        if mode == "local":
            self.fns = make_local_fns(cfg)
        elif mode == "spmd":
            if mesh is None:
                if part_shards is None:
                    # Auto: use every device (local chips, or the GLOBAL
                    # device list under jax.distributed).
                    import jax

                    part_shards = max(1, len(jax.devices()) // cfg.replicas)
                    while cfg.partitions % part_shards:
                        part_shards -= 1  # partitions must tile evenly
                mesh = make_mesh(cfg.replicas, part_shards)
            else:
                part_shards = mesh.shape["part"]
            self.fns = make_spmd_fns(cfg, mesh)
            if workers:
                # Multi-host: broadcast every engine call to the engine
                # workers on the other hosts (parallel.lockstep) so the
                # whole mesh launches each computation.
                from ripplemq_tpu.parallel.lockstep import LockstepController
                from ripplemq_tpu.wire.transport import TcpClient

                self.fns = LockstepController(
                    self.fns, cfg, part_shards, workers,
                    worker_client if worker_client is not None else TcpClient(),
                )
        else:
            raise ValueError(f"unknown mode {mode!r}")
        self.max_retry_rounds = max_retry_rounds

        P, R = cfg.partitions, cfg.replicas
        self._state = self.fns.init()
        self.leader = np.full((P,), -1, np.int32)
        self.term = np.zeros((P,), np.int32)
        self.alive = np.ones((P, R), bool)
        self.quorum = np.full((P,), cfg.quorum, np.int32)
        self._refresh_quorum_ok_locked()  # pre-start: no lock needed yet

        self._appends: dict[int, list[_Pending]] = {}
        self._offsets: dict[int, list[_PendingOffsets]] = {}
        # Idempotent-producer dedup state (guarded by self._lock).
        # `_pid_tab`: (pid, slot) → recent SETTLED batches as
        # (seq_start, seq_end, base), newest last, capped at _PID_WINDOW —
        # a replayed sequence is acked as a duplicate with its original
        # base instead of appending again. Entries are written into the
        # replicated record stream (REC_PIDSEQ, beside each round's
        # REC_APPEND) and rebuilt by boot/promotion replay, so a
        # controller failover cannot re-open the dup window: every acked
        # round is on every standby, and its pid entry rides the same
        # records. `_pid_inflight`: (pid, slot, seq) → the Future of a
        # batch whose round has not settled yet — a concurrent wire-dup
        # of the same request attaches to the SAME future (one append,
        # two identical acks).
        self._pid_tab: dict[tuple[int, int], list[tuple[int, int, int]]] = {}
        self._pid_inflight: dict[tuple[int, int, int], Future] = {}
        # Consecutive device-uncommitted rounds per slot (reset on any
        # committed round, and on set_leader — a fresh term is a fresh
        # chance). A long streak with a LIVE leader is the signature of
        # the device-term-skew wedge the chaos plane caught (seed 7): an
        # election bumped the device current_term but its OP_SET_LEADER
        # advert never stuck, so every round dispatches with a stale
        # term and is refused forever while the metadata plane sees a
        # healthy leader and never re-elects. stalled_slots() feeds the
        # controller duty's needs_elections gate so exactly that state
        # self-heals by re-election instead of wedging the partition.
        self._nocommit_streak: dict[int, int] = {}
        # Locks ride the witness factories (obs/lockwitness.py): raw
        # threading primitives unless the runtime lock witness is
        # enabled, in which case acquisition orderings are recorded
        # under these names and cross-checked against the static graph
        # (analysis/lock_graph.py) by the chaos smokes.
        self._lock = make_lock("DataPlane._lock")  # queues + ctrl tables
        self._device_lock = make_lock(
            "DataPlane._device_lock")          # every touch of self._state
        self._work = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="dataplane-step"
        )
        # Two-stage round pipeline: the STEP thread only drains queues and
        # dispatches device rounds; RESOLVER threads block on each
        # round's `committed` host fetch, persist it, and settle its
        # futures. Several resolvers run CONCURRENTLY — sound because the
        # busy sets guarantee in-flight rounds touch disjoint partition
        # slots (per-slot ordering is the only ordering the settle path
        # needs, and the store/replication streams only require per-slot
        # record order — replay is per-slot later-wins). Concurrency
        # matters when the chip sits behind a network tunnel: each host
        # fetch costs a full ~70 ms RTT even for already-computed values,
        # and serial resolves would cap round throughput at 1/RTT. The
        # round's `base` is NOT fetched at all: it is the drain-time
        # log-end shadow (exact — one in-flight round per slot, and
        # log_end only moves on commit), captured in the round ctx. The
        # bounded queue backpressures dispatch at `pipeline_depth`
        # outstanding rounds.
        self.pipeline_depth = max(1, pipeline_depth)
        self.resolver_threads = max(1, resolver_threads)
        # Deep backlogs drain as CHAINS of up to chain_depth rounds per
        # device dispatch (engine step_many: lax.scan over complete
        # quorum rounds). Dispatch latency and the resolver's host fetch
        # both amortize over the chain; a chain may take several pendings
        # of one slot (device-ordered). 1 disables chaining.
        self.chain_depth = max(1, chain_depth)
        self._zero_round = None  # lazy pad template (chain dispatches)
        # Entries placeholder ([P, 1, 1], see _dummy_entries): built
        # EAGERLY — the lazy build was reachable from the step, warm,
        # and duty threads with no common lock (the ownership lint's
        # first whole-tree run flagged it; benign-idempotent, but a
        # pre-spawn constant costs P bytes and zero reasoning).
        self._dummy = np.zeros((cfg.partitions, 1, 1), np.uint8)
        # Read coalescer: device reads queue here and drain as ONE
        # read_many dispatch of up to read_q queries — the consume-side
        # mirror of append batching. No artificial wait: while one batch
        # executes (serialized by _device_lock), concurrent readers
        # accumulate into the next, so batching emerges exactly when the
        # dispatch cost would otherwise multiply.
        self.read_q = max(1, read_q)
        # Tiny assembly window before each read dispatch: consumers whose
        # previous read just resolved need ~a millisecond to decode and
        # resubmit; draining the instant the first request lands would
        # phase-lock the cohort into half-filled batches (measured: 8/16
        # consumers per dispatch without it). Negligible vs the dispatch
        # RTT it amortizes. Constructor/config-surfaced like coalesce_s
        # (ClusterConfig.read_coalesce_s); 0 disables.
        self.read_coalesce_s = max(0.0, read_coalesce_s)
        self._reads: list[tuple[int, int, int, Future]] = []
        self._read_lock = make_lock("DataPlane._read_lock")
        self._read_work = threading.Event()
        self._read_thread = threading.Thread(
            target=self._read_loop, daemon=True, name="dataplane-read"
        )
        # Host shadow of the replicated consumer-offset table: offset
        # commits pass through this host (rounds), so the committed table
        # is reproducible without a device fetch — read_offset serves
        # from here, halving the device round-trips per consume.
        self._offsets_shadow = np.zeros(
            (cfg.partitions, cfg.max_consumers), np.int32
        )
        # Coalescing window: when few submissions are pending, wait this
        # long before dispatching so a whole burst of concurrent
        # producers lands in ONE round — every round costs a full
        # host↔device sync to resolve, which dwarfs the window (~100 ms
        # behind a tunnel, ~1 ms attached). 0 disables.
        self.coalesce_s = coalesce_s
        self._inflight: "queue.Queue[tuple[StepInput, dict, object]]" = (
            queue.Queue(maxsize=self.pipeline_depth)
        )
        self._resolvers = [
            threading.Thread(
                target=self._resolve_loop, daemon=True,
                name=f"dataplane-resolve-{i}",
            )
            for i in range(self.resolver_threads)
        ]
        # --- settle pipeline (third stage) -------------------------------
        # Resolvers no longer block on standby replication: each resolved
        # dispatch enters a bounded settle window — its records already
        # streaming to the standbys (replicate_begin_fn) — and ONE settle
        # thread waits out the acks strictly in dispatch order before
        # persisting, mirroring, advancing the settled-read horizon, and
        # releasing producer futures. Ordering invariants this preserves
        # verbatim: per-slot standby-stream record order (begin happens
        # inside the dispatch-order turnstile), settle-gated reads
        # (_settled_end moves only here, in order), ack-only-after-all-
        # member-acks (replicate_wait_fn runs the full waiver/fence
        # discipline), and the empty-set refusal (begin raises it). The
        # window backpressures resolvers when full; a FencedError latches
        # `_settle_fenced` and DRAINS the window without acking any
        # unsettled round (a deposed controller's pre-received standby
        # acks prove nothing against the successor's history).
        self.settle_window = max(
            1, cfg.settle_window if settle_window is None
            else int(settle_window)
        )
        # The window bound is the SEMAPHORE (held from replication begin
        # until release completes), not the queue: a bounded queue alone
        # would let one extra round begin streaming while blocked on the
        # put, making settle_window=1 overlap instead of serialize.
        self._settle_q: "queue.Queue[tuple]" = queue.Queue()
        self._settle_sem = threading.Semaphore(self.settle_window)
        self._settle_thread = threading.Thread(
            target=self._settle_loop, daemon=True, name="dataplane-settle"
        )
        self._settle_fenced = False
        # Dispatch-order turnstile: resolvers run concurrently, but
        # settle-pipeline entry (and the replication begin inside it)
        # must follow dispatch order or a slot's standby stream could
        # carry round k+1's records before round k's (standby replay is
        # later-record-wins per slot — a reordered stream would regress
        # its log end). Seqs are assigned by the step thread.
        self._dispatch_seq = 0
        self._next_turn = 0
        self._turnstile = make_condition("DataPlane._turnstile")
        # Occupancy counters (bench/admin surface): depth is sampled at
        # each settle enqueue; backpressure counts enqueues that found
        # the window full.
        self.settle_depth_sum = 0
        self.settle_samples = 0
        self.settle_backpressure = 0
        # Live settle-window occupancy (rounds between window entry and
        # release) and the SLO autopilot's soft-window bookkeeping: the
        # controller shrinks the effective window by holding
        # `_settle_held` semaphore permits (set_knobs), so the window
        # narrows without rebuilding the semaphore mid-flight. Both
        # guarded by self._lock.
        self._settle_inflight = 0
        self._settle_held = 0
        # Guarded by self._lock (read by _drain, cleared by the resolver).
        self._busy_a: set[int] = set()   # partition slots with appends in flight
        self._busy_o: set[int] = set()   # ... with offset commits in flight
        # Slots whose log-end shadow must be re-read from the device
        # before their next round (a resolve failed with the round's
        # outcome possibly unknown). Guarded by self._lock.
        self._shadow_dirty: set[int] = set()
        # Host-side counters (exposed through the broker's admin.stats
        # RPC). `rounds` counts quorum rounds; `dispatches` device
        # launches (rounds/dispatches = chaining factor); the read pair
        # measures the read coalescer's batching.
        self.rounds = 0
        self.dispatches = 0
        self.read_queries = 0
        self.read_dispatches = 0
        self.read_cache_hits = 0
        self.committed_entries = 0
        self.step_errors = 0

    def start(self) -> None:
        self._thread.start()
        self._read_thread.start()
        for r in self._resolvers:
            r.start()
        self._settle_thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._work.set()
        self._read_work.set()
        # A never-started plane (boot failed between construction and
        # start — server._boot_dataplane's cleanup path) must still run
        # the rest of stop (fail queued futures, flush): joining an
        # unstarted Thread raises, so join only what ran. The settle
        # thread joins LAST — it exits only once the resolvers are dead
        # and the window is drained.
        for t in (self._thread, self._read_thread, *self._resolvers,
                  self._settle_thread):
            if t.ident is not None:
                t.join(timeout=10)  # lands every dispatched round
        # Stranded settle entries (settle thread wedged past its join
        # timeout, or never started): fail their committed futures —
        # nothing will release them now.
        while True:
            try:
                ctx, committed, *_ = self._settle_q.get_nowait()
            except queue.Empty:
                break
            self._fail_committed(ctx, committed,
                                 NotCommittedError("data plane stopped"))
        with self._read_lock:
            stranded = self._reads
            self._reads = []
        for *_, fut in stranded:
            if not fut.done():
                fut.set_exception(NotCommittedError("data plane stopped"))
        if self.store is not None:
            self.store.flush()
        # Nothing will ever drain the queues again: fail leftovers instead
        # of letting their futures hang until caller timeouts (matters on
        # controller fencing, where the deposed data plane stops while
        # frontends still hold futures).
        with self._lock:
            leftovers = [p for q in self._appends.values() for p in q]
            leftovers += [p for q in self._offsets.values() for p in q]
            self._appends.clear()
            self._offsets.clear()
            self._pid_inflight.clear()  # plane dead: nothing will settle
        for p in leftovers:
            if not p.future.done():
                p.future.set_exception(
                    NotCommittedError("data plane stopped")
                )

    # ------------------------------------------------------------- control

    def set_leader(self, slot: int, leader_slot: int, term: int) -> None:
        """Record partition `slot`'s leader replica-slot + term (host
        election outcome; fed into every round's StepInput)."""
        with self._lock:
            self.leader[slot] = leader_slot
            self.term[slot] = term
            # A new term is a new chance to commit: clear the slot's
            # no-commit streak so a just-healed term skew doesn't keep
            # re-triggering elections before the next round lands.
            self._nocommit_streak.pop(slot, None)
        self.recorder.record("set_leader", slot=int(slot),
                             leader=int(leader_slot), term=int(term))

    def set_alive(self, alive: np.ndarray) -> None:
        """Install a new [P, R] per-partition replica liveness mask."""
        alive = np.asarray(alive, bool)
        if alive.shape != (self.cfg.partitions, self.cfg.replicas):
            raise ValueError(f"alive mask must be [P, R], got {alive.shape}")
        with self._lock:
            self.alive = alive.copy()
            self._refresh_quorum_ok_locked()

    def set_quorum(self, quorum: np.ndarray) -> None:
        """Install per-partition quorum sizes (RF//2+1 per topic)."""
        quorum = np.asarray(quorum, np.int32)
        if quorum.shape != (self.cfg.partitions,):
            raise ValueError(f"quorum must be [P], got {quorum.shape}")
        with self._lock:
            self.quorum = quorum.copy()
            self._refresh_quorum_ok_locked()

    def _refresh_quorum_ok_locked(self) -> None:
        # Plain python list, swapped whole: quorum_lost() runs on EVERY
        # consume/offset-commit, and a per-call numpy sum under the
        # control lock measurably contends with the drain loop at high
        # request rates (sampled hot in the e2e profile).
        self._quorum_ok = (
            self.alive.sum(axis=1) >= self.quorum
        ).tolist()

    def mirror_gap_slots(self) -> int:
        """Count of slots whose host mirror is gap-disabled (resolve
        failure; pending trim-passage heal) — taken under the plane's
        lock (observability readers must not race the resolver's
        heal-time dict mutation)."""
        with self._lock:
            return len(self._mirror_gap)

    def settled_gap_slots(self) -> int:
        """Count of slots carrying at least one settled gap (device-
        committed rows whose replication failed; skipped by every read
        path) — locked like mirror_gap_slots: observability readers must
        not race the settle thread's dict mutation."""
        with self._lock:
            return sum(1 for g in self._settled_gaps.values() if g)

    def settled_end(self, slot: int) -> int:
        """The slot's settled-read horizon, under the plane's lock (the
        advisor pattern of mirror_gap_slots: external pollers — the
        broker's long-poll probe, admin surfaces — must not reach into
        the array bare)."""
        with self._lock:
            return int(self._settled_end[slot])

    def settle_floors(self, slots) -> list[list]:
        """Per-slot settled-floor stamp for the replication sender
        (follower reads, ISSUE 16): `[[slot, settled_end, gaps], ...]`
        for the requested slots, snapshotted in ONE pass under the
        plane's lock so a frame never carries a floor that is newer
        than the gap map it rode with (a follower trusting such a pair
        could serve a nacked row the gap entry would have fenced).
        Floors are conservative by construction — the settle pipeline
        advances `_settled_end` only after the round's standby acks
        landed, so every offset at-or-below a stamped floor is already
        replicated to the whole (full-copy) standby set."""
        with self._lock:
            return [
                [int(s), int(self._settled_end[s]),
                 [list(g) for g in self._settled_gaps.get(s, ())]]
                for s in slots
            ]

    def log_end(self, slot: int) -> int:
        """The slot's host-shadow log end (device-committed absolute
        offset), under the plane's lock — the settled_end() pattern:
        external readers (profiles, admin surfaces) must not reach into
        `_log_end` bare while the resolver advances it."""
        with self._lock:
            return int(self._log_end[slot])

    def stalled_slots(self, threshold: Optional[int] = None) -> list[int]:
        """Slots whose last `threshold` dispatched rounds ALL failed to
        commit on device (default: 2x the per-submit retry budget, so a
        single submit's worth of transient failures never trips it).
        This is the liveness probe for the device-term-skew wedge: the
        controller duty treats a stalled slot as election-worthy even
        though its leader looks alive, and plan_elections confirms the
        skew against the device current_term before nominating."""
        if threshold is None:
            threshold = 2 * self.max_retry_rounds
        with self._lock:
            return sorted(
                s for s, n in self._nocommit_streak.items()
                if n >= threshold
            )

    def reset_stall(self, slot: int) -> None:
        """Clear the slot's no-commit streak: the election duty's device
        probe disproved term skew (stalled but term-aligned — an engine-
        quorum outage elections cannot help). Without this decay, a slot
        whose traffic stops right after such an outage stays "stalled"
        forever: stalled_slots() keeps reporting it and every duty tick
        re-pays the plan_elections device fetch at the election timeout
        on a healthy idle cluster. Fresh failing dispatches re-build the
        streak, so a real skew appearing later still trips the probe."""
        with self._lock:
            had = self._nocommit_streak.pop(slot, None)
        if had is not None:
            self.recorder.record("stall_reset", slot=int(slot), streak=had)

    def _add_settled_gap_locked(self, slot: int, begin: int,
                                end: int) -> None:
        """Record one failed round's [begin, end) as a settled gap
        (caller holds self._lock). Ranges arrive in base order within a
        slot (bases only advance), so insertion is an append that merges
        with an adjacent/overlapping predecessor."""
        if end <= begin:
            return
        gaps = self._settled_gaps.setdefault(slot, [])
        if gaps and begin <= gaps[-1][1]:
            gaps[-1][1] = max(gaps[-1][1], end)
        else:
            gaps.append([begin, end])
        # Recorder appends are lock-free — safe under the plane's lock.
        self.recorder.record("settled_gap", slot=int(slot),
                             begin=int(begin), end=int(end))

    def _gap_clamp_locked(self, slot: int, offset: int,
                          count: int) -> tuple[Optional[int], int]:
        """Clamp one read window against the slot's settled gaps (caller
        holds self._lock). Returns (skip_to, count): `skip_to` non-None
        means `offset` sits INSIDE a gap — serve nothing and continue at
        skip_to (the same contract as alignment padding: nacked rows
        advance next_offset without delivering); otherwise `count` is
        clamped so the window stops at the first gap past `offset`."""
        gaps = self._settled_gaps.get(slot)
        if not gaps:
            return None, count
        # Sorted disjoint ranges: bisect to the candidate at-or-before
        # `offset` — a flap-heavy controller accumulates gaps for its
        # whole lifetime and this probe sits on every read path inside
        # the plane's contended lock, so the common no-gap case must not
        # walk the history.
        i = bisect.bisect_right(gaps, offset, key=lambda g: g[0]) - 1
        if i >= 0 and gaps[i][0] <= offset < gaps[i][1]:
            return gaps[i][1], 0
        if i + 1 < len(gaps):
            return None, min(count, gaps[i + 1][0] - offset)
        return None, count

    def quorum_lost(self, slot: int) -> bool:
        """True iff partition `slot` cannot commit ANY round right now:
        fewer replica slots alive than its quorum. Rounds for such a
        slot are doomed before dispatch, so callers fast-fail with a
        typed `unavailable` refusal instead of burning an RPC timeout.
        Lock-free: reads the precomputed list set_alive/set_quorum swap
        in whole (list indexing is atomic under the GIL)."""
        return not self._quorum_ok[slot]

    def degraded_slots(self) -> list[int]:
        """Partitions whose quorum is currently lost ([P]-masked under
        the lock) — the `degraded` surface admin.stats advertises."""
        with self._lock:
            lost = self.alive.sum(axis=1) < self.quorum
        return [int(s) for s in np.nonzero(lost)[0]]

    # --------------------------------------------------- runtime knobs (SLO)

    def knob_state(self) -> dict:
        """The SLO autopilot's view of the adjustable operating point,
        under the plane's lock: the live coalesce/chain values, the
        EFFECTIVE settle window (configured minus soft-held permits),
        the configured cap, and the window's live occupancy."""
        with self._lock:
            return {
                "read_coalesce_s": float(self.read_coalesce_s),
                "chain_depth": int(self.chain_depth),
                "settle_window": int(self.settle_window - self._settle_held),
                "settle_window_cap": int(self.settle_window),
                "settle_inflight": int(self._settle_inflight),
            }

    def set_knobs(self, read_coalesce_s: Optional[float] = None,
                  chain_depth: Optional[int] = None,
                  settle_window: Optional[int] = None) -> dict:
        """Apply one SLO-controller decision (slo/controller.py). All
        writes ride self._lock: _drain reads chain_depth under the same
        lock, so one dispatch never sees a torn value, and the ownership
        lint's common-mutex rule holds for the controller thread plus
        any direct caller (tests, profiles).

        `settle_window` is a SOFT bound in [slo_settle_window_min,
        configured window]: shrinking acquires spare semaphore permits
        non-blocking (occupied slots converge on later ticks as rounds
        release — never blocks the control loop against a full window),
        growing releases held ones. `chain_depth` changes take effect at
        the next dispatch; a depth this plane has not run yet compiles
        its chain program lazily on first use (the controller moves on a
        power-of-two ladder to bound that to log2(max) programs)."""
        with self._lock:
            if read_coalesce_s is not None:
                self.read_coalesce_s = max(0.0, float(read_coalesce_s))
            if chain_depth is not None:
                self.chain_depth = max(1, int(chain_depth))
            if settle_window is not None:
                want = min(self.settle_window,
                           max(1, int(settle_window)))
                target_held = self.settle_window - want
                while self._settle_held > target_held:
                    self._settle_sem.release()
                    self._settle_held -= 1
                while self._settle_held < target_held:
                    if not self._settle_sem.acquire(blocking=False):
                        break  # window occupied: converge next tick
                    self._settle_held += 1
        return self.knob_state()

    @property
    def broken_reason(self) -> Optional[str]:
        """Non-None once the plane is PERMANENTLY unable to commit (the
        lockstep mesh broke: a worker process died or fell out of
        sequence). The controller broker polls this and abdicates —
        controller failover is the recovery path, exactly as for
        controller death (parallel/lockstep.py module docstring)."""
        return getattr(self.fns, "broken", None)

    def _adopt_lockstep_state(self, e: Exception) -> None:
        """A LockstepController call failed AFTER its local launch ran:
        the donated state buffers are gone, and the error carries their
        replacement. Adopt it so the plane stays usable (the error still
        propagates — the round fails loudly with the lockstep-break
        diagnostic, not with confusing donated-buffer errors forever
        after). Caller holds _device_lock."""
        st = getattr(e, "lockstep_result", None)
        if st is None:
            return
        # Engine results are (state, ...) tuples except resync/init_from,
        # which return the state (a NamedTuple — itself a tuple) directly.
        self._state = st if hasattr(st, "_fields") else st[0]

    def _fetch_state(self, field: str) -> np.ndarray:
        """Host copy of one state leaf. Under lockstep, the allgather is
        a broadcast engine call (every process must launch it); callers
        must hold _device_lock."""
        fetch = getattr(self.fns, "fetch_state", None)
        if fetch is not None:
            return fetch(self._state, field)
        return _fetch_global(getattr(self._state, field))

    def busy(self) -> bool:
        """True while rounds are queued or in flight. Duty-loop callers
        use this to defer OPTIONAL device fetches (repair scans): a
        state fetch must wait for every dispatched round to execute —
        while holding the device lock — so fetching on a busy plane
        drains the whole dispatch pipeline (measured as multi-second
        throughput collapses every repair-scan tick)."""
        with self._lock:
            queued = bool(self._appends) or bool(self._offsets)
        return queued or not self._inflight.empty()

    def log_ends(self) -> np.ndarray:
        """Per-replica log ends [R, P] — the lag map the repair loop uses
        to find replicas needing resync."""
        with self._device_lock:
            return self._fetch_state("log_end")

    def current_terms(self) -> np.ndarray:
        """Max observed term per partition [P] (election planners must
        propose above this, or granted-then-unadvertised elections would
        deadlock retries)."""
        with self._device_lock:
            return self._fetch_state("current_term").max(axis=0)

    # ------------------------------------------------------------- submits

    def submit_append(self, slot: int, payloads: list[bytes],
                      pid: int = 0, seq: int = -1, tctx=None) -> Future:
        """Queue payloads for partition `slot`; future resolves to the
        first assigned absolute offset once the round commits.

        `pid`/`seq` (pid > 0) make the submit IDEMPOTENT: a batch whose
        (pid, seq, len) matches a settled entry of the dedup table is
        acked immediately with its original base offset — no second
        append — and a batch identical to one still in flight attaches
        to the in-flight round's future (the wire-dup window: both RPCs
        see the same outcome). The table is replicated through the
        settle path (REC_PIDSEQ records) and rebuilt on boot/promotion
        replay, so the guarantee holds across controller failover. A
        sequence ABOVE the table's end is accepted as new — dedup never
        refuses fresh data, it only collapses replays."""
        fut: Future = Future()
        cfg = self.cfg
        if not 0 <= slot < cfg.partitions:
            fut.set_exception(ValueError(f"partition slot {slot} out of range"))
            return fut
        if not payloads:
            fut.set_exception(ValueError("empty append"))
            return fut
        if len(payloads) > cfg.max_batch:
            # Callers (the broker server) split client batches to fit one
            # round; a single submit never spans rounds.
            fut.set_exception(
                ValueError(
                    f"{len(payloads)} payloads exceed max_batch {cfg.max_batch}"
                )
            )
            return fut
        # Bulk validation (this runs per batch on RPC worker threads —
        # a per-message three-check python loop was a measurable slice
        # of the produce path's CPU): min/max are C-speed passes, and
        # non-bytes payloads fail pack_payload_rows's buffer coercion.
        try:
            lens = [len(m) for m in payloads]
            if min(lens) == 0:
                fut.set_exception(
                    ValueError("empty messages are not supported (length-0 "
                               "rows mark alignment padding)")
                )
                return fut
            if max(lens) > cfg.payload_bytes:
                fut.set_exception(
                    ValueError(
                        f"payload of {max(lens)} bytes exceeds payload_bytes "
                        f"{cfg.payload_bytes}"
                    )
                )
                return fut
            rows = pack_payload_rows(self.cfg, payloads)  # off-lock packing
        except TypeError as e:
            fut.set_exception(
                TypeError(f"payloads must be bytes: {e}")
            )
            return fut
        return self._submit_rows(slot, list(payloads), rows, pid, seq, fut,
                                 tctx)

    def submit_packed(self, slot: int, packed, lens: list[int],
                      pid: int = 0, seq: int = -1, tctx=None) -> Future:
        """Queue a PRE-PACKED append batch: `packed` is the
        `[len(lens), slot_bytes]` row block a host-plane worker already
        validated and packed (parallel/hostplane.py `_pack_rows`, the
        byte-identical twin of pack_payload_rows) — the payload bytes
        cross this boundary once and are never re-encoded. Semantics
        are submit_append's exactly; validation here is only the cheap
        structural re-check (the block shape), since the worker ran the
        per-message checks where packing ran."""
        fut: Future = Future()
        cfg = self.cfg
        SB = cfg.slot_bytes
        k = len(lens)
        if not 0 <= slot < cfg.partitions:
            fut.set_exception(ValueError(f"partition slot {slot} out of range"))
            return fut
        if k == 0 or k > cfg.max_batch or len(packed) != k * SB:
            fut.set_exception(ValueError(
                f"packed block of {len(packed)} bytes does not hold "
                f"{k} rows of {SB} (max_batch {cfg.max_batch})"
            ))
            return fut
        if k and (min(lens) <= 0 or max(lens) > cfg.payload_bytes):
            fut.set_exception(ValueError(
                f"packed row lengths out of (0, {cfg.payload_bytes}]"
            ))
            return fut
        rows = np.frombuffer(packed, np.uint8).reshape(k, SB)
        # Zero-copy payload views into the block (the drain only ever
        # len()s and persists them; the block itself is what rides the
        # round).
        mv = memoryview(packed)
        payloads = [
            mv[i * SB + _HDR : i * SB + _HDR + lens[i]] for i in range(k)
        ]
        return self._submit_rows(slot, payloads, rows, pid, seq, fut, tctx)

    def _submit_rows(self, slot: int, payloads: list, rows,
                     pid: int, seq: int, fut: Future,
                     tctx=None) -> Future:
        """Shared enqueue tail of submit_append / submit_packed (the
        caller validated and packed)."""
        self._m_submits.inc()
        self._m_messages.inc(len(payloads))
        pid, seq = int(pid), int(seq)
        with self._lock:
            if pid > 0:
                dup = self._pid_lookup_locked(pid, slot, seq, len(payloads))
                if dup is not None:
                    fut.set_result(dup)
                    return fut
                inflight = self._pid_inflight.get((pid, slot, seq))
                if inflight is not None:
                    # Same batch, round still in flight (wire dup /
                    # concurrent retry): one append, shared outcome.
                    return inflight
            if self._log_end[slot] >= _OFFSET_HORIZON:
                fut.set_exception(
                    PartitionFullError(
                        f"partition {slot} reached the int32 offset horizon "
                        f"({_OFFSET_HORIZON} rows); re-key onto another "
                        f"partition"
                    )
                )
                return fut
            self._appends.setdefault(slot, []).append(
                _Pending(list(payloads), fut, self.max_retry_rounds, rows,
                         pid=pid, seq=seq,
                         tctx=tctx if self.spans is not None else None)
            )
            if pid > 0:
                # Settled batches are moved to the dedup table — and
                # popped from here — by the settle thread under this
                # same lock, so no dup can slip between the two. FAILED
                # batches are popped at every terminal-failure site
                # (_pid_drop_locked): the producer's retry must
                # re-submit a real append, not attach to a dead future.
                # (Not a done-callback: those run inline at
                # set_exception, and several failure sites already hold
                # this non-reentrant lock.)
                self._pid_inflight[(pid, slot, seq)] = fut
        self._work.set()
        return fut

    def _pid_lookup_locked(self, pid: int, slot: int, seq: int,
                           n: int) -> Optional[int]:
        """Dedup probe (caller holds self._lock): the batch's original
        base offset if (pid, seq, n) replays a settled batch, -1 if it
        falls fully below the settled window without an exact entry
        (still a duplicate — ack it, position forgotten), None if the
        batch is new. A batch extending PAST the settled end is new by
        definition: refusing it could strand fresh data behind a stale
        table after an at-least-once gap."""
        entries = self._pid_tab.get((pid, slot))
        if not entries:
            return None
        if seq + n > entries[-1][1]:
            return None
        for s0, s1, base in reversed(entries):
            if s0 == seq and s1 == seq + n:
                return base
        return -1

    def _pid_drop_locked(self, pend: "_Pending", slot: int) -> None:
        """Drop one TERMINALLY-FAILED batch's in-flight dedup entry
        (caller holds self._lock): nothing settled, so the producer's
        retry must append for real. Guarded by identity — a fresh
        submit may already occupy the key."""
        if pend.pid <= 0:
            return
        key = (pend.pid, slot, pend.seq)
        if self._pid_inflight.get(key) is pend.future:
            self._pid_inflight.pop(key, None)

    def _pid_drop(self, pend: "_Pending", slot: int) -> None:
        if pend.pid > 0:
            with self._lock:
                self._pid_drop_locked(pend, slot)

    def pid_table_size(self) -> int:
        """Number of (pid, partition) keys in the producer dedup table
        (admin.stats surface) — locked accessor, settle thread mutates."""
        with self._lock:
            return len(self._pid_tab)

    def drop_pids(self, pids: set[int]) -> int:
        """Drop the dedup entries of REAPED producer ids (pid expiry,
        OP_RETIRE_PRODUCER): settled-window entries go; in-flight
        entries stay — they belong to LIVE submissions whose futures
        settle through the normal path, and a reaped-mid-flight batch
        keeps its wire-dup protection until it lands. Safe because
        reaped pids are never reissued (the replicated counter is
        monotone), so no new producer can collide with a dropped key.
        Returns how many table keys were dropped."""
        if not pids:
            return 0
        with self._lock:
            drop = [k for k in self._pid_tab if k[0] in pids]
            for k in drop:
                del self._pid_tab[k]
        return len(drop)

    def retain_pids(self, keep: set[int], below: Optional[int] = None
                    ) -> int:
        """Reconciliation sweep: drop dedup entries whose pid is NOT in
        `keep` (the replicated registry) — boot replay rebuilds
        REC_PIDSEQ entries for pids reaped while this broker was down,
        and those would otherwise linger forever. `below` is the
        locally-applied pid counter: a pid >= below belongs to a
        registration THIS replica has not applied yet (the pid space
        is the replicated monotone counter), so its absence from
        `keep` is apply lag, not a reap — never drop it. Returns
        drops."""
        with self._lock:
            drop = [
                k for k in self._pid_tab
                if k[0] not in keep
                and (below is None or k[0] < below)
            ]
            for k in drop:
                del self._pid_tab[k]
        return len(drop)

    def submit_offsets(self, slot: int, updates: list[tuple[int, int]]) -> Future:
        """Queue consumer-offset commits [(consumer_slot, offset)]; the
        future resolves to True when the round commits (offset commits
        replicate through the same quorum round as appends — the
        reference routes them through the same partition Raft log,
        ConsumerOffsetUpdateRequestProcessor.java:38-69)."""
        fut: Future = Future()
        C = self.cfg.max_consumers
        if not 0 <= slot < self.cfg.partitions:
            fut.set_exception(ValueError(f"partition slot {slot} out of range"))
            return fut
        if len(updates) > self.cfg.max_offset_updates:
            # An oversized pending could never fit a round and would wedge
            # the slot's FIFO queue forever.
            fut.set_exception(
                ValueError(
                    f"{len(updates)} offset updates exceed max_offset_updates "
                    f"{self.cfg.max_offset_updates}"
                )
            )
            return fut
        if not updates or any(not 0 <= s < C for s, _ in updates):
            fut.set_exception(ValueError(f"bad consumer slots in {updates}"))
            return fut
        self._m_offsets.inc()
        with self._lock:
            self._offsets.setdefault(slot, []).append(
                _PendingOffsets([(int(s), int(o)) for s, o in updates], fut,
                                self.max_retry_rounds)
            )
        self._work.set()
        return fut

    # --------------------------------------------------------------- reads

    def read(
        self, slot: int, offset: int, replica: int,
        max_msgs: Optional[int] = None,
    ) -> tuple[list[bytes], int]:
        """Committed messages of `slot` from storage offset `offset` as
        seen by `replica`; returns (messages, next_offset). Offsets are
        STORAGE offsets (rounds are ALIGN-padded), so the caller must
        always continue from the returned `next_offset`, never from
        `offset + len(messages)`. Replica-local, no quorum round —
        matching the reference's leader-local reads
        (PartitionStateMachine.handleBatchRead:85) but bounded by the
        commit index (stricter: never serves un-replicated entries).

        Offsets below the retention watermark are served from the round
        store via the log index (only committed rounds are ever
        persisted, so store reads need no commit bound). The HOT window
        — above trim — is served from the host ring mirror with no
        device dispatch (see __init__); only a mirror gap (resolve
        failure) falls through to the device ring. A ring read races
        the step thread — trim can advance and a committed round can
        recycle the window's rows between the watermark check and the
        read — so the watermark is re-checked AFTER the read and a
        covered window is re-served from the store (store records are
        immutable, so that path is race-free). `replica` only selects a
        serving replica on the device paths: the mirror holds the
        COMMITTED prefix, which is replica-invariant by the quorum
        round's log-matching (per-replica divergence exists only above
        commit, which no read path ever serves)."""
        if not 0 <= slot < self.cfg.partitions:
            raise ValueError(f"partition slot {slot} out of range")
        self._m_read_calls.inc()
        gc_races = 0
        while True:
            with self._lock:
                trim = int(self.trim[slot])
                skip_to, _ = self._gap_clamp_locked(slot, offset, 1)
            if skip_to is not None:
                # Inside a settled gap (replication-FAILED round): walk
                # PAST it and keep reading — consumers only advance
                # their committed offset when a batch delivers messages,
                # so an empty-but-advanced answer here would strand them
                # below the gap forever (the same contract as the store
                # path's jump-forward: nacked rows, like padding, are
                # crossed inside ONE read call).
                offset = skip_to
                continue
            if offset < trim and self.log_index is not None:
                try:
                    got = self._read_store(slot, offset, max_msgs)
                except StoreReadRaceError:
                    # Sustained GC churn: records exist but every lookup
                    # lost the race. Retry (bounded) instead of treating
                    # the window as absent — an earliest-reset here
                    # would skip retained rows.
                    gc_races += 1
                    if gc_races > 50:
                        raise
                    time.sleep(0.001)
                    continue
                if got is not None:
                    msgs_got, nxt_got = got
                    if not msgs_got and nxt_got > offset:
                        # An all-padding store window (a persisted
                        # boundary-pad round, or a record clamped at a
                        # gap): keep walking — see the gap comment
                        # above for why empty-but-advanced must not
                        # reach the caller while rows remain.
                        offset = nxt_got
                        continue
                    self._m_read_msgs.inc(len(msgs_got))
                    return got
                # Nothing persisted at-or-after `offset` (store GC can
                # reclaim a partition's entire below-trim history):
                # earliest-reset to the watermark — rows >= trim are
                # ring-resident — or this loop would spin forever.
                offset = trim
            if self._host_ring is not None:
                res = self._read_cache(slot, offset, max_msgs)
                if res is _CACHE_LAPPED:
                    continue  # trim overran the window mid-copy: store-serve
                if res is _CACHE_GAP:
                    # Mirror-gap window: device-probe ONCE per gap
                    # generation (the probe re-validates the window
                    # against the device commit bound), then serve the
                    # store path directly for the gap's remaining
                    # lifetime — settled rows are persisted and indexed
                    # BEFORE they are mirrored (_release_one order), so
                    # the previous per-call device round-trip here was
                    # pure overhead.
                    with self._lock:
                        gen = self._mirror_gap_gen.get(slot, 0)
                        probed = self._gap_probed_gen.get(slot) == gen
                        self._gap_probed_gen[slot] = gen
                    if probed and self.log_index is not None:
                        try:
                            got = self._read_store(slot, offset, max_msgs)
                        except StoreReadRaceError:
                            got = None  # GC churn: the device re-serves
                        if got is not None:
                            msgs_got, nxt_got = got
                            if not msgs_got and nxt_got > offset:
                                offset = nxt_got  # all-padding: walk on
                                continue
                            self._m_read_msgs.inc(len(msgs_got))
                            return got
                    res = None  # first probe this gap: device authority
                if res is not None:
                    msgs_res, nxt_res = res
                    if not msgs_res and nxt_res > offset:
                        offset = nxt_res  # all-padding window: keep walking
                        continue
                    self.read_cache_hits += 1
                    self._m_read_msgs.inc(len(msgs_res))
                    return res
            fut: Future = Future()
            with self._read_lock:
                if self._stop.is_set():
                    # stop() already drained stranded reads; enqueueing
                    # now would hang this caller forever.
                    raise NotCommittedError("data plane stopped")
                self._reads.append((slot, offset, replica, fut))
            self._read_work.set()
            data, lens, count = fut.result()
            # Clamp to the settled horizon: the device's commit index
            # includes rounds whose replication may still fail — those
            # rows are nacked and must stay invisible (see _resolve_one).
            # Settled GAPS (replication-FAILED rounds the horizon later
            # passed) are skipped the same way: inside a gap the read
            # serves nothing and jumps to its end; a window reaching a
            # gap stops at its begin.
            count = int(count)
            with self._lock:
                settled_room = max(0, int(self._settled_end[slot]) - offset)
                skip_to, gap_room = self._gap_clamp_locked(
                    slot, offset, count
                )
            if skip_to is not None:
                offset = skip_to  # raced into a gap recorded mid-read
                continue
            count = min(count, settled_room, gap_room)
            with_pos = decode_entries_with_pos(data, lens, count)
            with self._lock:
                trim_after = int(self.trim[slot])
            if trim_after > offset and self.log_index is not None:
                # trim advanced past this window mid-read: its ring rows
                # may hold the next lap now — retry (store-serves next).
                continue
            if not with_pos and 0 < count < settled_room:
                # All-padding window short of the horizon (clamped at a
                # settled gap, or a boundary-pad round): walk on — an
                # empty-but-advanced answer must not reach the caller
                # while settled rows remain above (see the gap comment
                # at the loop head).
                offset += count
                continue
            break
        count = int(count)
        if max_msgs is not None and len(with_pos) > max(0, max_msgs):
            with_pos = with_pos[: max(0, max_msgs)]
            # Continue right after the last returned message's row.
            next_offset = offset + (with_pos[-1][0] + 1 if with_pos else 0)
        else:
            next_offset = offset + count
        self._m_read_msgs.inc(len(with_pos))
        return [m for _, m in with_pos], next_offset

    def _read_cache(
        self, slot: int, offset: int, max_msgs: Optional[int]
    ) -> Optional[tuple[list[bytes], int]]:
        """Serve one hot read from the host ring mirror. Returns the
        (messages, next_offset) result, None to fall through to the
        device (dirty log-end shadow: the device commit bound is the
        authority), _CACHE_GAP when the offset sits in a mirror-gap
        window (resolve failure — caller probes the device once per gap
        generation, then store-serves), or _CACHE_LAPPED when trim
        overran the window mid-copy (caller retries; the next pass
        store-serves). An offset at-or-past the SETTLED end answers
        empty WITHOUT device dispatch: reads may never see past the
        settled horizon anyway (a device dispatch would clamp to it and
        return the same emptiness), so tail polls stay host-authoritative
        even while the settle pipeline holds committed-but-unsettled
        rounds in flight."""
        S = self.cfg.slots
        with self._lock:
            end = int(self._settled_end[slot])
            cend = int(self._cache_end[slot])
            dirty = slot in self._shadow_dirty
            skip_to, gap_room = self._gap_clamp_locked(
                slot, offset, self.cfg.read_batch
            )
        if dirty:
            # A resolve failed with the slot's round outcome unknown:
            # the log-end shadow may TRAIL device-committed rows until
            # the next drain re-derives it, so an empty answer here
            # could hide a committed suffix indefinitely on an idle
            # partition. The device path's commit bound is the
            # authority.
            return None
        if skip_to is not None:
            # Inside a settled gap (replication-FAILED round): nothing
            # to serve, continue past it — host-authoritative, same as
            # the at-horizon empty answer below.
            return [], skip_to
        if offset >= end:
            return [], offset  # caught up: nothing committed past offset
        if offset >= cend:
            return _CACHE_GAP  # mirror gap: store/device is the authority
        pos = offset % S
        k = min(end - offset, cend - offset, self.cfg.read_batch, gap_room)
        if pos + k <= S:
            rows = self._host_ring[slot, pos : pos + k].copy()
        else:  # window spans the ring wrap, same as the device read
            rows = np.concatenate([
                self._host_ring[slot, pos:],
                self._host_ring[slot, : pos + k - S],
            ])
        with self._lock:
            lapped = int(self.trim[slot]) > offset
        if lapped and self.log_index is not None:
            return _CACHE_LAPPED  # rows may hold the next lap now
        # Decode on flat bytes: one tobytes() for the window, then
        # length-prefixed slices — ~3x the msgs/s of per-row numpy
        # slicing on the host-RAM-bound consume path.
        SB = self.cfg.slot_bytes
        lens = np.minimum(np.asarray(row_lens(rows)), SB - _HDR)
        flat = rows.tobytes()
        # Lengths are clamped to the row capacity above — a corrupt
        # length header must not bleed the next row's bytes into a
        # message (the device/store decode paths clamp per row too).
        with_pos = [
            (i, flat[i * SB + _HDR : i * SB + _HDR + n])
            for i, n in enumerate(lens.tolist())
            if n > 0
        ]
        if max_msgs is not None and len(with_pos) > max(0, max_msgs):
            with_pos = with_pos[: max(0, max_msgs)]
            next_offset = offset + (with_pos[-1][0] + 1 if with_pos else 0)
        else:
            next_offset = offset + k
        return [m for _, m in with_pos], next_offset

    def _read_store(
        self, slot: int, offset: int, max_msgs: Optional[int]
    ) -> Optional[tuple[list[bytes], int]]:
        """Serve one read below the retention watermark from the round
        store: find the append record holding `offset` (or the next one —
        a consumer below the earliest retained record jumps forward, the
        documented earliest-reset semantics), seek-read its rows, decode.
        Serves from ONE record per call; the caller's next_offset loop
        walks forward and falls back to the device ring once past the
        watermark. Returns None if nothing is indexed at-or-after offset
        (caller falls through to the ring)."""
        SB = self.cfg.slot_bytes
        for _ in range(4):  # bounded GC-race retries (one per deleted seg)
            entry = self.log_index.find(slot, offset)
            floor = self.log_index.floor(slot)
            if floor is not None and offset < floor:
                # Below the bounded index's floor: records may exist in
                # the store that fell out of the index — only a scan can
                # tell.
                try:
                    scanned = self._scan_store_for(slot, offset)
                except FileNotFoundError:
                    # Store GC deleted a segment mid-walk: rebuild the
                    # scan from the surviving files on the next pass.
                    with self._lock:
                        self._scan_index = None
                    continue
                if scanned is not None:
                    entry = scanned
            if entry is None:
                return None
            base, nrows, locator = entry
            eff = max(offset, base)  # jump to the earliest retained record
            row = eff - base
            k = min(nrows - row, self.cfg.read_batch)
            if k <= 0:
                return None
            # Settled-gap clamp, store edition: a LOCAL store never holds
            # gap rows (failed rounds are not persisted here), but a
            # promoted standby's can, and the trim watermark passing a
            # gap after a ring wrap must not let the store re-expose
            # rows every other path refuses.
            with self._lock:
                skip_to, k = self._gap_clamp_locked(slot, eff, k)
            if skip_to is not None:
                return [], skip_to
            try:
                data = self.store.read_payload(locator, row * SB, k * SB)
            except FileNotFoundError:
                # Store GC deleted the backing segment between lookup and
                # read: drop its stale entries (this also clears the scan
                # cache) and redo the FULL lookup, including the
                # below-floor scan path. Other OSErrors (truncation or
                # corruption of a RETAINED segment) must surface, not be
                # mistaken for deliberate deletion.
                seg = locator[0] if isinstance(locator, tuple) else None
                if seg is None:
                    raise
                self.drop_index_segments({seg})
                continue
            offset = eff
            break
        else:
            # Exhausted the per-call retry budget WITH a record found
            # each time: that is GC churn, not absence — the caller must
            # not earliest-reset over it.
            raise StoreReadRaceError(
                f"partition {slot} offset {offset}: store read lost the "
                f"GC race 4 times"
            )
        rows = np.frombuffer(data, np.uint8).reshape(k, SB)
        lens = np.asarray(row_lens(rows))  # one header decoder (core.state)
        with_pos = decode_entries_with_pos(rows, lens, k)
        if max_msgs is not None and len(with_pos) > max(0, max_msgs):
            with_pos = with_pos[: max(0, max_msgs)]
            next_offset = offset + (with_pos[-1][0] + 1 if with_pos else 0)
        else:
            next_offset = offset + k
        return [m for _, m in with_pos], next_offset

    def read_offset(self, slot: int, consumer_slot: int, replica: int = 0) -> int:
        """Committed consumer offset — served from the host shadow of the
        replicated table (every offset commit passes through this host's
        rounds, and install() seeds the shadow from the recovered image,
        so the shadow is exact). `replica` is kept for API compatibility;
        no device fetch happens."""
        del replica
        if not 0 <= slot < self.cfg.partitions:
            raise ValueError(f"partition slot {slot} out of range")
        if not 0 <= consumer_slot < self.cfg.max_consumers:
            raise ValueError(f"consumer slot {consumer_slot} out of range")
        with self._lock:
            return int(self._offsets_shadow[slot, consumer_slot])

    def warm(self, buckets: tuple[int, ...] = (8, 32)) -> None:
        """Compile the hot programs before traffic needs them: the sparse
        single and chained rounds at the given active-set buckets, and
        the batched read. Dispatches no-op rounds of those exact shapes
        (counts 0, all-padding ids: nothing commits, state is
        semantically unchanged). Safe concurrently with traffic (device
        lock); brokers kick this in the background at boot so the first
        produce doesn't pay the multi-second XLA compile."""
        cfg = self.cfg
        P, B, SB, U = (cfg.partitions, cfg.max_batch, cfg.slot_bytes,
                       cfg.max_offset_updates)
        noop = StepInput(
            entries=self._dummy_entries(),
            counts=np.zeros((P,), np.int32),
            off_slots=np.zeros((P, U), np.int32),
            off_vals=np.zeros((P, U), np.int32),
            off_counts=np.zeros((P,), np.int32),
            leader=np.zeros((P,), np.int32),
            term=np.zeros((P,), np.int32),
            extents=np.zeros((P,), np.int32),
        )
        alive = np.ones((P, cfg.replicas), bool)
        K = self.chain_depth
        stacked = StepInput(*[
            np.broadcast_to(np.asarray(f), (K,) + np.asarray(f).shape).copy()
            for f in noop
        ])
        for A in buckets:
            if self._stop.is_set():
                return  # fenced/stopped mid-warm: the programs are moot
            A = max(1, min(A, P))
            # One lock hold per dispatch: elections/traffic (takeover
            # duty) interleave between the multi-second compiles instead
            # of stalling behind a whole bucket's pair.
            with self._device_lock:
                try:
                    self._state, _ = self.fns.step_sparse(
                        self._state, noop, np.zeros((A, B, SB), np.uint8),
                        np.full((A,), -1, np.int32), alive,
                    )
                except Exception as e:
                    self._adopt_lockstep_state(e)
                    raise
            if K > 1 and not self._stop.is_set():
                with self._device_lock:
                    try:
                        self._state, _ = self.fns.step_many_sparse(
                            self._state, stacked,
                            np.zeros((K, A, B, SB), np.uint8),
                            np.full((K, A), -1, np.int32), alive,
                        )
                    except Exception as e:
                        self._adopt_lockstep_state(e)
                        raise
        if self._stop.is_set():
            return
        with self._device_lock:
            self.fns.read_many(
                self._state, np.zeros((self.read_q,), np.int32),
                np.zeros((self.read_q,), np.int32),
                np.zeros((self.read_q,), np.int32),
            )

    def warm_async(self, buckets: tuple[int, ...] = (8, 32),
                   delay_s: float = 0.0) -> threading.Thread:
        """warm() on a daemon thread (boot path); errors are logged, never
        raised — warming is an optimization, not a correctness step.
        `delay_s` defers the first compile so latency-critical boot work
        (a promoted controller's first election pass) wins the device-
        lock race; the thread exits early if the plane stops meanwhile."""
        def run() -> None:
            if delay_s > 0 and self._stop.wait(timeout=delay_s):
                return
            try:
                self.warm(buckets)
            except Exception as e:
                log.warning("program warm-up failed: %s: %s",
                            type(e).__name__, e)

        t = threading.Thread(target=run, daemon=True, name="dataplane-warm")
        t.start()
        return t

    def _read_loop(self) -> None:
        """Read-coalescer thread: drain queued device reads as read_many
        batches of up to read_q queries (padded to a fixed Q so exactly
        one program compiles)."""
        Q = self.read_q
        while not self._stop.is_set():
            if not self._read_work.wait(timeout=0.05):
                continue
            if self.read_coalesce_s > 0:
                with self._read_lock:
                    n = len(self._reads)
                if 0 < n < Q:
                    time.sleep(self.read_coalesce_s)  # assemble the cohort
            with self._read_lock:
                batch = self._reads[:Q]
                del self._reads[:Q]
                if not self._reads:
                    self._read_work.clear()
            if not batch:
                continue
            self.read_dispatches += 1
            self.read_queries += len(batch)
            reps = np.zeros((Q,), np.int32)
            parts = np.zeros((Q,), np.int32)
            offs = np.zeros((Q,), np.int32)
            for i, (slot, offset, replica, _) in enumerate(batch):
                reps[i], parts[i], offs[i] = replica, slot, offset
            try:
                with self._device_lock:
                    data, lens, count = self.fns.read_many(
                        self._state, reps, parts, offs
                    )
                    data = np.asarray(data)
                    lens = np.asarray(lens)
                    count = np.asarray(count)
            except Exception as e:
                for *_, fut in batch:
                    if not fut.done():
                        fut.set_exception(e)
                continue
            for i, (_, _, _, fut) in enumerate(batch):
                if not fut.done():
                    fut.set_result((data[i], lens[i], int(count[i])))

    def drop_index_segments(self, seg_indices: set[int]) -> None:
        """Store GC deleted these segments: prune their entries from the
        retention indexes (reads below the remaining floor jump forward
        to the earliest retained record)."""
        if self.log_index is None or not seg_indices:
            return
        self.log_index.prune(
            lambda loc: isinstance(loc, tuple) and loc[0] in seg_indices
        )
        with self._lock:
            self._scan_index = None

    def _scan_store_for(
        self, slot: int, offset: int
    ) -> Optional[tuple[int, int, object]]:
        """Slow path behind the bounded index: one full framing walk of
        the store builds an UNBOUNDED throwaway LogIndex (same add()
        truncation semantics as the live one), cached until the next
        install(). Records below the live index's floor are immutable
        (later-records-win regressions only touch unsettled tail rounds),
        so serving a whole catch-up from one scan is sound; entries the
        cache lacks (appended after the scan) live above the floor and
        are served by the live index. Only reachable for consumers
        lagging by more than the index's per-slot entry cap."""
        def build():
            import sys as _sys

            from ripplemq_tpu.storage.logindex import LogIndex

            idx = LogIndex(max_entries_per_slot=_sys.maxsize)
            idx.load(self.store.scan_indexed(), self.cfg.slot_bytes,
                     REC_APPEND)
            return idx

        # LOCAL-REF discipline (ownership lint, PR 11): concurrent
        # lagging readers run this on RPC worker threads while store GC
        # (drop_index_segments, duty thread) and install() null the
        # cache under the plane's lock. Re-reading `self._scan_index`
        # between the rebuild and the find raced that invalidation —
        # a None landing in between raised AttributeError out of a
        # consume (tests/test_concurrency_triage.py::
        # test_scan_index_local_ref_race is the directed repro). Every lookup now runs against a local
        # reference; the shared slot is only SWAPPED, under the lock.
        idx = self._scan_index
        if idx is None:
            idx = build()
            with self._lock:
                self._scan_index = idx
        entry = idx.find(slot, offset)
        if entry is None or not entry[0] <= offset < entry[0] + entry[1]:
            # The cached scan predates records that have since fallen out
            # of the bounded live index (its floor rose past them) — a
            # non-covering answer here could silently jump a consumer
            # over store-resident data. Rebuild once from the current
            # store before trusting it.
            idx = build()
            with self._lock:
                self._scan_index = idx
            entry = idx.find(slot, offset)
        return entry

    def slot_detail(self, slots) -> dict[str, dict[str, int]]:
        """Per-slot observability snapshot: the COMMIT leaf fetched from
        the device (one fetch for all requested slots — not log_end
        relabeled), plus the host log-end shadow and trim watermark read
        together under the control lock so the host pair is mutually
        consistent. Commit and the host pair are separate snapshots with
        rounds possibly landing between them, so either may lead the
        other by in-flight rounds — treat a small commit/log_end skew as
        pipelining, not corruption."""
        with self._device_lock:
            commit = self._fetch_state("commit").max(axis=0)  # [P]
        with self._lock:
            ends = self._log_end.copy()
            trim = self.trim.copy()
        out = {}
        for s in slots:
            s = int(s)
            if 0 <= s < self.cfg.partitions:
                out[str(s)] = {
                    "commit": int(commit[s]),
                    "log_end": int(ends[s]),
                    "trim": int(trim[s]),
                }
        return out

    def commit_index(self, slot: int) -> int:
        """Max commit index across replicas (the leader's view)."""
        with self._device_lock:
            commit = self._fetch_state("commit")  # [R, P]
        return int(commit[:, slot].max())

    # ----------------------------------------------------------- elections

    def elect(self, candidates: dict[int, tuple[int, int]]) -> dict[int, bool]:
        """One batched RequestVote round. `candidates` maps partition slot
        -> (candidate replica slot, proposed term). Returns slot -> elected.
        Many partitions elect in a single device round."""
        P = self.cfg.partitions
        cand = np.full((P,), -1, np.int32)
        cterm = np.zeros((P,), np.int32)
        for slot, (c, t) in candidates.items():
            cand[slot] = c
            cterm[slot] = t
        with self._lock:
            alive = self.alive.copy()
            quorum = self.quorum.copy()
        with self._device_lock:
            try:
                self._state, elected, votes = self.fns.vote(
                    self._state, cand, cterm, alive, quorum
                )
            except Exception as e:
                self._adopt_lockstep_state(e)
                raise
            elected = np.asarray(elected)
        out = {slot: bool(elected[slot]) for slot in candidates}
        self.recorder.record(
            "elect", candidates=len(candidates),
            won=sum(1 for w in out.values() if w),
            slots=[int(s) for s in sorted(candidates)][:32],
        )
        return out

    def resync(self, src_slot: int, dst_slot: int, partitions: list[int]) -> None:
        """Copy `src_slot`'s replica state over `dst_slot` for the given
        partitions (recovering replica catch-up)."""
        mask = np.zeros((self.cfg.partitions,), bool)
        mask[list(partitions)] = True
        with self._device_lock:
            try:
                self._state = self.fns.resync(
                    self._state, np.int32(src_slot), np.int32(dst_slot), mask
                )
            except Exception as e:
                self._adopt_lockstep_state(e)
                raise

    # ---------------------------------------------------------- step thread

    def _drain(self) -> Optional[tuple[StepInput, dict]]:
        """Build one dispatch's worth of rounds from the queues — up to
        `chain_depth` CHAINED rounds when the backlog is deep (one
        device launch commits them all via the engine's scan path; see
        parallel.engine step_many). Returns None if idle.

        Chained rounds may take several pendings of the SAME slot (the
        device executes the chain in order, so per-slot FIFO holds). The
        per-slot committed-prefix property of a chain (alive/quorum/trim
        are chain-constant, so once a slot's round fails every later one
        does too) makes the predicted bases exact for every committed
        round."""
        cfg = self.cfg
        with self._lock:
            if not self._appends and not self._offsets:
                return None
            dirty = self._shadow_dirty & set(self._appends)
        if dirty:
            # Re-derive failed-resolve slots' shadow from the device (one
            # fetch covers all of them; their values are stable — a dirty
            # slot is never busy when drained).
            ends = self.log_ends().max(axis=0)
            with self._lock:
                for s in dirty:
                    self._log_end[s] = int(ends[s])
                self._shadow_dirty -= dirty
        with self._lock:
            pred_end: dict[int, int] = {}
            rounds = []
            for _ in range(self.chain_depth):
                r = self._build_round_locked(pred_end)
                if r is None:
                    break
                rounds.append(r)
            if not rounds:
                return None
            alive = self.alive.copy()
            quorum = self.quorum.copy()
            trim = self.trim.astype(np.int32)
            if len(rounds) > 1:
                # Pad to exactly chain_depth rounds (all-zero rounds
                # carry no work and commit nothing) so chain programs
                # compile once per active-set bucket, not per length.
                # Zero tensors are a shared cached template (np.stack
                # below copies them out; nothing ever writes them), and
                # the leader/term snapshot happens HERE, under the lock,
                # consistent with the chain's real rounds.
                zero = self._zero_round_template()
                pad_inp = StepInput(self._dummy_entries(), *zero,
                                    leader=self.leader.copy(),
                                    term=self.term.copy(),
                                    extents=zero[0])
                while len(rounds) < self.chain_depth:
                    rounds.append((
                        pad_inp,
                        {"appends": {}, "offsets": {}, "bases": {},
                         "entries": {}, "counts": {}},
                    ))
        chain = [r[1] for r in rounds]
        # Compact active-set arrays: one [A, B, SB] block stack + global
        # slot ids per round (A = shared bucket over the chain so the
        # stacked shape is uniform; -1 pads). This is the ONLY bulk
        # device input — a sparse round ships A/P of the dense bytes.
        B, SB = cfg.max_batch, cfg.slot_bytes
        A = self._active_bucket(max(len(rc["entries"]) for rc in chain))
        ec = np.zeros((len(chain), A, B, SB), np.uint8)
        ids = np.full((len(chain), A), -1, np.int32)
        for k, rc in enumerate(chain):
            for a, (slot, block) in enumerate(sorted(rc["entries"].items())):
                ec[k, a] = block
                ids[k, a] = slot
        if len(rounds) == 1:
            inp = rounds[0][0]
            entries_c, slot_ids = ec[0], ids[0]
        else:
            inp = StepInput(*[
                np.stack([np.asarray(getattr(r[0], f)) for r in rounds])
                for f in StepInput._fields
            ])
            entries_c, slot_ids = ec, ids
        # Top-level unions drive busy bookkeeping and whole-dispatch
        # failure paths (_fail_round, shadow-dirty marking).
        union_a: dict[int, list] = {}
        union_o: dict[int, list] = {}
        for rc in chain:
            for slot, taken in rc["appends"].items():
                union_a.setdefault(slot, []).extend(taken)
            for slot, toff in rc["offsets"].items():
                union_o.setdefault(slot, []).extend(toff)
        return inp, {"chain": chain, "appends": union_a, "offsets": union_o,
                     "entries_c": entries_c, "slot_ids": slot_ids,
                     "alive": alive, "quorum": quorum, "trim": trim}

    def _zero_round_template(self):
        """Shared all-zero (counts, off_slots, off_vals, off_counts)
        arrays for chain padding — read-only by contract (np.stack
        copies them into the dispatch tensor)."""
        if self._zero_round is None:
            cfg = self.cfg
            P, U = cfg.partitions, cfg.max_offset_updates
            self._zero_round = (
                np.zeros((P,), np.int32),
                np.zeros((P, U), np.int32),
                np.zeros((P, U), np.int32),
                np.zeros((P,), np.int32),
            )
        return self._zero_round

    def _dummy_entries(self) -> np.ndarray:
        """The StepInput entries placeholder: the control phase never
        reads entries, and the real rows travel compacted (active-set;
        see _drain). Shaped [P, 1, 1] so the spmd binding can shard its
        leading axis like the dense field it replaces. Built eagerly in
        __init__ (multiple threads reach this; a lazy build here was an
        unguarded shared write — ownership lint, PR 11)."""
        return self._dummy

    def _active_bucket(self, n: int) -> int:
        """Smallest active-set capacity bucket >= n (8, 32, 128, ... up
        to P): rounds compile once per bucket, not once per active
        count."""
        a = 8
        while a < n:
            a *= 4
        return max(1, min(a, self.cfg.partitions))

    def all_buckets(self) -> tuple[int, ...]:
        """Every active-set bucket this shape can hit — the boot-time
        warm list (a bucket first reached under traffic charges its
        multi-second XLA compile to live produces; measured as
        multi-second dead zones in the e2e bench before full warming).
        Derived FROM _active_bucket so the ladder geometry lives in one
        place: sweep n over doubling active counts up to P and collect
        the buckets they map to."""
        P = self.cfg.partitions
        out = []
        n = 1
        while n < P:
            out.append(self._active_bucket(n))
            n *= 2
        out.append(self._active_bucket(P))
        return tuple(dict.fromkeys(out))

    def _build_round_locked(self, pred_end: dict[int, int]):
        """Build ONE round from the queues (caller holds self._lock).
        `pred_end` carries the chain's predicted per-slot log ends —
        exact for committed rounds by the chain prefix property. Returns
        (StepInput, round_ctx) or None if nothing drainable remains."""
        cfg = self.cfg
        P, B, SB, U = cfg.partitions, cfg.max_batch, cfg.slot_bytes, cfg.max_offset_updates
        # Active-set rounds: packed [B, SB] blocks per appending slot
        # (compact device input + the bytes the resolver persists); the
        # StepInput ships only a tiny dummy in the entries field.
        blocks: dict[int, np.ndarray] = {}
        counts = np.zeros((P,), np.int32)
        off_slots = np.zeros((P, U), np.int32)
        off_vals = np.zeros((P, U), np.int32)
        off_counts = np.zeros((P,), np.int32)
        # round_appends: slot -> [(pending, start, n)] taken this round
        round_appends: dict[int, list[tuple[_Pending, int, int]]] = {}
        round_offsets: dict[int, list[_PendingOffsets]] = {}
        # Drain-time log-end shadow per append slot — the round's
        # base, known without a device fetch (see pipeline comment).
        round_bases: dict[int, int] = {}

        S = cfg.slots
        can_trim = self.store is not None and self.log_index is not None
        for slot, queue in list(self._appends.items()):
            if slot in self._busy_a:
                continue  # rounds of PRIOR dispatches stay ordered
            end = pred_end.get(slot, int(self._log_end[slot]))
            if end >= _OFFSET_HORIZON:
                if slot in pred_end:
                    # Predicted (an earlier chain round advanced it) —
                    # not authoritative: if that round loses quorum the
                    # real end stays below the horizon, so just stop
                    # chaining this slot; the next dispatch re-checks
                    # against the exact shadow.
                    continue
                # Authoritative horizon check (submit_append's check
                # races a deep backlog: it compares against a shadow
                # that only advances at resolve time). `end` here is
                # exact — the slot is not busy and untouched this chain.
                for pend in queue:
                    self._pid_drop_locked(pend, slot)
                    if not pend.future.done():  # caller may cancel()
                        pend.future.set_exception(PartitionFullError(
                            f"partition {slot} reached the int32 "
                            f"offset horizon; re-key onto another "
                            f"partition"
                        ))
                self._appends.pop(slot, None)
                continue
            if can_trim:
                # Lazy retention: raise the trim watermark just enough
                # for a full window past the current end — but never
                # above the PERSISTED prefix (self._persisted). `end` may
                # be chain-predicted rounds ahead of what the resolver
                # has persisted; an unclamped raise could let a
                # concurrent read find nothing in the store below the
                # watermark and silently skip committed rows. Clamped,
                # a deep chain that outruns the ring simply fails the
                # device capacity check on its later rounds and
                # retries next dispatch.
                needed = min(end + B - S, int(self._persisted[slot]))
                if needed > self.trim[slot]:
                    self.trim[slot] = needed
                # Rounds must never lap the ring boundary (live rows
                # would land in the wrap margin): cap this round's
                # batch at the rows left before the boundary.
                cap = min(B, S - end % S)
            else:
                cap = B  # store-less: bounded log, old behavior
            taken: list[tuple[_Pending, int, int]] = []
            fill = 0
            while queue and fill + len(queue[0].payloads) <= cap:
                pend = queue.pop(0)
                n = len(pend.payloads)
                taken.append((pend, fill, n))
                fill += n
            if taken:
                # Assemble pre-packed row blocks (C memcpys), then stamp
                # the round term over every row — padding included — in
                # one vectorized write. No per-message work here.
                block = np.zeros((B, SB), np.uint8)
                for pend, start, n in taken:
                    block[start : start + n] = pend.rows
                stamp_term(block, int(self.term[slot]))
                blocks[slot] = block
                counts[slot] = fill
                round_appends[slot] = taken
                round_bases[slot] = end
                adv = -(-fill // ALIGN) * ALIGN
                pred_end[slot] = end + adv
            elif queue and can_trim:
                # The queue head cannot fit before the ring boundary:
                # submit a boundary-padding round (length-0 rows carry
                # the term; decode skips them) so the next round
                # starts the lap at ring position 0.
                pad = S - end % S  # < B here (head <= B did not fit)
                block = np.zeros((B, SB), np.uint8)
                stamp_term(block, int(self.term[slot]))
                blocks[slot] = block
                counts[slot] = pad
                round_appends[slot] = []
                round_bases[slot] = end
                pred_end[slot] = end + pad
            if not queue:
                self._appends.pop(slot, None)

        for slot, queue in list(self._offsets.items()):
            if slot in self._busy_o:
                continue
            taken_off: list[_PendingOffsets] = []
            fill = 0
            while queue and fill + len(queue[0].payloads) <= U:
                pend = queue.pop(0)
                for i, (cslot, off) in enumerate(pend.payloads):
                    off_slots[slot, fill + i] = cslot
                    off_vals[slot, fill + i] = off
                fill += len(pend.payloads)
                taken_off.append(pend)
            if taken_off:
                off_counts[slot] = fill
                round_offsets[slot] = taken_off
            if not queue:
                self._offsets.pop(slot, None)

        if not round_appends and not round_offsets:
            return None
        inp = StepInput(
            entries=self._dummy_entries(),
            counts=counts,
            off_slots=off_slots,
            off_vals=off_vals,
            off_counts=off_counts,
            leader=self.leader.copy(),
            term=self.term.copy(),
            # Rows this round's write must cover (packed_writes clips
            # the append DMA to this; boundary-padding rounds count
            # their padding in `counts`, so the extent covers them too).
            extents=row_extents(counts),
        )
        return inp, {"appends": round_appends, "offsets": round_offsets,
                     "bases": round_bases, "entries": blocks,
                     "counts": {s: int(counts[s]) for s in blocks}}

    def _run(self) -> None:
        """Step thread: drain → dispatch → hand off to the resolver."""
        while not self._stop.is_set():
            ctx = None
            try:
                if self.coalesce_s > 0:
                    with self._lock:
                        # Only pendings on non-busy slots count: queues
                        # behind an in-flight round cannot be drained this
                        # iteration, so sleeping for them delays the
                        # drainable work (and offset commits) for nothing.
                        npend = sum(
                            len(q) for slot, q in self._appends.items()
                            if slot not in self._busy_a
                        )
                    if 0 < npend < self.cfg.max_batch:
                        time.sleep(self.coalesce_s)  # gather the burst
                work = self._drain()
                if work is None:
                    self._work.clear()
                    # Short timeout: pendings for busy slots become
                    # drainable when the resolver clears the slot, which
                    # does not set the work event.
                    self._work.wait(timeout=0.02)
                    continue
                inp, ctx = work
                t_dispatch = self.metrics.clock()
                with self._device_lock:
                    try:
                        if len(ctx["chain"]) == 1:
                            self._state, out = self.fns.step_sparse(
                                self._state, inp, ctx["entries_c"],
                                ctx["slot_ids"], ctx["alive"], ctx["quorum"],
                                ctx["trim"],
                            )
                        else:
                            self._state, out = self.fns.step_many_sparse(
                                self._state, inp, ctx["entries_c"],
                                ctx["slot_ids"], ctx["alive"], ctx["quorum"],
                                ctx["trim"],
                            )
                    except Exception as e:
                        self._adopt_lockstep_state(e)
                        raise
                self.dispatches += 1
                live_rounds = sum(
                    1 for rc in ctx["chain"]
                    if rc["appends"] or rc["offsets"]
                )
                self.rounds += live_rounds
                # Stage 1 of the round-lifecycle decomposition: the
                # (async) device launch call. Stamp t_dispatch in the
                # ctx so the downstream stages (commit fetch, settle
                # entry, acks, persist, release) measure against it.
                t_dispatched = self.metrics.clock()
                self._m_dispatch_us.observe(t_dispatched - t_dispatch)
                self._m_chain_rounds.observe_int(live_rounds)
                ctx["t_dispatch"] = t_dispatch
                ctx["t_dispatched"] = t_dispatched
                self.recorder.record(
                    "dispatch", round_seq=self._dispatch_seq,
                    rounds=live_rounds,
                    slots=len(ctx["appends"]) + len(ctx["offsets"]),
                )
                start_async = getattr(out.committed, "copy_to_host_async",
                                      None)
                if start_async is not None:
                    start_async()  # overlap D2H with later rounds
                with self._lock:
                    self._busy_a |= ctx["appends"].keys()
                    self._busy_o |= ctx["offsets"].keys()
                # Settle-pipeline turn: assigned only to dispatches that
                # reach the resolvers (a seq that never arrives would
                # stall the turnstile forever).
                ctx["seq"] = self._dispatch_seq
                self._dispatch_seq += 1
                # Blocks at pipeline_depth outstanding rounds (backpressure).
                self._inflight.put((inp, ctx, out))
                ctx = None  # now owned by the resolver
            except Exception as e:  # the step thread must never die: fail
                # this round's futures and keep serving (one bad round must
                # not wedge the whole data plane).
                with self._lock:  # counters race the resolver threads
                    self.step_errors += 1
                log.warning("step thread error: %s: %s", type(e).__name__, e)
                if ctx is not None:
                    with self._lock:
                        self._busy_a -= ctx["appends"].keys()
                        self._busy_o -= ctx["offsets"].keys()
                        # The failure may postdate device dispatch (e.g.
                        # the D2H copy kickoff raised on a dropped link),
                        # so the round's outcome is unknown: re-derive
                        # these slots' shadow before their next round.
                        self._shadow_dirty |= ctx["appends"].keys()
                    self._fail_round(ctx, e)

    def _resolve_loop(self) -> None:
        """Resolver thread: land rounds — several run concurrently, so
        landing order is only guaranteed PER SLOT (in-flight rounds touch
        disjoint slots; see the pipeline comment in __init__), not across
        slots. Resolvers stop at the settle handoff: the blocking
        standby-ack wait lives in the settle thread (_settle_loop)."""
        while True:
            try:
                item = self._inflight.get(timeout=0.05)
            except queue.Empty:
                if self._stop.is_set() and not self._thread.is_alive():
                    return
                continue
            self._resolve_one(*item)

    def _resolve_one(self, inp: StepInput, ctx: dict, out) -> None:
        """Fetch one dispatch's outputs (blocking), nack/requeue its
        UNCOMMITTED rounds, and hand the committed work to the settle
        pipeline. Fetch failures fail the whole dispatch here. The
        uncommitted nack runs while the slots are still busy, so retry
        requeues land at the queue front before drain can take later
        submits for the same slot (per-slot FIFO); the busy bits then
        clear at the settle HANDOFF — the device may advance a slot
        whose standby replication is still in flight (the pipelined
        settle window), with reads gated on _settled_end as ever."""
        seq = ctx["seq"]
        entry = None
        try:
            committed = np.asarray(out.committed)  # the ONE device fetch
            # Stage 2: dispatch → committed-fetch landed (device execute
            # + D2H). Wall time since the launch, so queueing behind
            # other dispatches is IN the number — this is the latency a
            # producer's round actually experiences.
            ctx["t_commit"] = self.metrics.clock()
            self._m_commit_wait_us.observe(ctx["t_commit"]
                                           - ctx["t_dispatch"])
            if committed.ndim == 1:
                committed = committed[None]  # single round as a 1-chain
            chain = ctx["chain"]
            n_committed = sum(
                1
                for k, rc in enumerate(chain)
                for slot in set(rc["appends"]) | set(rc["offsets"])
                if committed[k, slot]
            )
            self.recorder.record("commit", round_seq=seq, committed=n_committed)
            records = []
            for k, rc in enumerate(chain):
                records.extend(self._round_records(rc, committed[k]))
            # Chain bases are exact for committed rounds (prefix
            # property, see _drain). The log-end shadow tracks what the
            # DEVICE committed (base arithmetic for subsequent rounds
            # must build past these rows whether or not replication
            # settles them below) — it is NOT a read-visibility
            # watermark; that is _settled_end.
            with self._lock:
                for k, rc in enumerate(chain):
                    for slot in rc["appends"]:
                        n = rc["counts"].get(slot, 0)
                        if committed[k, slot] and n > 0:
                            adv = -(-n // ALIGN) * ALIGN
                            self._log_end[slot] = rc["bases"][slot] + adv
            # Nack in REVERSE round order: failed pendings requeue at
            # the queue FRONT, so the earliest round's retries must be
            # inserted last to land first. Pad charging belongs to the
            # LAST chained round per slot (see _settle_round).
            last_round = {
                slot: k
                for k, rc in enumerate(chain)
                for slot in rc["appends"]
            }
            for k in range(len(chain) - 1, -1, -1):
                rc = chain[k]
                rc["charge_pads"] = {
                    s for s in rc["appends"] if last_round[s] == k
                }
                self._settle_round(rc, rc["bases"], committed[k], ack=False)
            entry = (ctx, committed, records)
        except Exception as e:
            with self._lock:
                self.step_errors += 1
                # The round's device outcome may be unknown (the
                # committed fetch itself failed): re-derive these slots'
                # shadow from the device before their next round.
                self._shadow_dirty |= ctx["appends"].keys()
            log.warning("round resolve error: %s: %s", type(e).__name__, e)
            self._fail_round(ctx, e)
        # Dispatch-order turnstile (see __init__): replication begin and
        # settle-queue entry must follow dispatch order even though
        # resolvers complete out of order. Failed dispatches still take
        # and release their turn, or the sequence would stall.
        with self._turnstile:
            while self._next_turn != seq:
                self._turnstile.wait(timeout=0.5)
        try:
            if entry is not None:
                self._enqueue_settle(entry)
        finally:
            with self._turnstile:
                self._next_turn = seq + 1
                self._turnstile.notify_all()
            with self._lock:
                self._busy_a -= ctx["appends"].keys()
                self._busy_o -= ctx["offsets"].keys()

    def _enqueue_settle(self, entry: tuple) -> None:
        """Start the entry's standby replication (non-blocking when the
        replicator supports begin/wait) and push it into the bounded
        settle window. Called inside the dispatch-order turnstile, so
        the per-slot standby stream order equals dispatch order. Blocks
        when the window is full — the backpressure that bounds how far
        the device may run ahead of standby acks."""
        ctx, committed, records = entry
        # Window slot FIRST (backpressure: the device may run at most
        # settle_window rounds ahead of the standby acks), then begin.
        if not self._settle_sem.acquire(blocking=False):
            with self._lock:
                self.settle_backpressure += 1
            self._settle_sem.acquire()
        # Stage 3: commit → settle-window entry (turnstile ordering +
        # window backpressure). A growing number here with a small
        # commit_wait means the standbys, not the device, are the wall.
        t_enter = self.metrics.clock()
        ctx["t_enter"] = t_enter
        self._m_enter_wait_us.observe(t_enter - ctx.get("t_commit", t_enter))
        self.recorder.record("settle_enter", round_seq=ctx["seq"],
                             records=len(records),
                             depth=self._settle_q.qsize())
        ticket = exc = None
        if records and self.replicate_begin_fn is not None:
            tctxs = None
            if self.spans is not None:
                # Wire-form trace contexts of the sampled produces in
                # this round: the replicators stamp them onto their
                # frames so the standby's apply spans join the trace.
                # Only the 2-arg call when there IS something to carry —
                # single-arg replicate_begin_fn stand-ins stay valid.
                tctxs = [pend.tctx.wire()
                         for rc in ctx["chain"]
                         for taken in rc["appends"].values()
                         for pend, _, _ in taken if pend.tctx is not None]
            try:
                if tctxs:
                    ticket = self.replicate_begin_fn(records, tctxs)
                else:
                    ticket = self.replicate_begin_fn(records)
            except Exception as e:
                # Fencing/empty-set refusal at begin: carried into the
                # window so the release stage fails the entry IN ORDER
                # (acks of earlier rounds still release first).
                exc = e
        with self._lock:
            self.settle_depth_sum += self._settle_q.qsize()
            self.settle_samples += 1
            # Live occupancy (knob_state): held from window entry until
            # _release_one's release — the SLO shed machine's
            # settle-occupancy signal.
            self._settle_inflight += 1
        self._settle_q.put((ctx, committed, records, ticket, exc))

    def _settle_loop(self) -> None:
        """Settle thread: release the window strictly in dispatch order —
        wait out each entry's standby acks, persist, mirror, advance the
        settled-read horizon, settle futures. ONE thread by design: the
        in-order release is what keeps every PR 2 handover invariant
        intact under pipelining."""
        while True:
            try:
                entry = self._settle_q.get(timeout=0.05)
            except queue.Empty:
                if (self._stop.is_set()
                        and not self._thread.is_alive()
                        and not any(r.is_alive() for r in self._resolvers)):
                    return
                continue
            self._release_one(*entry)

    def _release_one(self, ctx: dict, committed, records: list,
                     ticket, exc: Optional[Exception]) -> None:
        chain = ctx["chain"]
        try:
            if self._settle_fenced:
                # Drain-the-window fence: once deposed, NO later round
                # of the window may ack — even one whose standby acks
                # already arrived (they predate the successor epoch and
                # prove nothing against its history).
                from ripplemq_tpu.broker.replication import FencedError

                raise FencedError(
                    "settle window draining: controller deposed"
                )
            if exc is not None:
                raise exc
            # Ack barrier BEFORE the local persist: the local store must
            # only ever contain standby-acked records, or a controller
            # crash between persist and replicate leaves a record that
            # exists NOWHERE else — its restart-recovery then replays
            # and serves a round that was nacked to its producer, and a
            # (possibly late-committing) promotion forgets it again: two
            # divergent histories observed by consumers (the seeded
            # chaos soak caught this as a delivered-message order
            # violation). With this order a crash before persist nacks
            # the round everywhere EXCEPT the standby stores, whose
            # replay is later-record-wins — the retry's re-append at the
            # same base supersedes the orphaned copy.
            t_wait = self.metrics.clock()
            if ticket is not None:
                self.replicate_wait_fn(ticket)
            elif records and self.replicate_fn is not None:
                # No begin/wait split available (plain replicate_fn):
                # synchronous, still strictly in release order.
                self.replicate_fn(records)
            # Stage 4: the standby-ack barrier as the settle thread
            # experiences it (overlap with the pipelined stream means
            # this can be ~0 even when the RPC itself took longer —
            # repl.frame_us has the raw sender-side number).
            t_acked = self.metrics.clock()
            self._m_standby_ack_us.observe(t_acked - t_wait)
            self._persist_round(records)
            # Stage 5: local persist (store framing + any strict-mode
            # inline fsync; store.append_us/fsync_us decompose further).
            t_persist = self.metrics.clock()
            self._m_persist_us.observe(t_persist - t_acked)
            # ---- DURABLY SETTLED from here: the round is persisted AND
            # standby-acked. Only now may readers see its effects —
            # mirror rows (the _cache_end advance admits cache readers),
            # the settled-read horizon, and the consumer-offset shadow.
            # Advancing any of these before the acks landed served
            # state that a controller failover then rolled back: the
            # seeded chaos soak caught it as an acked-commit offset
            # REGRESSION across a promotion (read 24, failover, read 16)
            # — rounds that fail replication are nacked to their
            # producers/committers and must stay invisible to reads.
            # (Residual window: rows of a replication-FAILED round that
            # the ring recycles within this controller's lifetime are
            # store-served below trim — local-store consistent, and only
            # nacked data; acked state never regresses. Pipelining widens
            # the cases that can create such rows — ROADMAP's per-slot
            # settled-gap structure remains the full fix if soaks flag
            # it.)
            self._mirror_records(records)
            mirror_fn = self.mirror_fn
            if mirror_fn is not None:
                for rec_type, slot, base, payload in records:
                    if rec_type == REC_APPEND:
                        mirror_fn(slot, base, payload)
            with self._lock:
                for k, rc in enumerate(chain):
                    for slot in rc["appends"]:
                        n = rc["counts"].get(slot, 0)
                        if committed[k, slot] and n > 0:
                            adv = -(-n // ALIGN) * ALIGN
                            end = rc["bases"][slot] + adv
                            if end > self._settled_end[slot]:
                                self._settled_end[slot] = end
                    for slot, taken_off in rc["offsets"].items():
                        if committed[k, slot]:
                            for pend in taken_off:
                                for cs, off in pend.payloads:
                                    self._offsets_shadow[slot, cs] = off
            for k in range(len(chain) - 1, -1, -1):
                self._settle_round(chain[k], chain[k]["bases"],
                                   committed[k], ack=True)
            # Stage 6 (the whole-round number): dispatch → ack release.
            t0 = ctx.get("t_dispatch")
            t_rel = self.metrics.clock()
            if t0 is not None:
                self._m_release_us.observe(t_rel - t0)
            self.recorder.record("settle_release", round_seq=ctx["seq"],
                                 records=len(records))
            if self.spans is not None:
                self._emit_stage_spans(ctx, t_wait, t_acked, t_persist,
                                       t_rel)
        except Exception as e:
            from ripplemq_tpu.broker.replication import FencedError

            if isinstance(e, FencedError):
                self._settle_fenced = True
            with self._lock:
                self.step_errors += 1
                # Settled-gap recording: every device-committed round of
                # this entry is now NACKED (its futures fail below) while
                # its rows sit in the device ring and its range advanced
                # the log-end shadow. If the slot later settles newer
                # rounds, `_settled_end` passes this range — the gap is
                # what keeps every read path from serving it (the two
                # PR 2 residual windows; see __init__).
                for k, rc in enumerate(ctx["chain"]):
                    for slot in rc["appends"]:
                        n = rc["counts"].get(slot, 0)
                        if committed[k, slot] and n > 0:
                            adv = -(-n // ALIGN) * ALIGN
                            self._add_settled_gap_locked(
                                slot, rc["bases"][slot],
                                rc["bases"][slot] + adv,
                            )
            log.warning("round settle error: %s: %s", type(e).__name__, e)
            self.recorder.record("settle_fail", round_seq=ctx.get("seq", -1),
                                 error=f"{type(e).__name__}: {e}"[:200],
                                 fenced=self._settle_fenced)
            self._fail_committed(ctx, committed, e)
        finally:
            with self._lock:
                self._settle_inflight -= 1
            self._settle_sem.release()

    def _emit_stage_spans(self, ctx: dict, t_wait: float, t_acked: float,
                          t_persist: float, t_rel: float) -> None:
        """Emit the six round-stage spans (PR 5's stage boundaries, now
        ATTRIBUTED) for every sampled batch the settled round carried —
        usually one; an untraced round costs one tctx scan, and an
        untraced PLANE (spans is None) never reaches here. All
        timestamps are metrics.clock() = perf_counter, the span ring's
        own domain. Stage spans are siblings under the produce path's
        span (rpc.recv / worker.hop) that submitted the batch."""
        t0 = ctx.get("t_dispatch")
        if t0 is None:
            return
        tctxs = []
        for rc in ctx["chain"]:
            for taken in rc["appends"].values():
                for pend, _, _ in taken:
                    if pend.tctx is not None:
                        tctxs.append(pend.tctx)
        if not tctxs:
            return
        sp = self.spans
        td = ctx.get("t_dispatched", t0)
        tc = ctx.get("t_commit", td)
        te = ctx.get("t_enter", tc)
        for tctx in tctxs:
            sp.span_at("engine.dispatch", tctx, t0, td - t0)
            sp.span_at("settle.commit_wait", tctx, td, tc - td)
            sp.span_at("settle.enter_wait", tctx, tc, te - tc)
            sp.span_at("settle.standby_ack", tctx, t_wait, t_acked - t_wait)
            sp.span_at("settle.persist", tctx, t_acked, t_persist - t_acked)
            sp.span_at("settle.release", tctx, t0, t_rel - t0)

    def _mirror_records(self, records) -> None:
        """Write committed append rows into the host ring mirror at
        their ring positions and advance the contiguous-prefix
        watermark. Advances are CONTIGUOUS only: a record landing past a
        gap (an earlier round's resolve failed before mirroring) must
        not mark the gap served — reads in it fall through to the
        device ring, the authority the mirror shadows. Writes race only
        readers (the slot's busy bit serializes writers per slot), and
        any reader the write could corrupt is one whose window the trim
        watermark already overran — exactly the race the read path
        re-checks."""
        if self._host_ring is None:
            return
        S, SB = self.cfg.slots, self.cfg.slot_bytes
        for rec_type, slot, base, payload in records:
            if rec_type != REC_APPEND:
                continue
            rows = np.frombuffer(payload, np.uint8).reshape(-1, SB)
            pos = base % S
            self._host_ring[slot, pos : pos + rows.shape[0]] = rows
            with self._lock:
                new_end = base + rows.shape[0]
                if self._cache_end[slot] >= base:
                    self._cache_end[slot] = max(
                        new_end, int(self._cache_end[slot])
                    )
                    continue
                # Mirror gap (an earlier round's resolve failed before
                # mirroring): keep writing and track the contiguous
                # POST-GAP run. Heal when trim passes the run's base:
                # every unmirrored row then sits below trim (store
                # -served; mirror-eligible reads are all >= trim), so
                # the mirror is valid again from run_base to run_end.
                # Comparing trim against the run base — not this
                # record's `base`, which tracks the advancing log end
                # and stays forever ahead of trim — is what lets the
                # heal actually fire (r4 advisor).
                g = self._mirror_gap.get(slot)
                if g is None or base > g[1]:
                    g = self._mirror_gap[slot] = [base, new_end]
                    self._mirror_gap_gen[slot] = (
                        self._mirror_gap_gen.get(slot, 0) + 1
                    )
                else:
                    g[1] = max(g[1], new_end)
                if int(self.trim[slot]) >= g[0]:
                    self._cache_end[slot] = g[1]
                    del self._mirror_gap[slot]

    def _round_records(self, rc: dict, committed
                       ) -> list[tuple[int, int, int, bytes]]:
        """One round's committed writes as store/replication records —
        built from the round ctx's host-side copies (the packed blocks
        the drain shipped to the device, plus counts and bases)."""
        records: list[tuple[int, int, int, bytes]] = []
        for slot in rc["appends"]:
            n = rc["counts"].get(slot, 0)
            if not committed[slot] or n == 0:
                continue
            adv = int(-(-n // ALIGN) * ALIGN)
            payload = rc["entries"][slot][:adv].tobytes()
            records.append(
                (REC_APPEND, int(slot), int(rc["bases"][slot]), payload)
            )
            # Producer-dedup entries ride the SAME record stream, right
            # after their rows (a torn tail may drop the entry, never
            # leave it pointing at unpersisted rows): standbys and boot
            # replay rebuild the dedup table from these, closing the
            # failover dup window.
            ents = [
                (pend.pid, pend.seq, n_taken,
                 int(rc["bases"][slot]) + start)
                for pend, start, n_taken in rc["appends"][slot]
                if pend.pid > 0
            ]
            if ents:
                records.append((
                    REC_PIDSEQ, int(slot), len(ents),
                    b"".join(struct.pack("<IqIq", p, s, k, b)
                             for p, s, k, b in ents),
                ))
        for slot, taken_off in rc["offsets"].items():
            if not committed[slot]:
                continue
            pairs = [p for pend in taken_off for p in pend.payloads]
            payload = b"".join(struct.pack("<II", s, o) for s, o in pairs)
            records.append((REC_OFFSETS, int(slot), len(pairs), payload))
        return records

    def _persist_round(self, records) -> None:
        """Frame this round's committed records into the segment store
        and index the append records for the retention read path. The
        whole round goes down as ONE batched store write when the store
        supports it (SegmentStore.append_many) — per-record appends paid
        a call/GIL round-trip each, which under load was the settle
        stage's dominant cost."""
        if self.store is None or not records:
            return
        append_many = getattr(self.store, "append_many", None)
        if append_many is not None:
            locators = append_many(records)
        else:
            locators = [self.store.append(*rec) for rec in records]
        if self.log_index is not None:
            ends: list[tuple[int, int]] = []
            for (rec_type, slot, base, payload), locator in zip(
                records, locators
            ):
                if rec_type != REC_APPEND:
                    continue
                nrows = len(payload) // self.cfg.slot_bytes
                self.log_index.add(slot, base, nrows, locator)
                ends.append((slot, base + nrows))
            if ends:
                with self._lock:
                    # Only a SUCCESSFUL append moves the persisted
                    # watermark (the trim clamp's authority).
                    for slot, end in ends:
                        if end > self._persisted[slot]:
                            self._persisted[slot] = end
        if self.durability == "strict":
            # Strict deployments opt out of the flush_async lag wholesale:
            # the settle thread fsyncs BEFORE this round's acks release,
            # so an acked round is on disk on the controller (the standby
            # ack path flushes synchronously too — server._handle_repl_
            # rounds) even across a correlated full-cluster kill.
            self.store.flush()
            return
        now = time.monotonic()
        if now - self._last_flush >= self.flush_interval_s:
            # Deferred fsync (same durability lag contract — see
            # SegmentStore.flush_async): the settle thread must not
            # spend its capacity inside the filesystem's fsync latency.
            flush = getattr(self.store, "flush_async", self.store.flush)
            flush()
            self._last_flush = now

    def install(self, image: ReplicaState,
                settled_gaps: Optional[dict[int, list[list[int]]]] = None,
                pid_table: Optional[dict] = None) -> None:
        """Install a recovered single-replica image (see recover_image).
        Re-derives the retention tables: the replayed ring holds at most
        the last `slots` rows per partition, so anything below
        `log_end - slots` is store-only (replay writes exactly the rows
        each record carried — no full-window clobber — hence everything
        ring-resident is intact and servable). `settled_gaps` is the
        recovered store's coverage-hole map (replay_records gaps_out):
        ranges below the final log end that no record covers — exactly
        the rounds this store's controller nacked — re-registered so the
        restarted plane keeps refusing to serve them (without it, a gap
        inside the final ring window reads back as the PREVIOUS lap's
        rows at the wrong offsets)."""
        ends = np.asarray(image.log_end, np.int64)
        with self._lock:
            self._log_end = ends.copy()
            self._persisted = ends.copy()  # the image came FROM the store
            self._settled_end = ends.copy()  # store records are settled
            self._settled_gaps = {
                int(s): [[int(b), int(e)] for b, e in v]
                for s, v in (settled_gaps or {}).items() if v
            }
            if self._host_ring is not None:
                # Seed the mirror from the replayed image: rows land at
                # their ring positions during replay, so the first
                # `slots` rows ARE the ring-resident window.
                self._host_ring[:] = np.asarray(
                    image.log_data, np.uint8
                )[:, : self.cfg.slots]
                self._cache_end = ends.copy()
                self._mirror_gap.clear()
            self.trim = np.maximum(0, ends - self.cfg.slots)
            self._scan_index = None  # history may differ on this store
            self._offsets_shadow = np.asarray(image.offsets, np.int32).copy()
            # Producer-dedup table recovered from the store's REC_PIDSEQ
            # records (replay_records pid_tab_out): the failover half of
            # idempotence — a retry straddling a promotion finds its
            # settled entry here instead of re-appending. In-flight
            # entries belong to the PREVIOUS plane's futures; drop them.
            self._pid_tab = {
                (int(p), int(s)): [tuple(int(x) for x in e) for e in v]
                for (p, s), v in (pid_table or {}).items()
            }
            self._pid_inflight = {}
        with self._device_lock:
            self._state = self.fns.init_from(image)
        self.recorder.record(
            "install", partitions_with_data=int((ends > 0).sum()),
            max_log_end=int(ends.max()),
            gap_slots=len(self._settled_gaps),
        )
        log.info("installed recovered image: %d partitions with data, "
                 "max log end %d", int((ends > 0).sum()), int(ends.max()))

    def _wrap_engine_exc(self, exc: Exception) -> Exception:
        if not isinstance(exc, NotCommittedError):
            if self.broken_reason is not None:
                # Producers must see a RETRYABLE refusal (retry lands on
                # the promoted controller after abdication), not an opaque
                # internal RuntimeError from the lockstep transport.
                exc = NotCommittedError(f"data plane broken: {exc}")
            elif getattr(exc, "retryable", False):
                # Transient engine failure that did NOT condemn the plane
                # (e.g. a pre-broadcast lockstep send failure — the seq
                # was restored, the next round can succeed): same typed
                # refusal, same client retry path.
                exc = NotCommittedError(f"transient engine failure: {exc}")
        return exc

    def _fail_round(self, ctx, exc: Exception) -> None:
        """Fail EVERY future of one dispatch (outcome unknown: dispatch
        or committed-fetch failure — nothing was requeued)."""
        exc = self._wrap_engine_exc(exc)
        for slot, taken in ctx["appends"].items():
            for pend, _, _ in taken:
                self._pid_drop(pend, slot)
                if not pend.future.done():
                    pend.future.set_exception(exc)
        for taken_off in ctx["offsets"].values():
            for pend in taken_off:
                if not pend.future.done():
                    pend.future.set_exception(exc)

    def _fail_committed(self, ctx, committed, exc: Exception) -> None:
        """Fail only the COMMITTED rounds' futures of one dispatch
        (settle-stage failure: replication refused or failed). The
        uncommitted rounds were already nacked/requeued by the resolver
        — their pendings may be live in the queues again and must not
        be touched."""
        exc = self._wrap_engine_exc(exc)
        for k, rc in enumerate(ctx["chain"]):
            for slot, taken in rc["appends"].items():
                if not committed[k, slot]:
                    continue
                for pend, _, _ in taken:
                    self._pid_drop(pend, slot)
                    if not pend.future.done():
                        pend.future.set_exception(exc)
            for slot, taken_off in rc["offsets"].items():
                if not committed[k, slot]:
                    continue
                for pend in taken_off:
                    if not pend.future.done():
                        pend.future.set_exception(exc)

    def settle_stats(self) -> dict:
        """Settle-pipeline occupancy snapshot (bench/admin surface):
        mean window depth sampled at each enqueue, plus how many
        enqueues found the window full (backpressure engaged)."""
        with self._lock:
            samples = self.settle_samples
            return {
                "window": self.settle_window,
                "occupancy_mean": (
                    round(self.settle_depth_sum / samples, 3)
                    if samples else 0.0
                ),
                "samples": samples,
                "backpressure_waits": self.settle_backpressure,
            }

    def postmortem(self) -> dict:
        """The engine section of a postmortem bundle (obs/postmortem.py):
        the PR 4 term-skew cross-section — control tables vs device
        scalars in ONE snapshot — plus stall streaks, settled gaps,
        settle-window occupancy, degradation, and retry budgets. All
        wire-encodable (str keys, plain ints/lists).

        One device-lock hold spanning three leaf fetches (terms,
        commits, log ends — under lockstep, three broadcast calls): a
        one-shot diagnosis RPC, not a polling surface — on a busy plane
        the fetches wait out the dispatch pipeline exactly like any
        other state fetch (see busy()), so expect the RPC to stall up
        to a few dispatch drains on a loaded broker. A FAILING
        fetch (broken lockstep plane — exactly a state this bundle
        exists to diagnose) degrades to a host-only bundle with
        `device_error` set instead of losing the control tables, stall
        streaks, and gaps that never needed the device."""
        device_error = None
        P = self.cfg.partitions
        try:
            with self._device_lock:
                dev_terms = self._fetch_state("current_term").max(axis=0)
                dev_commit = self._fetch_state("commit").max(axis=0)
                dev_ends = self._fetch_state("log_end").max(axis=0)
        except Exception as e:
            device_error = f"{type(e).__name__}: {e}"[:200]
            dev_terms = np.full((P,), -1, np.int64)
            dev_commit = np.full((P,), -1, np.int64)
            dev_ends = np.full((P,), -1, np.int64)
        with self._lock:
            leader = self.leader.copy()
            term = self.term.copy()
            host_end = self._log_end.copy()
            settled = self._settled_end.copy()
            persisted = self._persisted.copy()
            trim = self.trim.copy()
            streaks = dict(self._nocommit_streak)
            gaps = {
                int(s): [[int(b), int(e)] for b, e in v]
                for s, v in self._settled_gaps.items() if v
            }
        # The wedge signature, precomputed: the control table advertises
        # a term BEHIND what the device granted — every dispatch at the
        # table's term is refused, commits freeze, the leader looks
        # healthy. (PR 4: ctrl_table_term=[5,5] vs device=[8,8].) With
        # the device unreachable (-1 sentinels) no slot reads skewed.
        skew = [
            int(s) for s in range(self.cfg.partitions)
            if int(dev_terms[s]) > int(term[s])
        ]
        return {
            "partitions": self.cfg.partitions,
            "device_error": device_error,
            "ctrl_table": {
                "leader": [int(x) for x in leader],
                "term": [int(x) for x in term],
            },
            "device_current_terms": [int(x) for x in dev_terms],
            "device_commit": [int(x) for x in dev_commit],
            "device_log_ends": [int(x) for x in dev_ends],
            "host_log_end": [int(x) for x in host_end],
            "settled_end": [int(x) for x in settled],
            "persisted": [int(x) for x in persisted],
            "trim": [int(x) for x in trim],
            "term_skew_slots": skew,
            "stall_streaks": {str(s): int(n) for s, n in streaks.items()},
            "stalled_slots": self.stalled_slots(),
            "settled_gaps": {str(s): v for s, v in gaps.items()},
            "mirror_gap_slots": self.mirror_gap_slots(),
            "pid_table_size": self.pid_table_size(),
            "settle": self.settle_stats(),
            "degraded_slots": self.degraded_slots(),
            "retry_budget": {
                "max_retry_rounds": self.max_retry_rounds,
                "pipeline_depth": self.pipeline_depth,
                "chain_depth": self.chain_depth,
                "settle_window": self.settle_window,
                "round_retries": self._m_retries.n
                if hasattr(self._m_retries, "n") else 0,
                "retry_exhausted": self._m_retry_exhausted.n
                if hasattr(self._m_retry_exhausted, "n") else 0,
            },
            "counters": {
                "rounds": self.rounds,
                "dispatches": self.dispatches,
                "committed_entries": self.committed_entries,
                "step_errors": self.step_errors,
                "read_queries": self.read_queries,
                "read_dispatches": self.read_dispatches,
                "read_cache_hits": self.read_cache_hits,
            },
        }

    def _settle_round(self, ctx, base: dict, committed, ack: bool) -> None:
        """One round's future settlement, in two phases. `ack=False`
        (resolver, slots still busy): nack/requeue the round's
        UNCOMMITTED work so retries reach the queue front before later
        submits drain. `ack=True` (settle thread, strictly in dispatch
        order after the standby acks landed): release the COMMITTED
        work's futures."""
        if ack:
            # Producer-dedup bookkeeping FIRST, in one lock hold and
            # strictly before any future resolves: a wire-dup of an
            # acked batch must find either the in-flight entry (pre-
            # settle) or the table entry (post-settle) — never the gap
            # between them (which would re-append an acked batch).
            any_pid = any(
                pend.pid > 0
                for slot, taken in ctx["appends"].items()
                if committed[slot]
                for pend, _, _ in taken
            )
            if any_pid:
                with self._lock:
                    for slot, taken in ctx["appends"].items():
                        if not committed[slot]:
                            continue
                        for pend, start, n in taken:
                            if pend.pid <= 0:
                                continue
                            ents = self._pid_tab.setdefault(
                                (pend.pid, slot), []
                            )
                            ents.append(
                                (pend.seq, pend.seq + n,
                                 int(base[slot]) + start)
                            )
                            del ents[:-_PID_WINDOW]
                            self._pid_inflight.pop(
                                (pend.pid, slot, pend.seq), None
                            )
            new_entries = 0
            for slot, taken in ctx["appends"].items():
                if committed[slot]:
                    for pend, start, n in taken:
                        new_entries += n
                        if not pend.future.done():
                            pend.future.set_result(int(base[slot]) + start)
            for slot, taken_off in ctx["offsets"].items():
                if committed[slot]:
                    for pend in taken_off:
                        if not pend.future.done():
                            pend.future.set_result(True)
            if new_entries:
                with self._lock:
                    self.committed_entries += new_entries
            return
        # No-commit streak bookkeeping (this resolver pass sees every
        # dispatched round exactly once): a committed round clears its
        # slots, an uncommitted one lengthens them — see stalled_slots().
        touched = set(ctx["appends"]) | set(ctx["offsets"])
        if touched:
            with self._lock:
                for slot in touched:
                    if committed[slot]:
                        self._nocommit_streak.pop(slot, None)
                    else:
                        self._nocommit_streak[slot] = (
                            self._nocommit_streak.get(slot, 0) + 1
                        )
        requeue_a: list[tuple[int, _Pending]] = []
        requeue_o: list[tuple[int, _PendingOffsets]] = []
        for slot, taken in ctx["appends"].items():
            if committed[slot]:
                continue  # released by the ack phase after standby acks
            # Distinguish permanent backpressure (log full) from a
            # transient quorum outage. Only index-less deployments
            # (no store, or a store the drain cannot trim against)
            # can fill permanently: the write phase needs a full
            # max_batch window past the leader's log end and nothing
            # is ever trimmed, so base + B > slots means no retry can
            # ever fit. With a log index the drain raises trim and
            # retries commit.
            full = (
                self.log_index is None
                and base[slot] + self.cfg.max_batch > self.cfg.slots
                and base[slot] > 0
            )
            for pend, _, _ in taken:
                pend.rounds_left -= 1
                if full:
                    self._pid_drop(pend, slot)
                    if not pend.future.done():  # caller may cancel()
                        pend.future.set_exception(
                            PartitionFullError(
                                f"partition {slot}: log full "
                                f"({base[slot]}/{self.cfg.slots} used)"
                            )
                        )
                elif pend.rounds_left <= 0:
                    self._m_retry_exhausted.inc()
                    self._pid_drop(pend, slot)
                    if not pend.future.done():
                        pend.future.set_exception(
                            NotCommittedError(
                                f"partition {slot}: no quorum after "
                                f"{self.max_retry_rounds} rounds"
                            )
                        )
                else:
                    requeue_a.append((slot, pend))
        # Failed boundary-pad rounds (empty taken) must still charge the
        # blocked queue head's retry budget: the head is what forced the
        # pad, and without this a quorum outage at the ring boundary would
        # regenerate failing pads forever while the producer's future
        # hangs past max_retry_rounds. `charge_pads` (chain dispatch)
        # restricts charging to slots whose LAST chained round was the
        # failed pad — if a later round of the same chain took the head,
        # that round's own settle already charged it.
        charge = ctx.get("charge_pads")
        pad_failures = [
            slot for slot, taken in ctx["appends"].items()
            if not taken and not committed[slot]
            and (charge is None or slot in charge)
        ]
        if pad_failures:
            with self._lock:
                for slot in pad_failures:
                    q = self._appends.get(slot)
                    if not q:
                        continue
                    head = q[0]
                    head.rounds_left -= 1
                    if head.rounds_left <= 0:
                        q.pop(0)
                        if not q:
                            self._appends.pop(slot, None)
                        self._pid_drop_locked(head, slot)
                        if not head.future.done():  # caller may cancel()
                            head.future.set_exception(
                                NotCommittedError(
                                    f"partition {slot}: no quorum after "
                                    f"{self.max_retry_rounds} rounds (ring-"
                                    f"boundary pad)"
                                )
                            )
        for slot, taken_off in ctx["offsets"].items():
            if committed[slot]:
                continue  # released by the ack phase after standby acks
            for pend in taken_off:
                pend.rounds_left -= 1
                if pend.rounds_left <= 0:
                    self._m_retry_exhausted.inc()
                    if not pend.future.done():  # caller may cancel()
                        pend.future.set_exception(
                            NotCommittedError(
                                f"partition {slot}: no quorum"
                            )
                        )
                else:
                    requeue_o.append((slot, pend))
        if requeue_a or requeue_o:
            self._m_retries.inc(len(requeue_a) + len(requeue_o))
            with self._lock:
                for slot, pend in reversed(requeue_a):
                    self._appends.setdefault(slot, []).insert(0, pend)
                for slot, pend in reversed(requeue_o):
                    self._offsets.setdefault(slot, []).insert(0, pend)
            self._work.set()


def recover_image(cfg: EngineConfig, store_dir: str,
                  use_native: Optional[bool] = None,
                  gaps_out: Optional[dict] = None,
                  pid_tab_out: Optional[dict] = None
                  ) -> Optional[ReplicaState]:
    """Replay a segment store directory into a single-replica state image,
    healing erasure-protected sealed segments first: a missing/corrupt
    sealed segment is rebuilt from any 3 of its 5 RS shards (the torn-
    tail contract of replay_records only covers the ACTIVE segment's
    tail). `gaps_out` receives the store's settled-gap map (see
    replay_records) for DataPlane.install; `pid_tab_out` the recovered
    producer-dedup table."""
    from ripplemq_tpu.storage.erasure import repair_store

    repair_store(store_dir)
    return replay_records(cfg, scan_store(store_dir, use_native),
                          gaps_out=gaps_out, pid_tab_out=pid_tab_out)


def replay_records(cfg: EngineConfig, records,
                   gaps_out: Optional[dict] = None,
                   pid_tab_out: Optional[dict] = None
                   ) -> Optional[ReplicaState]:
    """Replay committed-round records into a single-replica state image.

    Returns None if there are no records. Only committed rounds are ever
    persisted/replicated, so the rebuilt image is a valid post-commit
    state for EVERY replica slot (install via DataPlane.install). The
    replay is the recovery path the reference inherits from JRaft's log
    replay (SURVEY.md §5 checkpoint) — here it also re-derives the cached
    last_term from the tail row's embedded header.

    Later records win per slot: a record's base may regress below an
    earlier record's end (a controller-failover standby can hold an
    UNSETTLED round the promoted controller never had — the new
    generation's rounds re-cover those rows) and may leave a zero-row gap
    (the standby missed an unsettled round the deposed controller
    persisted locally). Both only ever affect rows whose producers were
    NEVER acked; zero rows read back as alignment padding.

    Record bases are ABSOLUTE storage offsets; rows land at their ring
    positions (base % slots), so a partition that wrapped the ring many
    times replays to exactly the last `slots` rows — older rows stay
    store-only, served through the log index (core.state ring doc).

    `gaps_out` (optional dict) receives {slot: [[begin, end), ...]} —
    the COVERAGE HOLES between this store's records, below each slot's
    final log end. A hole is a round the writing controller committed on
    device but never settled (replication failed → never persisted):
    exactly the settled gaps DataPlane.install must re-register, because
    a hole inside the final ring window otherwise replays as the
    PREVIOUS lap's rows at the wrong offsets. Ring rows inside such
    holes are zeroed here too (zero rows read back as alignment
    padding), so even a read path that misses the gap clamp cannot
    serve a stale lap.
    """
    P, S, SB, C = cfg.partitions, cfg.slots, cfg.slot_bytes, cfg.max_consumers
    log_data = np.zeros((P, S + cfg.max_batch, SB), np.uint8)
    log_end = np.zeros((P,), np.int32)
    last_term = np.zeros((P,), np.int32)
    commit = np.zeros((P,), np.int32)
    offsets = np.zeros((P, C), np.int32)
    coverage: dict[int, list[list[int]]] = {}
    found = False
    for rec_type, slot, base, payload in records:
        if not 0 <= slot < P:
            raise ValueError(
                f"record for partition {slot} outside engine shape P={P} "
                f"(store written under a different config?)"
            )
        if rec_type == REC_APPEND:
            if len(payload) % SB:
                raise ValueError(
                    f"append payload of {len(payload)} bytes is not a "
                    f"multiple of slot_bytes {SB}"
                )
            rows = np.frombuffer(payload, np.uint8).reshape(-1, SB)
            n = rows.shape[0]
            pos = base % S
            if pos + n > S:
                raise ValueError(
                    f"replayed round laps the ring ({base}%{S}+{n}>{S}; "
                    f"store written under a different config?)"
                )
            log_data[slot, pos : pos + n] = rows
            log_end[slot] = base + n
            commit[slot] = base + n
            last_term[slot] = int(
                np.frombuffer(rows[-1, 4:8].tobytes(), np.int32)[0]
            )
            # Coverage bookkeeping mirrors the later-records-win replay:
            # a regressing record drops/truncates everything at-or-above
            # its base before extending (same rule as LogIndex.add).
            cov = coverage.setdefault(slot, [])
            while cov and cov[-1][0] >= base:
                cov.pop()
            if cov and cov[-1][1] > base:
                cov[-1][1] = base
            if cov and cov[-1][1] == base:
                cov[-1][1] = base + n
            else:
                cov.append([base, base + n])
        elif rec_type == REC_OFFSETS:
            for cs, off in struct.iter_unpack("<II", payload):
                if cs < C:
                    offsets[slot, cs] = off
        elif rec_type == REC_PIDSEQ:
            # Producer-dedup entries (idempotent producers): rebuild the
            # (pid, slot) → recent-settled-batches table alongside the
            # image. Scan order matters only within a key; a re-covered
            # round's retry carries the same (pid, seq), so replayed
            # duplicates collapse into equivalent entries.
            if pid_tab_out is not None:
                for pid, seq, n, b in struct.iter_unpack("<IqIq", payload):
                    ents = pid_tab_out.setdefault((int(pid), int(slot)), [])
                    ents.append((int(seq), int(seq) + int(n), int(b)))
                    del ents[:-_PID_WINDOW]
        found = True
    if not found:
        return None
    for slot, cov in coverage.items():
        gaps = [
            [cov[i - 1][1], cov[i][0]]
            for i in range(1, len(cov))
            if cov[i][0] > cov[i - 1][1]
        ]
        if not gaps:
            continue
        end = int(log_end[slot])
        for b, e in gaps:
            # Zero the hole's rows inside the final ring window: they
            # hold whatever an earlier lap's record replayed there. The
            # window clamp bounds e - lo to at most S rows, so the range
            # is at most two contiguous ring spans (split at the wrap).
            lo = max(b, end - S)
            if lo >= e:
                continue
            p0 = lo % S
            n = e - lo
            if p0 + n <= S:
                log_data[slot, p0 : p0 + n] = 0
            else:
                log_data[slot, p0:S] = 0
                log_data[slot, : p0 + n - S] = 0
        if gaps_out is not None:
            gaps_out[slot] = gaps
    return ReplicaState(
        log_data=log_data,
        log_end=log_end,
        last_term=last_term,
        current_term=last_term.copy(),
        commit=commit,
        offsets=offsets,
    )
