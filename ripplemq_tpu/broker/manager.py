"""PartitionManager: the control-plane brain on every broker.

Role-for-role equivalent of the reference's PartitionManager (reference:
mq-broker/src/main/java/metadata/PartitionManager.java), re-shaped for the
TPU architecture:

- It is the metadata Raft's STATE MACHINE: `apply()` consumes committed
  commands (topic/assignment rewrites, leader advertisements, consumer
  registrations) in log order on every broker — the
  TopicsStateMachine.setTopics + handleTopicListChange pair (reference
  TopicsStateMachine.java:49-78, PartitionManager.java:111-164).
- Where the reference starts/stops one JRaft server per partition, here a
  topics change only rewrites CONTROL TABLES of the always-running device
  program: per-partition leader slot, term, replica-liveness mask and
  quorum (partition "start/stop" is a mask flip, never a shape change —
  SURVEY.md §7 hard parts).
- Cluster-leader duties (run by whichever broker holds the metadata Raft
  lease): assignment refresh on membership change
  (handleMembershipChange, PartitionManager.java:72-109).
- Controller duties (the broker driving the TPU mesh): batched
  elections for leaderless partitions and lag repair (resync) — the
  host-coordinated election design (SURVEY.md §7 layer 5).

Static slot map: topics are config-defined (as in the reference — no
runtime topic creation, SURVEY.md §5 config), so (topic, partition) →
engine slot is a pure function of the config, identical on every broker.
"""

from __future__ import annotations

import dataclasses
import threading

from ripplemq_tpu.obs.lockwitness import make_rlock
import time
from typing import Optional

import numpy as np

from ripplemq_tpu.broker.dataplane import DataPlane
from ripplemq_tpu.groups.coordinator import GroupTable
from ripplemq_tpu.groups.state import group_consumer_name
from ripplemq_tpu.metadata.assigner import assign_partitions
from ripplemq_tpu.metadata.cluster_config import ClusterConfig
from ripplemq_tpu.metadata.models import (
    RANGE_SPACE,
    GroupKey,
    PartitionAssignment,
    Topic,
    placement_only,
    topics_from_wire,
    topics_to_wire,
)
from ripplemq_tpu.stripes.codec import stripe_assignment

class ConsumerTableFullError(Exception):
    """All `max_consumers` device-table slots are bound to names. The
    reference's consumerOffsets map grows without bound and never refuses
    (PartitionStateMachine.java:27); this framework's table is a fixed
    [P, C] device tensor, so the refusal must exist — and must surface as
    a typed, client-distinguishable error rather than `internal:`."""


# Metadata-plane command ops (the hostraft log's vocabulary).
OP_SET_TOPICS = "set_topics"
OP_SET_LEADER = "set_leader"
OP_REGISTER_CONSUMER = "register_consumer"
# Idempotent producers: the metadata plane ISSUES producer ids (one
# replicated counter — a pid must be unique across every broker and
# every process lifetime, or two producers' sequence spaces collide in
# the broker's dedup table).
OP_REGISTER_PRODUCER = "register_producer"
# Producer-id expiry (the PR 7 grow-forever residual): the metadata
# leader reaps a pid idle past pid_retention_s. Registration is also
# the SESSION REFRESH — re-registering an existing name bumps its
# replicated `seen` counter — and the reap command carries the counter
# value the leader observed, so the apply re-checks idleness and a
# racing refresh/produce-driven re-register always wins. Reaped pids
# are never reissued (next_pid is monotone); the attached dataplane
# drops the pid's dedup entries in the same apply.
OP_RETIRE_PRODUCER = "retire_producer"
# Consumer-slot recycling: release frees a name→slot binding but parks
# the slot as DIRTY (its device offset row still holds the old
# consumer's positions); the controller resets the row through ordinary
# offset rounds and proposes slot_clean, which returns the slot to the
# allocatable pool. Split into two ops so allocation stays a pure
# function of replicated state — a slot is never handed out while any
# broker could still serve its stale offsets.
OP_RELEASE_CONSUMER = "release_consumer"
OP_CONSUMER_SLOT_CLEAN = "consumer_slot_clean"
# Consumer groups (groups/): membership changes are replicated ops; the
# assignment is recomputed deterministically inside the apply, so every
# broker advertises the identical generation + partition map.
OP_GROUP_JOIN = "group_join"
OP_GROUP_LEAVE = "group_leave"
# Reaping an EMPTY group after its retention window (metadata-leader
# duty): the apply is conditional on the group still being empty, so a
# racing re-join always wins. Only here does the group's shared
# consumer slot release — an emptied-but-retained group keeps its
# generation and offsets (see GroupTable.leave).
OP_GROUP_DELETE = "group_delete"
# Controller-failover ops (broker/replication.py): which broker drives
# the device program (fenced by a monotone epoch) and which brokers hold
# a full copy of its committed-round stream (the standby set).
OP_SET_CONTROLLER = "set_controller"
OP_SET_STANDBYS = "set_standbys"
# Follower-read leases (broker/follower.py): which standbys may answer
# consumes from their replicated settled floor, and under WHICH
# controller epoch. The grant is {broker_id: epoch}; an apply whose
# epoch is not the current controller epoch is ignored, and every
# controller handover clears the whole table — a deposed generation's
# lease can never authorize serving past the new generation's trim/gap
# map. Brokers re-check the lease per answered read (server.py), so
# revocation is one metadata round, not a timeout.
OP_SET_FOLLOWER_LEASES = "set_follower_leases"
# Elastic partitions (online split/merge). OP_SPLIT_PARTITION carves a
# parent's key-hash range at its midpoint into a child partition placed
# on a SPARE engine slot (the engine's [P, R] shape is fixed at boot, so
# elasticity spends pre-provisioned slots: `engine.partitions` beyond
# the configured topic total; with no spare slot the apply is a
# deterministic no-op). The split bumps the parent's generation, opens
# the HANDOFF window (the parent's leader dual-writes migrated-range
# traffic into the child's slot), and revokes every follower-read lease
# (the handover fence discipline reapplied — the lease duty re-grants
# once the child's floor is live). OP_SPLIT_CUTOVER closes the window:
# proposed by the controller only once the parent's settled floor has
# reached the watermark recorded at split begin (no write acked before
# the split can be lost to a post-cutover failover) — both generations
# bump again so any still-handoff-stamped client re-resolves.
# OP_MERGE_PARTITIONS reabsorbs an adjacent split child's range into
# its parent and RETIRES the child: produces draw the typed
# `stale_partition_gen:` refusal with routing to the parent, while the
# child's log stays readable for consumers draining it.
OP_SPLIT_PARTITION = "split_partition"
OP_SPLIT_CUTOVER = "split_cutover"
OP_MERGE_PARTITIONS = "merge_partitions"
# N commands applied atomically as ONE hostraft entry. Exists because a
# thousand-partition election wave must not pay a thousand per-entry
# proposal/broadcast costs: the controller advertises every winner of a
# batched device ballot in one replicated command (the reference has no
# analogue — each JRaft group advertises its own leader independently,
# PartitionManager.java:200-253).
OP_BATCH = "batch"


def build_slot_map(config: ClusterConfig) -> dict[GroupKey, int]:
    """Deterministic (topic, partition) → engine-slot mapping."""
    keys = [
        (t.name, pid) for t in config.topics for pid in range(t.partitions)
    ]
    keys.sort()
    return {k: i for i, k in enumerate(keys)}


class PartitionManager:
    def __init__(
        self,
        broker_id: int,
        config: ClusterConfig,
        dataplane: Optional[DataPlane] = None,
    ) -> None:
        self.broker_id = broker_id
        self.config = config
        self.dataplane = dataplane
        self.slot_map = build_slot_map(config)
        self.lock = make_rlock("PartitionManager.lock")

        # Replicated state (the metadata Raft's state machine).
        self.topics: list[Topic] = []
        self.live: list[int] = list(config.broker_ids())
        self.consumers: dict[str, int] = {}
        # Recycled-but-unreset consumer slots: released bindings whose
        # device offset rows still hold the old consumer's positions.
        # Not allocatable until the controller's reset rounds land and
        # OP_CONSUMER_SLOT_CLEAN applies (see the op comments above).
        self.dirty_consumer_slots: set[int] = set()
        # Idempotent-producer registry: name → pid, plus the replicated
        # pid counter (pid 0 is reserved = "no pid").
        self.producers: dict[str, int] = {}
        self.next_pid = 1
        # Replicated session-refresh counter per producer name: bumped
        # by every (re-)registration; the reaper's OP_RETIRE_PRODUCER
        # names the value it observed and the apply drops the pid only
        # if it still matches (idleness re-checked at apply time).
        self.producer_seen: dict[str, int] = {}
        # Consumer groups: replicated membership/generation/assignment.
        self.groups = GroupTable()
        # True while an OP_BATCH wave is expanding (lock held): group
        # membership sub-ops defer their rebalance to the wave end.
        self._in_wave = False
        # Optional flight recorder (the owning BrokerServer's): group
        # lifecycle events — join/leave/eviction/generation bumps — are
        # control-plane transitions a rebalance timeline needs.
        self.recorder = None
        self._applied_index = 0
        # Controller-failover state: the active controller, its fencing
        # epoch, and the standby set holding its committed-round stream.
        # Epoch 0 is the config-designated bootstrap controller.
        self.controller_broker: int = config.controller
        self.controller_epoch: int = 0
        self.standbys: tuple[int, ...] = ()
        # Stripe→member assignment (replication="striped"): derived
        # deterministically from the standby set inside every standby-
        # set apply and recorded beside it, so "who holds stripe i" is
        # replicated metadata promotion can consult (stripes/codec.
        # stripe_assignment; recovery still asks every live broker, so
        # the map is routing truth, not a safety dependency).
        self.stripe_holders: tuple[int, ...] = ()
        # Follower-read leases: standby broker → controller epoch the
        # lease was granted under (OP_SET_FOLLOWER_LEASES). Only entries
        # matching the CURRENT epoch authorize serving; the table is
        # cleared on every controller handover.
        self.follower_leases: dict[int, int] = {}
        # Elastic partitions: dynamic (topic, pid) → engine-slot
        # extension for split children (replicated — assigned inside
        # the split apply from the spare-slot pool, so every broker
        # routes a child identically), and the open handoff windows:
        # (topic, parent_pid) → {"child": pid, "watermark": parent log
        # end the proposer observed at split begin}. Replicated so a
        # controller that fails over mid-handoff still finishes the
        # cutover.
        self.dyn_slots: dict[GroupKey, int] = {}
        self.handoffs: dict[GroupKey, dict] = {}
        # Election debounce: slot → when it was first seen leaderless.
        # A partition must stay leaderless for config.election_timeout_s
        # before the controller ballots it (the role JRaft's per-group
        # election timeout plays in the reference,
        # PartitionRaftServer.java:85); repeated failed ballots are
        # likewise spaced by the timeout.
        self._leaderless_since: dict[int, float] = {}

    # ------------------------------------------------- state machine hooks

    def apply(self, index: int, cmd: dict) -> None:
        """hostraft apply_fn: committed metadata commands, in log order."""
        with self.lock:
            self._applied_index = index
            if cmd.get("op") == OP_BATCH:
                # One WAVE: sub-ops expand in order, but each touched
                # group's rebalance is deferred to the end of the wave —
                # N membership events to one group cost ONE generation
                # bump and ONE assignment compute, and a duplicate wave
                # (leader retry straddling a failover re-proposing the
                # same cmds) finds every sub-op a no-op and bumps
                # nothing. The wave flag routes _apply_group_join/_leave
                # onto the deferred path; everything else applies
                # exactly as it would standalone.
                self._in_wave = True
                try:
                    for sub in cmd["cmds"]:
                        self._apply_one(sub)
                finally:
                    self._in_wave = False
                    self._finish_wave()
            else:
                self._apply_one(cmd)

    def _finish_wave(self) -> None:
        """Rebalance every group the wave changed (lock held)."""
        parts = {t.name: t.partitions for t in self.config.topics}
        for group, st in self.groups.finish_wave(parts):
            if self.recorder is not None:
                self.recorder.record(
                    "group_rebalance", group=group,
                    generation=st.generation, members=len(st.members),
                )

    def _apply_one(self, cmd: dict) -> None:
        """One command, lock held (apply + OP_BATCH expansion)."""
        op = cmd.get("op")
        if op == OP_SET_TOPICS:
            self._apply_set_topics(
                topics_from_wire(cmd["topics"]), [int(b) for b in cmd["live"]]
            )
        elif op == OP_SET_LEADER:
            self._apply_set_leader(
                cmd["topic"], int(cmd["partition"]),
                None if cmd["leader"] is None else int(cmd["leader"]),
                int(cmd["term"]),
            )
        elif op == OP_REGISTER_CONSUMER:
            self._apply_register_consumer(str(cmd["consumer"]), int(cmd["slot"]))
        elif op == OP_REGISTER_PRODUCER:
            self._apply_register_producer(str(cmd["producer"]))
        elif op == OP_RETIRE_PRODUCER:
            self._apply_retire_producer(
                str(cmd["producer"]), int(cmd["seen"])
            )
        elif op == OP_RELEASE_CONSUMER:
            self._apply_release_consumer(str(cmd["consumer"]))
        elif op == OP_CONSUMER_SLOT_CLEAN:
            self.dirty_consumer_slots.discard(int(cmd["slot"]))
        elif op == OP_GROUP_JOIN:
            self._apply_group_join(
                str(cmd["group"]), str(cmd["member"]),
                tuple(str(t) for t in cmd["topics"]),
            )
        elif op == OP_GROUP_LEAVE:
            self._apply_group_leave(
                str(cmd["group"]), str(cmd["member"]),
                str(cmd.get("reason", "leave")),
            )
        elif op == OP_GROUP_DELETE:
            self._apply_group_delete(str(cmd["group"]))
        elif op == OP_SET_CONTROLLER:
            self._apply_set_controller(
                int(cmd["controller"]), int(cmd["epoch"]),
                [int(b) for b in cmd["standbys"]],
            )
        elif op == OP_SET_STANDBYS:
            self._apply_set_standbys(
                int(cmd["epoch"]), [int(b) for b in cmd["standbys"]]
            )
        elif op == OP_SET_FOLLOWER_LEASES:
            self._apply_set_follower_leases(
                int(cmd["epoch"]),
                {int(b): int(e) for b, e in dict(cmd["leases"]).items()},
            )
        elif op == OP_SPLIT_PARTITION:
            self._apply_split(
                str(cmd["topic"]), int(cmd["partition"]),
                int(cmd.get("watermark", 0)),
            )
        elif op == OP_SPLIT_CUTOVER:
            self._apply_split_cutover(
                str(cmd["topic"]), int(cmd["partition"]),
                int(cmd.get("watermark", 0)),
            )
        elif op == OP_MERGE_PARTITIONS:
            self._apply_merge(
                str(cmd["topic"]), int(cmd["parent"]), int(cmd["child"])
            )
        # Unknown ops are ignored (forward compatibility).

    def snapshot(self) -> dict:
        """hostraft snapshot_fn — metadata state for log compaction."""
        with self.lock:
            return {
                "topics": topics_to_wire(self.topics),
                "live": list(self.live),
                "consumers": dict(self.consumers),
                "dirty_consumer_slots": sorted(self.dirty_consumer_slots),
                "producers": dict(self.producers),
                "producer_seen": dict(self.producer_seen),
                "next_pid": self.next_pid,
                "groups": self.groups.to_wire(),
                "controller": self.controller_broker,
                "controller_epoch": self.controller_epoch,
                "standbys": list(self.standbys),
                "stripe_holders": list(self.stripe_holders),
                "follower_leases": {
                    str(b): int(e) for b, e in self.follower_leases.items()
                },
                # Elastic partitions: the dynamic slot extension and the
                # open handoff windows ("topic|pid" keys — wire codecs
                # want string map keys).
                "dyn_slots": {
                    f"{t}|{p}": int(s)
                    for (t, p), s in self.dyn_slots.items()
                },
                "handoffs": {
                    f"{t}|{p}": dict(h)
                    for (t, p), h in self.handoffs.items()
                },
            }

    def restore(self, state: dict) -> None:
        """hostraft restore_fn — install a metadata snapshot."""
        with self.lock:
            self.consumers = {str(k): int(v) for k, v in state["consumers"].items()}
            # Pre-groups snapshots lack the newer sections: default them
            # empty (same forward-compatibility rule as unknown ops).
            self.dirty_consumer_slots = {
                int(s) for s in state.get("dirty_consumer_slots", ())
            }
            self.producers = {
                str(k): int(v) for k, v in state.get("producers", {}).items()
            }
            self.producer_seen = {
                str(k): int(v)
                for k, v in state.get("producer_seen", {}).items()
            }
            self.next_pid = int(state.get("next_pid", 1))
            self.groups = GroupTable.from_wire(state.get("groups", {}))
            # Controller fields default to bootstrap values for snapshots
            # written before the failover machinery existed.
            self.controller_broker = int(
                state.get("controller", self.config.controller)
            )
            self.controller_epoch = int(state.get("controller_epoch", 0))
            self.standbys = tuple(int(b) for b in state.get("standbys", ()))
            self.stripe_holders = tuple(
                int(b) for b in state.get(
                    "stripe_holders", stripe_assignment(self.standbys)
                )
            )
            # Pre-follower-reads snapshots: no leases were granted.
            self.follower_leases = {
                int(b): int(e)
                for b, e in state.get("follower_leases", {}).items()
            }
            # Pre-elastic snapshots: no dynamic children, no handoffs.
            self.dyn_slots = {
                (k.rsplit("|", 1)[0], int(k.rsplit("|", 1)[1])): int(s)
                for k, s in state.get("dyn_slots", {}).items()
            }
            self.handoffs = {
                (k.rsplit("|", 1)[0], int(k.rsplit("|", 1)[1])):
                    {"child": int(h["child"]),
                     "watermark": int(h.get("watermark", 0))}
                for k, h in state.get("handoffs", {}).items()
            }
            self._apply_set_topics(
                topics_from_wire(state["topics"]),
                [int(b) for b in state["live"]],
                full_surface=True,
            )

    def _apply_set_controller(
        self, controller: int, epoch: int, standbys: list[int]
    ) -> None:
        """Monotone-epoch controller handover (stale proposals ignored)."""
        if epoch <= self.controller_epoch:
            return
        self.controller_broker = controller
        self.controller_epoch = epoch
        self.standbys = tuple(b for b in standbys if b != controller)
        self.stripe_holders = stripe_assignment(self.standbys)
        # Generation fence: every handover revokes ALL follower-read
        # leases — the new controller's duty re-grants to the standbys
        # it trusts, under the new epoch.
        self.follower_leases = {}

    def _apply_set_standbys(self, epoch: int, standbys: list[int]) -> None:
        """Standby-set rewrite, valid only within the current epoch."""
        if epoch != self.controller_epoch:
            return
        self.standbys = tuple(
            b for b in standbys if b != self.controller_broker
        )
        self.stripe_holders = stripe_assignment(self.standbys)
        # Brokers dropped from the standby set stop replicating — their
        # floor parks, so their lease goes with their membership.
        self.follower_leases = {
            b: e for b, e in self.follower_leases.items()
            if b in self.standbys
        }

    def _apply_set_follower_leases(
        self, epoch: int, leases: dict[int, int]
    ) -> None:
        """Install the follower-read lease table, valid only within the
        current controller epoch (a stale grant — proposed before a
        handover committed — must not authorize the old generation)."""
        if epoch != self.controller_epoch:
            return
        self.follower_leases = {
            int(b): int(e) for b, e in leases.items()
            if int(b) != self.controller_broker and b in self.standbys
        }

    # ------------------------------------------- elastic-partition applies

    def _used_slots_locked(self) -> set[int]:
        return set(self.slot_map.values()) | set(self.dyn_slots.values())

    def _next_spare_slot_locked(self) -> Optional[int]:
        """Lowest engine slot not owned by any configured or dynamic
        partition (deterministic: replicated state + config only)."""
        used = self._used_slots_locked()
        for s in range(self.config.engine.partitions):
            if s not in used:
                return s
        return None

    def _find_topic(self, name: str) -> Optional[int]:
        for i, t in enumerate(self.topics):
            if t.name == name:
                return i
        return None

    def _replace_assignment(self, ti: int, assign: PartitionAssignment) -> None:
        t = self.topics[ti]
        assigns = tuple(
            assign if a.partition_id == assign.partition_id else a
            for a in t.assignments
        )
        self.topics[ti] = t.with_assignments(assigns)

    def _apply_split(self, topic: str, pid: int, watermark: int) -> None:
        """Split `pid`'s key-hash range at its midpoint into a new child
        partition on a spare engine slot. Deterministic no-op when the
        parent is missing, not active, un-splittable (range width < 2),
        capped (split_max_partitions), or no spare slot remains."""
        ti = self._find_topic(topic)
        if ti is None:
            return
        t = self.topics[ti]
        parent = t.assignment_for(pid)
        if parent is None or parent.state != "active":
            return
        if parent.range_hi - parent.range_lo < 2:
            return
        cap = int(self.config.split_max_partitions)
        if cap and t.partitions >= cap:
            return
        slot = self._next_spare_slot_locked()
        if slot is None:
            return
        mid = (parent.range_lo + parent.range_hi) // 2
        child_pid = t.partitions
        gen = parent.generation + 1
        new_parent = dataclasses.replace(
            parent, generation=gen, range_hi=mid, state="handoff",
        )
        child = PartitionAssignment(
            partition_id=child_pid,
            replicas=parent.replicas,
            # The child starts under the PARENT's leader (dual-write
            # wants one serialization point); term 1 distinguishes the
            # grant from "never led". An election re-places it freely.
            leader=parent.leader,
            term=max(1, parent.term),
            generation=gen,
            range_lo=mid,
            range_hi=parent.range_hi,
            state="handoff",
            origin=pid,
        )
        assigns = tuple(
            new_parent if a.partition_id == pid else a
            for a in t.assignments
        ) + (child,)
        self.topics[ti] = dataclasses.replace(
            t, partitions=t.partitions + 1, assignments=assigns,
        )
        self.dyn_slots[(topic, child_pid)] = slot
        self.handoffs[(topic, pid)] = {
            "child": child_pid, "watermark": int(watermark),
        }
        # Fence discipline: revoke every follower-read lease FIRST —
        # the lease duty re-grants (same epoch) only after this apply
        # is visible everywhere, so no standby serves the pre-split
        # routing while the child's floor comes live.
        self.follower_leases = {}
        if self.dataplane is not None:
            self._push_control_tables()
        if self.recorder is not None:
            self.recorder.record(
                "split_begin", topic=topic, partition=pid,
                child=child_pid, slot=slot, mid=mid, generation=gen,
                watermark=int(watermark),
            )

    def _apply_split_cutover(self, topic: str, pid: int,
                             watermark: int) -> None:
        """Close a handoff window: parent and child both return to
        "active" under a bumped generation (clients still stamped with
        the handoff generation re-resolve). The proposer (controller
        reconfig duty) gates this on the parent's settled floor having
        reached the split-begin watermark."""
        ho = self.handoffs.get((topic, pid))
        if ho is None:
            return
        ti = self._find_topic(topic)
        if ti is None:
            return
        t = self.topics[ti]
        parent = t.assignment_for(pid)
        child = t.assignment_for(int(ho["child"]))
        if parent is None or child is None or parent.state != "handoff":
            self.handoffs.pop((topic, pid), None)
            return
        gen = max(parent.generation, child.generation) + 1
        self._replace_assignment(ti, dataclasses.replace(
            parent, generation=gen, state="active"))
        self._replace_assignment(ti, dataclasses.replace(
            child, generation=gen, state="active"))
        self.handoffs.pop((topic, pid), None)
        if self.recorder is not None:
            self.recorder.record(
                "split_cutover", topic=topic, partition=pid,
                child=int(ho["child"]), generation=gen,
                watermark=int(watermark),
            )

    def _apply_merge(self, topic: str, parent_pid: int,
                     child_pid: int) -> None:
        """Reabsorb an adjacent split child's range into its parent and
        retire the child. No-op unless (parent, child) is an active
        split pair with adjacent ranges and no open handoff."""
        ti = self._find_topic(topic)
        if ti is None:
            return
        t = self.topics[ti]
        parent = t.assignment_for(parent_pid)
        child = t.assignment_for(child_pid)
        if parent is None or child is None:
            return
        if child.origin != parent_pid or (topic, parent_pid) in self.handoffs:
            return
        if parent.state != "active" or child.state != "active":
            return
        if parent.range_hi != child.range_lo:
            return  # not adjacent (an intervening split re-carved it)
        gen = max(parent.generation, child.generation) + 1
        self._replace_assignment(ti, dataclasses.replace(
            parent, generation=gen, range_hi=child.range_hi))
        self._replace_assignment(ti, dataclasses.replace(
            child, generation=gen, range_lo=child.range_hi,
            state="retired"))
        # Same fence as the split: routing changed, revoke leases; the
        # duty re-grants under the unchanged epoch.
        self.follower_leases = {}
        if self.recorder is not None:
            self.recorder.record(
                "merge_done", topic=topic, partition=parent_pid,
                child=child_pid, generation=gen,
            )

    def _apply_register_consumer(self, name: str, slot: int) -> None:
        """Idempotent consumer registration. The proposed slot was chosen
        from a PRE-proposal read, so two concurrent registrations can
        propose the same slot; the apply path (serialized by the Raft log,
        identical on every broker) resolves the collision by assigning the
        lowest free slot instead."""
        if name in self.consumers:
            return
        used = set(self.consumers.values()) | self.dirty_consumer_slots
        if slot in used:
            C = self.config.engine.max_consumers
            free = [s for s in range(C) if s not in used]
            if not free:
                return  # table full; registration request will time out
            slot = free[0]
        self.consumers[name] = slot

    def _apply_register_producer(self, name: str) -> None:
        """Issue one pid per producer name (idempotent — the client's
        registration proposal may be retried/duplicated). The counter is
        replicated state: a pid is unique across brokers AND process
        lifetimes, which is what makes it a safe dedup-table key.
        Re-registering an EXISTING name is the session refresh: it
        bumps the replicated seen counter the reaper's idleness check
        keys on (see OP_RETIRE_PRODUCER)."""
        self.producer_seen[name] = self.producer_seen.get(name, 0) + 1
        if name in self.producers:
            return
        self.producers[name] = self.next_pid
        self.next_pid += 1

    def _apply_retire_producer(self, name: str, seen: int) -> None:
        """Reap one idle pid — ONLY if its seen counter still equals
        what the proposing leader observed: a registration refresh (or
        a fresh client re-registering the name) racing the reap bumps
        the counter and the reap no-ops, so an active producer never
        loses its dedup window to a stale idleness observation."""
        if self.producer_seen.get(name, 0) != seen:
            return
        pid = self.producers.pop(name, None)
        self.producer_seen.pop(name, None)
        if pid is not None and self.dataplane is not None:
            # The controller's dedup table drops the reaped pid's
            # entries in the same apply (other brokers have no table).
            self.dataplane.drop_pids({pid})

    def _apply_release_consumer(self, name: str) -> None:
        """Free a consumer-name binding (group dissolution, member
        eviction, or explicit release). The slot parks as DIRTY until
        the controller's offset-reset rounds land (see the op comments):
        reallocating it immediately would hand the new consumer the old
        one's committed positions. The reference never releases at all —
        its consumerOffsets map grows without bound
        (PartitionStateMachine.java:27); this closes that as a recycle
        instead of the PR-seed's refuse-only stance."""
        slot = self.consumers.pop(name, None)
        if slot is not None:
            self.dirty_consumer_slots.add(slot)

    def _apply_group_join(self, group: str, member: str,
                          topics: tuple[str, ...]) -> None:
        if self._in_wave:
            st, changed = self.groups.join_deferred(group, member, topics)
        else:
            parts = {t.name: t.partitions for t in self.config.topics}
            st, changed = self.groups.join(group, member, topics, parts)
        if changed and self.recorder is not None:
            self.recorder.record(
                "group_join", group=group, member=member,
                generation=st.generation, members=len(st.members),
            )

    def _apply_group_leave(self, group: str, member: str,
                           reason: str) -> None:
        if self._in_wave:
            st, changed, emptied = self.groups.leave_deferred(group, member)
        else:
            parts = {t.name: t.partitions for t in self.config.topics}
            st, changed, emptied = self.groups.leave(group, member, parts)
        # An emptied group is RETAINED (generation + offsets intact):
        # transient total-churn must not reset the group's identity.
        # The metadata leader reaps it via OP_GROUP_DELETE only after
        # group_retention_s of continuous emptiness.
        if changed and self.recorder is not None:
            self.recorder.record(
                "group_leave", group=group, member=member, reason=reason,
                generation=st.generation if st is not None else -1,
                emptied=emptied,
            )

    def _apply_group_delete(self, group: str) -> None:
        """Reap an empty group past retention: only NOW does the shared
        offset slot release into the recycle path — the multi-tenant
        workload's groups come and go without exhausting the fixed
        [P, C] device table."""
        if self.groups.delete(group):
            self._apply_release_consumer(group_consumer_name(group))
            if self.recorder is not None:
                self.recorder.record("group_delete", group=group)

    def _apply_set_topics(self, topics: list[Topic], live: list[int],
                          *, full_surface: bool = False) -> None:
        old_alive = self._alive_mask() if self.dataplane is not None else None
        # OP SPLIT (PR 4 residual, load-bearing once placement moves
        # across mesh shards): OP_SET_TOPICS owns PLACEMENT only. The
        # (leader, term) surface belongs entirely to OP_SET_LEADER, so
        # an apply here sources it from the replicated CURRENT table —
        # whatever the payload carries is ignored (proposals strip it
        # anyway, metadata.models.placement_only). A stale topics
        # snapshot therefore can never regress the advertised term below
        # the device current_term (the permanent write wedge the chaos
        # plane caught), by construction rather than by merge. The
        # current table is replicated state, so every broker's apply
        # converges identically. A leader whose broker left the replica
        # set becomes unknown (the partition re-elects); its term is
        # kept — terms only move forward.
        #
        # `full_surface=True` is the SNAPSHOT-INSTALL path (restore):
        # a snapshot is the full applied state at a log index and must
        # carry leaders/terms; the original term-monotonic merge guards
        # it against a current table that is already ahead.
        merged: list[Topic] = []
        for t in topics:
            cur = next((c for c in self.topics if c.name == t.name), None)
            assigns = list(t.assignments)
            for j, a in enumerate(assigns):
                ca = cur.assignment_for(a.partition_id) if cur else None
                if full_surface:
                    if ca is None:
                        continue
                    keep_elastic = ca.generation > a.generation
                    if ca.term <= a.term and not keep_elastic:
                        continue
                    upd = a
                    if ca.term > a.term:
                        keep = ca.leader if (
                            ca.leader is None or ca.leader in a.replicas
                        ) else None
                        upd = dataclasses.replace(
                            upd, leader=keep, term=ca.term
                        )
                    if keep_elastic:
                        # Generations only move forward, like terms: a
                        # snapshot taken before a local split/merge
                        # applied must not regress the routing surface.
                        upd = dataclasses.replace(
                            upd, generation=ca.generation,
                            range_lo=ca.range_lo, range_hi=ca.range_hi,
                            state=ca.state, origin=ca.origin,
                        )
                    assigns[j] = upd
                elif ca is None:
                    # New partition: no leader until OP_SET_LEADER. Its
                    # genesis key-hash range is its 1/n-th share of the
                    # space (the payload is placement-stripped): with
                    # the overlapping full-range defaults, route_key
                    # would send every key to pid 0 and a split child's
                    # range would stay shadowed by its full-range
                    # siblings forever.
                    n = max(1, int(t.partitions))
                    assigns[j] = dataclasses.replace(
                        a, leader=None, term=0,
                        range_lo=(RANGE_SPACE * a.partition_id) // n,
                        range_hi=(RANGE_SPACE * (a.partition_id + 1)) // n,
                    )
                else:
                    keep = (ca.leader
                            if ca.leader is not None
                            and ca.leader in a.replicas else None)
                    # The elastic surface (generation/range/state/
                    # origin) is owned by the split/merge applies, same
                    # as (leader, term) is owned by OP_SET_LEADER:
                    # source it from the replicated current table, not
                    # the (stripped) placement payload.
                    assigns[j] = dataclasses.replace(
                        a, leader=keep, term=ca.term,
                        generation=ca.generation, range_lo=ca.range_lo,
                        range_hi=ca.range_hi, state=ca.state,
                        origin=ca.origin,
                    )
            npids = {a.partition_id for a in assigns}
            nparts = t.partitions
            if cur is not None:
                # Dynamic split children live past the configured shape:
                # a placement payload built from config.topics (the
                # assigner's shape) must never drop them.
                for ca in cur.assignments:
                    if ca.partition_id not in npids:
                        assigns.append(ca)
                nparts = max(nparts, cur.partitions, len(assigns))
            assigns.sort(key=lambda a: a.partition_id)
            merged.append(dataclasses.replace(
                t, partitions=nparts, assignments=tuple(assigns),
            ))
        topics = merged
        self.topics = topics
        self.live = live
        if self.dataplane is None:
            return
        self._push_control_tables()
        # Repair: replica slots that just came (back) alive have missed
        # commits; copy the leader's partition state over them. Under
        # atomic rounds a lagging replica never diverges, so a full-slot
        # copy from the leader is always safe.
        new_alive = self._alive_mask()
        came_alive = new_alive & ~old_alive
        self._resync_slots(came_alive)

    def _apply_set_leader(
        self, topic: str, pid: int, leader: Optional[int], term: int
    ) -> None:
        for i, t in enumerate(self.topics):
            if t.name != topic:
                continue
            assigns = list(t.assignments)
            for j, a in enumerate(assigns):
                if a.partition_id == pid:
                    if term < a.term:
                        # Stale advert (terms only move forward): a
                        # lower-term OP_SET_LEADER applying after a
                        # newer election would regress the control
                        # table below the device current_term — the
                        # permanent write wedge the chaos plane caught.
                        # Applies are deterministic across brokers, so
                        # every replica skips it identically.
                        return
                    assigns[j] = dataclasses.replace(a, leader=leader, term=term)
            self.topics[i] = t.with_assignments(tuple(assigns))
        if self.dataplane is not None:
            slot = self._slot_for(topic, pid)
            if slot is not None:
                assign = self.assignment_of((topic, pid))
                leader_slot = -1
                if assign and leader is not None and leader in assign.replicas:
                    leader_slot = assign.replicas.index(leader)
                self.dataplane.set_leader(slot, leader_slot, term)

    # -------------------------------------------------- control-table sync

    def _slot_for(self, topic: str, pid: int) -> Optional[int]:
        """(topic, pid) → engine slot across BOTH maps: the static
        config-derived map and the replicated dynamic extension split
        children live in. Lock not required — the static map is
        immutable and dyn_slots reads ride the caller's apply lock or
        tolerate a racy miss (same contract as slot_map.get did)."""
        slot = self.slot_map.get((topic, pid))
        if slot is None:
            slot = self.dyn_slots.get((topic, pid))
        return slot

    def _alive_mask(self) -> np.ndarray:
        """[P, R] mask: replica slot r of partition p is alive iff the
        broker holding it is in the live set. Unassigned slots are dead."""
        cfg = self.dataplane.cfg
        alive = np.zeros((cfg.partitions, cfg.replicas), bool)
        live = set(self.live)
        for t in self.topics:
            for a in t.assignments:
                slot = self._slot_for(t.name, a.partition_id)
                if slot is None:
                    continue
                for r, b in enumerate(a.replicas[: cfg.replicas]):
                    alive[slot, r] = b in live
        return alive

    def _push_control_tables(self) -> None:
        cfg = self.dataplane.cfg
        # Unassigned slots (the SPARE pool splits spend) carry NO quorum
        # contract: quorum 0 over an all-dead alive row, so they never
        # read as quorum-lost (degraded_slots / the SLO shed signal
        # would otherwise see every spare slot as permanently degraded
        # and shed a healthy cluster). A split's apply re-pushes these
        # tables, promoting the child slot to its topic's real quorum.
        quorum = np.zeros((cfg.partitions,), np.int32)
        for t in self.topics:
            q = t.replication_factor // 2 + 1
            for a in t.assignments:
                slot = self._slot_for(t.name, a.partition_id)
                if slot is None:
                    continue
                quorum[slot] = q
                leader_slot = -1
                if a.leader is not None and a.leader in a.replicas:
                    leader_slot = a.replicas.index(a.leader)
                self.dataplane.set_leader(slot, leader_slot, a.term)
        self.dataplane.set_quorum(quorum)
        self.dataplane.set_alive(self._alive_mask())

    def _resync_slots(self, came_alive: np.ndarray) -> None:
        """Group newly-alive (partition, replica-slot) cells by (leader
        slot, dst slot) and issue batched resyncs. Partitions that are
        leaderless at this point are picked up by the periodic
        `plan_repairs` pass once they elect (a slot that comes alive while
        leaderless lags the eventual leader by log_end, which is exactly
        what plan_repairs keys on)."""
        pairs: dict[tuple[int, int], list[int]] = {}
        for key, slot in list(self.slot_map.items()) + list(
                self.dyn_slots.items()):
            assign = self.assignment_of(key)
            if assign is None or assign.leader is None:
                continue
            if assign.leader not in assign.replicas:
                continue
            src = assign.replicas.index(assign.leader)
            for r in range(self.dataplane.cfg.replicas):
                if came_alive[slot, r] and r != src:
                    pairs.setdefault((src, r), []).append(slot)
        for (src, dst), slots in pairs.items():
            self.dataplane.resync(src, dst, slots)

    def plan_repairs(
        self, log_ends: Optional[np.ndarray] = None
    ) -> dict[tuple[int, int], list[int]]:
        """Controller lag repair: alive replica slots whose log end trails
        their partition leader's, grouped into batched (src, dst) resyncs.
        Run periodically from the controller duty — this is the documented
        'lag repair' pass, and it covers the cases the event-driven
        `_resync_slots` cannot: slots that came alive while the partition
        was leaderless, and followers that missed rounds committed by a
        quorum that excluded them. Safe because atomic ballot-before-write
        rounds guarantee a lagging replica holds a strict prefix of the
        leader's log (never diverged), so a full-slot copy only moves it
        forward. `log_ends` lets the duty loop share one [R, P] device
        snapshot between this and plan_elections per tick."""
        with self.lock:
            if self.dataplane is None:
                return {}
            if log_ends is None:
                log_ends = self.dataplane.log_ends()  # [R, P]
            R = self.dataplane.cfg.replicas
            live = set(self.live)
            pairs: dict[tuple[int, int], list[int]] = {}
            for t in self.topics:
                for a in t.assignments:
                    slot = self._slot_for(t.name, a.partition_id)
                    if slot is None or a.leader is None or a.leader not in live:
                        continue
                    if a.leader not in a.replicas:
                        continue
                    src = a.replicas.index(a.leader)
                    if src >= R:
                        continue
                    src_end = int(log_ends[src, slot])
                    for r, b in enumerate(a.replicas[:R]):
                        if r == src or b not in live:
                            continue
                        if int(log_ends[r, slot]) < src_end:
                            pairs.setdefault((src, r), []).append(slot)
            return pairs

    # -------------------------------------------- dataplane attach/detach

    def attach_dataplane(self, dataplane: DataPlane) -> None:
        """Bind a (newly booted) device program and push the current
        replicated control state into its tables — the takeover half of
        controller failover (broker/server.py _takeover_duty)."""
        with self.lock:
            self.dataplane = dataplane
            if self.topics:
                self._push_control_tables()

    def detach_dataplane(self) -> Optional[DataPlane]:
        """Unbind the device program (controller fencing); returns it."""
        with self.lock:
            dp, self.dataplane = self.dataplane, None
            return dp

    # ------------------------------------------------------------- queries

    def current_controller(self) -> int:
        with self.lock:
            return self.controller_broker

    def current_epoch(self) -> int:
        with self.lock:
            return self.controller_epoch

    def current_standbys(self) -> tuple[int, ...]:
        with self.lock:
            return self.standbys

    def current_stripe_map(self) -> tuple[int, ...]:
        """The replicated stripe→member assignment (empty when no
        standby ever joined, or in replication='full' deployments —
        the map is derived from the standby set either way)."""
        with self.lock:
            return self.stripe_holders

    def live_brokers(self) -> list[int]:
        """The replicated liveness view (locked copy) — the striped
        plane's below-k refusal keys on holders that are both set
        members AND live."""
        with self.lock:
            return list(self.live)

    def follower_lease(self, broker_id: int) -> Optional[int]:
        """The epoch this broker's follower-read lease was granted
        under, or None. Valid only when it equals current_epoch() — the
        caller re-checks BOTH per answered read (server.py)."""
        with self.lock:
            return self.follower_leases.get(int(broker_id))

    def current_follower_leases(self) -> dict[int, int]:
        """Locked copy of the lease table (metadata advertisement +
        admin.stats)."""
        with self.lock:
            return dict(self.follower_leases)

    def get_topics(self) -> list[Topic]:
        with self.lock:
            return list(self.topics)

    def assignment_of(self, key: GroupKey) -> Optional[PartitionAssignment]:
        topic, pid = key
        for t in self.topics:
            if t.name == topic:
                return t.assignment_for(pid)
        return None

    def leader_of(self, key: GroupKey) -> Optional[int]:
        with self.lock:
            a = self.assignment_of(key)
            return a.leader if a else None

    def slot_of(self, key: GroupKey) -> Optional[int]:
        with self.lock:
            return self._slot_for(key[0], key[1])

    def replica_slot(self, key: GroupKey, broker_id: int) -> Optional[int]:
        """This broker's replica-slot index within the partition's set."""
        with self.lock:
            a = self.assignment_of(key)
            if a is None or broker_id not in a.replicas:
                return None
            return a.replicas.index(broker_id)

    def generation_of(self, key: GroupKey) -> Optional[int]:
        """Current reconfiguration generation of one partition (None =
        unknown partition) — what request-stamped `pgen` fences against."""
        with self.lock:
            a = self.assignment_of(key)
            return a.generation if a else None

    def route_key(self, topic: str, key_hash: int) -> Optional[int]:
        """The NON-RETIRED partition owning `key_hash`'s range slice
        (None when the topic is unknown). During a handoff the child
        already owns the migrated slice — routing truth moves at split
        begin; the parent's dual-write forward covers stale senders."""
        with self.lock:
            for t in self.topics:
                if t.name != topic:
                    continue
                for a in t.assignments:
                    if a.state != "retired" and a.owns_key(int(key_hash)):
                        return a.partition_id
            return None

    def current_handoffs(self) -> dict[GroupKey, dict]:
        """Locked copy of the open handoff windows (the controller's
        reconfig duty drives each to cutover)."""
        with self.lock:
            return {k: dict(h) for k, h in self.handoffs.items()}

    def merge_candidates(self) -> list[tuple[str, int, int]]:
        """(topic, parent, child) triples currently mergeable: active
        split children whose range is still adjacent to their parent's
        and whose parent has no open handoff."""
        with self.lock:
            out = []
            for t in self.topics:
                for a in t.assignments:
                    if a.origin < 0 or a.state != "active":
                        continue
                    if (t.name, a.origin) in self.handoffs:
                        continue
                    p = t.assignment_for(a.origin)
                    if (p is not None and p.state == "active"
                            and p.range_hi == a.range_lo):
                        out.append((t.name, a.origin, a.partition_id))
            return out

    def spare_slot_count(self) -> int:
        with self.lock:
            return self.config.engine.partitions - len(
                self._used_slots_locked()
            )

    def mapped_slots(self) -> set[int]:
        """Every engine slot the topic table currently maps (static
        config slots + dynamic split children) — what the follower
        plane prunes its per-slot serve state against."""
        with self.lock:
            return self._used_slots_locked()

    def reconfig_stats(self) -> dict:
        """The admin.stats `reconfig` block's replicated half (the
        server adds its local forward/fence counters): split/merge
        topology derived from the topic table, open handoffs, and the
        spare-slot pool."""
        with self.lock:
            children = retired = handoff = 0
            for t in self.topics:
                for a in t.assignments:
                    if a.origin >= 0:
                        children += 1
                    if a.state == "retired":
                        retired += 1
                    elif a.state == "handoff":
                        handoff += 1
            return {
                "children": children,
                "retired": retired,
                "handoff_partitions": handoff,
                "open_handoffs": [
                    {"topic": t, "partition": p,
                     "child": int(h["child"]),
                     "watermark": int(h["watermark"])}
                    for (t, p), h in sorted(self.handoffs.items())
                ],
                "spare_slots": self.config.engine.partitions - len(
                    self._used_slots_locked()
                ),
            }

    def consumer_slot(self, consumer: str) -> Optional[int]:
        with self.lock:
            return self.consumers.get(consumer)

    def next_consumer_slot(self) -> int:
        """Lowest unused consumer slot (proposals are idempotent: the
        first registration for a name wins, duplicates are no-ops)."""
        with self.lock:
            used = set(self.consumers.values()) | self.dirty_consumer_slots
            C = self.config.engine.max_consumers
            for s in range(C):
                if s not in used:
                    return s
            raise ConsumerTableFullError(
                f"consumer table full ({C} slots in use)"
            )

    def producer_id(self, name: str) -> Optional[int]:
        """Replicated pid for a registered producer name (None until the
        registration op applies locally)."""
        with self.lock:
            return self.producers.get(name)

    def producer_sessions(self) -> dict[str, tuple[int, int]]:
        """name → (pid, seen counter), a locked copy — the reaper
        duty's working set (BrokerServer._pid_reap_duty)."""
        with self.lock:
            return {
                n: (pid, self.producer_seen.get(n, 0))
                for n, pid in self.producers.items()
            }

    def registered_pids(self) -> tuple[set[int], int]:
        """(currently-registered pids, locally-applied pid counter) —
        the dedup-table reconciliation set plus its VALIDITY FLOOR: a
        pid at-or-above the local next_pid was issued by a registration
        this replica has not applied yet, so its absence from the
        registry proves nothing and the reconciler must not drop its
        entries (a freshly registered producer can settle batches on
        the controller before the controller's own apply catches up)."""
        with self.lock:
            return set(self.producers.values()), self.next_pid

    def group_state(self, group: str):
        """A WIRE-COPY of one group's replicated state (GroupState), or
        None. Copied so callers never hold a reference the next apply
        mutates under them."""
        from ripplemq_tpu.groups.state import GroupState

        with self.lock:
            st = self.groups.state(group)
            return None if st is None else GroupState.from_wire(st.to_wire())

    def groups_summary(self) -> dict:
        with self.lock:
            return self.groups.summary()

    def empty_groups(self) -> list[str]:
        """Groups retained with zero members (reap candidates once the
        retention window lapses — BrokerServer._group_duty)."""
        with self.lock:
            return self.groups.empty_groups()

    def dirty_slots(self) -> list[int]:
        """Recycled consumer slots awaiting their offset reset (the
        controller's slot-clean duty drains these)."""
        with self.lock:
            return sorted(self.dirty_consumer_slots)

    # ------------------------------------------- cluster-leader duty logic

    def plan_assignment(self, alive_brokers: list[int]) -> Optional[dict]:
        """Called on the metadata leader: if the live set changed (or no
        assignments exist yet), return a set_topics command to propose —
        the reference's membership-monitor + assigner path
        (TopicsRaftServer.java:202-217 → PartitionManager.java:72-109)."""
        with self.lock:
            have_assignments = any(t.assignments for t in self.topics)
            if have_assignments and sorted(alive_brokers) == sorted(self.live):
                return None
            base = self.topics if have_assignments else list(self.config.topics)
            try:
                new_topics = assign_partitions(
                    list(self.config.topics), alive_brokers,
                    previous=base if have_assignments else None,
                )
            except ValueError:
                # Not enough live brokers to meet RF. Keep the old
                # PLACEMENT — but still advance the LIVE view: leader
                # elections key on `self.live` (needs_elections/
                # plan_elections), so freezing it would leave a dead
                # broker's partitions leaderless forever whenever
                # RF == cluster size (the surviving quorum can and must
                # still elect among itself — the reference's JRaft groups
                # re-elect independently of placement,
                # PartitionRaftServer.java:83-93).
                if not have_assignments:
                    return None
                return {
                    "op": OP_SET_TOPICS,
                    # Placement-only payload (metadata.models.placement_only):
                    # the (leader, term) surface is OP_SET_LEADER's domain,
                    # so a proposal snapshot can never carry — and a racing
                    # apply can never revert — an election's advert.
                    "topics": topics_to_wire(placement_only(self.topics)),
                    "live": sorted(alive_brokers),
                }
            return {
                "op": OP_SET_TOPICS,
                "topics": topics_to_wire(placement_only(new_topics)),
                "live": sorted(alive_brokers),
            }

    def plan_controller(self, alive_brokers: list[int]) -> Optional[dict]:
        """Called on the metadata leader: controller-failover planning.

        Dead controller → promote the lowest-id live STANDBY under a
        bumped epoch (only set members hold the full committed-round
        stream — promoting anyone else would lose acked data, so with no
        live standby the plane stays down until the controller returns,
        exactly the pre-failover behavior). Live controller → prune dead
        brokers from the standby set (the controller duty re-adds fresh
        ones via catch-up). The reference's analogue is JRaft re-electing
        any partition's leader among surviving replicas
        (PartitionRaftServer.java:83-93)."""
        with self.lock:
            alive = set(alive_brokers)
            if self.controller_broker in alive:
                if any(s not in alive for s in self.standbys):
                    return {
                        "op": OP_SET_STANDBYS,
                        "epoch": self.controller_epoch,
                        "standbys": [s for s in self.standbys if s in alive],
                    }
                return None
            return self._promote_cmd([s for s in self.standbys if s in alive])

    def _promote_cmd(self, cands: list[int]) -> Optional[dict]:
        """Promotion command shared by dead-controller failover and
        broken-plane abdication (one handover contract; lock held).
        Lowest live standby wins under a bumped epoch."""
        if not cands:
            return None
        new = min(cands)
        return {
            "op": OP_SET_CONTROLLER,
            "controller": new,
            "epoch": self.controller_epoch + 1,
            "standbys": [s for s in cands if s != new],
        }

    def plan_abdication(self) -> Optional[dict]:
        """Called on a controller whose OWN data plane is permanently
        broken (lockstep mesh break — the broker is alive, so the
        metadata leader's dead-controller planning never fires): hand
        controllership to the lowest-id live standby under a bumped
        epoch. Same safety rule as plan_controller: only standby-set
        members hold the full committed-round stream; with no live
        standby the plane stays down (returns None) rather than losing
        acked data."""
        with self.lock:
            if self.controller_broker != self.broker_id:
                return None
            return self._promote_cmd([
                s for s in self.standbys
                if s in self.live and s != self.broker_id
            ])

    def plan_standby_add(self, target_count: int) -> Optional[int]:
        """Called on the controller: pick one live broker to catch up and
        admit to the standby set (None if the set is at target). The
        lowest id wins so repeated calls are stable."""
        with self.lock:
            if self.controller_broker != self.broker_id:
                return None
            live = set(self.live)
            others = live - {self.broker_id}
            want = min(target_count, len(others))
            members_live = [s for s in self.standbys if s in live]
            if len(members_live) >= want:
                return None
            cands = sorted(others - set(self.standbys))
            return cands[0] if cands else None

    # --------------------------------------------- controller duty logic

    def needs_elections(self) -> bool:
        """Cheap host-only pre-check for the controller duty: would
        plan_elections actually NOMINATE anyone? plan_elections needs a
        device log-ends fetch to pick candidates; that fetch holds the
        device lock for a full host-device round trip, so the duty loop
        must not pay it every tick — neither on a healthy cluster nor
        for a partition that is leaderless but CANNOT elect (quorum of
        its replicas dead) or is inside its election debounce window.
        Mirrors plan_elections' own gates (leaderless, quorum of live
        replicas, debounce elapsed) without stamping the debounce
        table."""
        with self.lock:
            if self.dataplane is None:
                return False
            live = set(self.live)
            R = self.dataplane.cfg.replicas
            now = time.monotonic()
            # Device-term-skew wedge probe (host-only, no device fetch):
            # a slot whose rounds ALL fail to commit despite a live
            # leader is election-worthy — an election bumped the device
            # current_term but its OP_SET_LEADER advert never stuck
            # (proposal lost mid-chaos, or reverted by a stale
            # OP_SET_TOPICS snapshot), so every round dispatches a stale
            # term and is refused forever. plan_elections confirms the
            # skew against the device terms before nominating.
            stalled = set(self.dataplane.stalled_slots())
            for t in self.topics:
                quorum = t.replication_factor // 2 + 1
                for a in t.assignments:
                    slot = self._slot_for(t.name, a.partition_id)
                    if a.leader is not None and a.leader in live:
                        if slot is None:
                            continue
                        if slot not in stalled:
                            # Clear STALE debounce stamps HERE, where
                            # healthy leadership is observed every duty
                            # tick — not only in plan_elections, which no
                            # longer runs on healthy clusters (this
                            # pre-check exists to skip it). A stale stamp
                            # from a previous outage would otherwise void
                            # the debounce window for the next one (r4
                            # advisor). A FRESH stamp survives: the
                            # term-aligned stall probe consumes the
                            # streak (reset_stall) and re-stamps, so
                            # popping its stamp on the next tick would
                            # let a streak that re-builds faster than
                            # the election window re-pay the
                            # plan_elections device fetch per rebuild
                            # instead of at most once per window.
                            since = self._leaderless_since.get(slot)
                            if (since is not None
                                    and now - since
                                    >= self.config.election_timeout_s):
                                self._leaderless_since.pop(slot, None)
                            continue
                        # Live leader but stalled: actionable (same
                        # debounce + quorum gates as leaderless below).
                    if slot is None:
                        continue
                    since = self._leaderless_since.get(slot)
                    if (since is not None
                            and now - since < self.config.election_timeout_s):
                        continue  # debouncing: not actionable yet
                    alive_n = sum(
                        1 for r, b in enumerate(a.replicas)
                        if b in live and r < R
                    )
                    if alive_n >= quorum:
                        return True
            return False

    def plan_elections(
        self, log_ends: Optional[np.ndarray] = None
    ) -> tuple[dict[int, tuple[int, int]], dict[int, dict]]:
        """Controller: find partitions whose leader is unknown or dead and
        pick candidates (the alive replica with the longest log — vote_step
        still enforces log-up-to-dateness on device). Returns
        (candidates for DataPlane.elect, slot → set_leader command draft).
        """
        with self.lock:
            if self.dataplane is None:
                return {}, {}
            if log_ends is None:
                log_ends = self.dataplane.log_ends()      # [R, P]
            device_terms = self.dataplane.current_terms() # [P]
            stalled = set(self.dataplane.stalled_slots())
            live = set(self.live)
            now = time.monotonic()
            cands: dict[int, tuple[int, int]] = {}
            drafts: dict[int, dict] = {}
            for t in self.topics:
                for a in t.assignments:
                    slot = self._slot_for(t.name, a.partition_id)
                    if slot is None:
                        continue
                    skew = False
                    if a.leader is not None and a.leader in live:
                        # Device-term-skew wedge (see needs_elections):
                        # a live leader whose slot is stalled AND whose
                        # device current_term ran ahead of the
                        # advertised term can never commit again.
                        # Anything else live-and-leading is healthy:
                        # clear the debounce stamp and move on.
                        if slot not in stalled:
                            self._leaderless_since.pop(slot, None)
                            continue
                        if int(device_terms[slot]) <= a.term:
                            # Stalled but term-aligned: an engine-quorum
                            # outage elections cannot help. The probe
                            # CONSUMES the stall evidence (reset_stall)
                            # — a streak frozen by traffic stopping
                            # right after the outage would otherwise
                            # keep this device fetch firing at the
                            # election timeout forever — and re-stamps
                            # so a streak that re-builds faster than the
                            # timeout still re-checks at most once per
                            # window; the healthy branch above clears
                            # the stamp once commits resume.
                            self.dataplane.reset_stall(slot)
                            self._leaderless_since[slot] = now
                            continue
                        skew = True
                    since = self._leaderless_since.setdefault(slot, now)
                    if now - since < self.config.election_timeout_s:
                        continue  # debounce (see __init__)
                    self._leaderless_since[slot] = now  # space retries too
                    if skew:
                        # Heal WITHOUT a new vote: the device already
                        # granted a term the table never learned (the
                        # OP_SET_LEADER advert was lost mid-chaos or
                        # skipped as stale). A re-VOTE would bump the
                        # device term again and — under load, where the
                        # advert's raft round-trip outlasts the election
                        # debounce — race its own advert forever (the
                        # observed runaway: device term 165 vs table 75).
                        # Appends ack at `inp.term >= current_term`, so
                        # re-advertising the SAME leader at the device's
                        # max granted term is all commit needs; the
                        # device state never moves, so lost re-adverts
                        # retry idempotently until one lands. No cands
                        # entry: the duty proposes vote-less drafts
                        # directly.
                        drafts[slot] = {
                            "op": OP_SET_LEADER,
                            "topic": t.name,
                            "partition": a.partition_id,
                            "leader": a.leader,
                            "term": int(device_terms[slot]),
                        }
                        continue
                    alive_replicas = [
                        (r, b)
                        for r, b in enumerate(a.replicas)
                        if b in live and r < self.dataplane.cfg.replicas
                    ]
                    if len(alive_replicas) < t.replication_factor // 2 + 1:
                        continue  # no quorum: stay leaderless
                    # Longest log wins (vote_step still enforces
                    # up-to-dateness on device). Ties prefer the replica
                    # hosted on the CONTROLLER broker: every append
                    # executes on the controller's device program anyway,
                    # so leadership elsewhere just buys each produce an
                    # extra broker-to-broker forwarding hop (measured as
                    # the e2e throughput cap — follower processes spend
                    # seconds per ack wave on codec work). Failover keeps
                    # this honest: a new controller wins the ties only
                    # where its log matches the longest.
                    r_best, b_best = max(
                        alive_replicas,
                        key=lambda rb: (
                            int(log_ends[rb[0], slot]),
                            rb[1] == self.controller_broker,
                            -rb[0],
                        ),
                    )
                    new_term = max(a.term, int(device_terms[slot])) + 1
                    cands[slot] = (r_best, new_term)
                    drafts[slot] = {
                        "op": OP_SET_LEADER,
                        "topic": t.name,
                        "partition": a.partition_id,
                        "leader": b_best,
                        "term": new_term,
                    }
            return cands, drafts
