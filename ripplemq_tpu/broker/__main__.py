"""Broker process entry: `python -m ripplemq_tpu.broker --id N --config F`.

The reference boots from `ApplicationMain.main` (reference:
mq-broker/src/main/java/app/ApplicationMain.java:12-54 — load YAML, build
BrokerServer, start, register a shutdown hook) and is launched as
`-id N` per container (mq-broker/docker-compose.yml:8). Same shape here,
with two documented deviations: the broker id is a proper `--id` flag
(the reference checks `args.length < 1` but reads `args[1]` —
ApplicationMain.java:15-20), and the process exits non-zero on a bad
config instead of stack-tracing.

A 5-broker cluster equivalent to the reference's docker-compose is:

    for i in 0 1 2 3 4; do
        python -m ripplemq_tpu.broker --id $i --config examples/cluster.yaml \
            --data-dir /var/lib/ripplemq &
    done
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ripplemq_tpu.broker",
        description="Start one RippleMQ-TPU broker.",
    )
    ap.add_argument("--id", type=int, required=True, dest="broker_id",
                    help="this broker's id (must appear in the config roster)")
    ap.add_argument("--config", required=True,
                    help="cluster config YAML (roster + topics + engine)")
    ap.add_argument("--data-dir", default=None,
                    help="durable storage root; segments + metadata live "
                         "under <data-dir>/broker-<id>/ (omit for in-memory)")
    ap.add_argument("--engine-mode", default="local",
                    choices=["local", "spmd"],
                    help="device binding for the controller's engine: "
                         "'local' vmaps replicas on one chip, 'spmd' shards "
                         "a (replica x part) device mesh")
    ap.add_argument("--log-level", default="INFO",
                    help="console log level for the ripplemq loggers "
                         "(DEBUG/INFO/WARNING/ERROR)")
    ap.add_argument("--log-json", action="store_true",
                    help="emit one JSON object per log line (ts/level/"
                         "subsystem/broker/thread/msg) instead of the "
                         "log4j2-style pattern — machine-greppable next "
                         "to the telemetry plane's event timeline")
    ap.add_argument("--coordinator", default=None,
                    help="multi-host SPMD: host 0's host:port for "
                         "jax.distributed (run the controller with "
                         "--engine-mode spmd on every participating "
                         "host; see parallel.multihost_check)")
    ap.add_argument("--num-hosts", type=int, default=1,
                    help="multi-host SPMD: number of participating hosts")
    ap.add_argument("--host-index", type=int, default=0,
                    help="multi-host SPMD: this process's index")
    ap.add_argument("--engine-workers", default=None,
                    help="multi-host SPMD: comma-separated host:port of "
                         "the engine workers on the other hosts (run "
                         "python -m ripplemq_tpu.parallel.worker there); "
                         "required with --coordinator so every process "
                         "launches each mesh computation")
    args = ap.parse_args(argv)

    from ripplemq_tpu.broker.server import BrokerServer
    from ripplemq_tpu.metadata.cluster_config import load_cluster_config
    from ripplemq_tpu.utils.logs import configure_logging

    configure_logging(args.log_level, json_lines=args.log_json,
                      broker_id=args.broker_id)

    if args.coordinator is not None:
        # Join the global mesh BEFORE any other JAX use: after this,
        # jax.devices() is the global device list and the controller's
        # spmd engine spans every host (collectives ride ICI within a
        # host, DCN across).
        from ripplemq_tpu.parallel.mesh import init_distributed

        n = init_distributed(args.coordinator, args.num_hosts,
                             args.host_index)
        print(f"joined {args.num_hosts}-host mesh: {n} global devices",
              flush=True)

    try:
        config = load_cluster_config(args.config)
        config.broker(args.broker_id)  # fail fast on an id not in the roster
    except (OSError, ValueError, KeyError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    data_dir = None
    if args.data_dir is not None:
        data_dir = os.path.join(args.data_dir, f"broker-{args.broker_id}")
        os.makedirs(data_dir, exist_ok=True)

    workers = None
    if args.engine_workers:
        workers = [w.strip() for w in args.engine_workers.split(",") if w.strip()]
    if args.coordinator is not None and args.num_hosts > 1:
        if not workers:
            print("error: --coordinator with --num-hosts > 1 requires "
                  "--engine-workers (every process of a jax.distributed "
                  "mesh must launch each computation; run "
                  "python -m ripplemq_tpu.parallel.worker on the other "
                  "hosts)", file=sys.stderr)
            return 2
        if args.engine_mode != "spmd":
            print("error: --coordinator with --num-hosts > 1 requires "
                  "--engine-mode spmd (mode 'local' would silently serve "
                  "from this host's devices alone while the workers wait "
                  "forever)", file=sys.stderr)
            return 2

    server = BrokerServer(
        args.broker_id, config,
        net=None,  # real TCP sockets
        engine_mode=args.engine_mode,
        data_dir=data_dir,
        engine_workers=workers,
    )

    stop = threading.Event()

    def _on_signal(signum, frame):  # the reference's shutdown hook
        stop.set()

    signal.signal(signal.SIGINT, _on_signal)
    signal.signal(signal.SIGTERM, _on_signal)

    server.start()
    role = "controller" if server.is_controller else "frontend"
    print(
        f"ripplemq-tpu broker {args.broker_id} ({role}) serving on "
        f"{server.addr}",
        flush=True,
    )
    try:
        while not stop.wait(timeout=1.0):
            pass
    finally:
        server.stop()
        print(f"broker {args.broker_id} stopped", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
