"""BrokerServer: one broker process — dispatch, duties, engine access.

The reference broker stacks five RpcProcessors on one Bolt server plus two
tiers of JRaft (reference: mq-broker/.../TopicsRaftServer.java:106-120,
BrokerServer.java). The equivalent surface here, one dict-typed request
each (wire/transport dispatches by the "type" field):

  meta.topics      ← TopicsRequestProcessor (read path; served by ANY broker)
  meta.propose     ← TopicsRequestProcessor write + PartitionLeaderUpdate
                     forwarding (both were metadata Raft writes)
  produce          ← MessageAppendRequestProcessor
  consume          ← MessageBatchReadRequestProcessor
  offset.commit    ← ConsumerOffsetUpdateRequestProcessor
  raft.*           ← JRaft's internal traffic (here: hostraft, metadata only)
  engine.*         ← controller-only: data-plane access for peer brokers
                     (the reference needs no equivalent — every JVM broker
                     holds state; here the device mesh is driven by the
                     CURRENT controller and peers reach it by RPC)
  repl.rounds      ← standby side of committed-round replication: the
                     controller streams every persisted round to the
                     metadata-replicated standby set, any member of which
                     can be promoted on controller death — restoring the
                     any-broker fault tolerance the reference gets from
                     per-broker JRaft groups (PartitionRaftServer.java:83-93;
                     see broker/replication.py)

Leader checks REFUSE with a hint instead of the reference's
missing-return fallthrough (MessageAppendRequestProcessor.java:29-33 — a
non-leader broker there answers "Not leader" and then appends anyway;
documented deviation, SURVEY.md §7 faithfulness checklist).

Broker duties, each a small periodic loop:
- metadata-leader duty: liveness-driven assignment refresh (the 10 s
  membership monitor of TopicsRaftServer.java:202-217).
- controller duty: batched device elections for leaderless partitions +
  lag repair resync (host-coordinated election, SURVEY.md §7 layer 5).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional


from ripplemq_tpu.broker.dataplane import DataPlane, NotCommittedError
from ripplemq_tpu.obs.lockwitness import make_lock
from ripplemq_tpu.obs.spans import (
    NULL_SPAN,
    TraceContext,
    ctx_from_wire,
    derive_trace_id,
    sampled,
)
from ripplemq_tpu.broker.hostraft import LEADER, RAFT_TYPES, RaftNode, RaftRunner
from ripplemq_tpu.broker.manager import (
    OP_BATCH,
    OP_CONSUMER_SLOT_CLEAN,
    OP_GROUP_DELETE,
    OP_GROUP_JOIN,
    OP_GROUP_LEAVE,
    OP_MERGE_PARTITIONS,
    OP_REGISTER_CONSUMER,
    OP_REGISTER_PRODUCER,
    OP_RETIRE_PRODUCER,
    OP_SET_FOLLOWER_LEASES,
    OP_SET_STANDBYS,
    OP_SPLIT_CUTOVER,
    OP_SPLIT_PARTITION,
    ConsumerTableFullError,
    PartitionManager,
)
from ripplemq_tpu.storage.segment import REC_APPEND
from ripplemq_tpu.groups.coordinator import GroupLiveness
from ripplemq_tpu.groups.state import group_consumer_name
from ripplemq_tpu.metadata.cluster_config import ClusterConfig
from ripplemq_tpu.metadata.models import group_key, topics_to_wire
from ripplemq_tpu.utils.logs import get_logger
from ripplemq_tpu.wire import codec
from ripplemq_tpu.wire.retry import RetryPolicy
from ripplemq_tpu.wire.transport import (
    InProcNetwork,
    RpcError,
    TcpClient,
    TcpServer,
    Transport,
)

log = get_logger("broker")


class _UpstreamRefusal(Exception):
    """A typed refusal from the controller that must reach the client
    VERBATIM (e.g. `unavailable:` quorum-lost degradation) — wrapping it
    in not_committed/internal would strip the prefix the error taxonomy
    and operator tooling key on. Carries the upstream response dict."""

    def __init__(self, resp: dict) -> None:
        super().__init__(str(resp.get("error", "")))
        self.resp = dict(resp)


class _BarrierGate:
    """Batched read-index barrier (SURVEY.md §7 "read semantics", the
    read-index option). Callers block until a barrier that STARTED after
    their arrival completes; concurrent callers share one barrier, so
    the per-read cost under load is a fraction of one standby round
    trip. `fire` confirms leadership — here, an empty epoch-fenced
    record batch through the standby ack stream (a standby knowing a
    newer epoch rejects it, a partitioned standby times it out; either
    way the read REFUSES instead of serving a possibly-stale prefix)."""

    def __init__(self, fire) -> None:
        self._fire = fire
        self._lock = make_lock("_BarrierGate._lock")
        self._pending = None  # Future whose fire has NOT started yet

    def wait(self, timeout_s: float) -> None:
        from concurrent.futures import Future
        from concurrent.futures import TimeoutError as FuturesTimeoutError

        with self._lock:
            fut = self._pending
            if fut is None:
                fut = self._pending = Future()
                threading.Thread(
                    target=self._run, args=(fut,), daemon=True,
                    name="read-barrier",
                ).start()
        try:
            fut.result(timeout=timeout_s)
        # Both classes: the gate can hit a result-wait timeout, or the
        # fire thread can set a FuturesTimeoutError raised by a standby
        # ack wait — pre-3.11 neither is the builtin TimeoutError.
        except (TimeoutError, FuturesTimeoutError):
            raise NotCommittedError(
                "read barrier timed out: leadership unconfirmed"
            ) from None

    def _run(self, fut) -> None:
        # Leave _pending BEFORE firing: a caller arriving after the fire
        # began must wait for the NEXT barrier (its leadership proof
        # must postdate the read's arrival).
        with self._lock:
            if self._pending is fut:
                self._pending = None
        try:
            self._fire()
            fut.set_result(True)
        except Exception as e:
            fut.set_exception(e)


class _ReplStreamGate:
    """Per-(sender, epoch) IN-ORDER application gate for the pipelined
    replication stream (broker/replication.py _Sender): the sender
    keeps `repl_pipeline_depth` frames in flight, each stamped with a
    per-stream sequence number, and concurrent RPC worker threads may
    decode them out of order — this gate serializes APPLICATION to
    sequence order without giving up the pipelining (successors park
    briefly instead of bouncing). `enter` returns "apply" for the
    in-order frame and for any DUPLICATE (sseq below expected: a
    rewound sender re-sends frames whose first delivery may already
    have applied — re-application is harmless, replay is
    later-record-wins), or "gap" when predecessors never arrive inside
    the wait (wire loss): the handler refuses with `repl_seq_gap` +
    the expected counter and the sender rewinds onto it — which also
    re-syncs a RESTARTED standby whose gate restarted at zero."""

    def __init__(self) -> None:
        self._lock = make_lock("_ReplStreamGate._lock")
        self._cond = threading.Condition(self._lock)
        self._expected: dict[tuple, int] = {}

    def expected(self, key: tuple) -> int:
        with self._cond:
            return self._expected.get(key, 0)

    def enter(self, key: tuple, sseq: int, timeout_s: float = 1.0) -> bool:
        """Block until `sseq` is applicable; False = sequence gap."""
        deadline = time.monotonic() + timeout_s
        with self._cond:
            if key not in self._expected:
                # New (sender, epoch) stream: retire the sender's older
                # epochs (the dict must not grow with failovers).
                for k in [k for k in self._expected
                          if k[0] == key[0] and k[1] < key[1]]:
                    del self._expected[k]
                self._expected[key] = 0
            while True:
                # .get, not []: a newer-epoch frame for the same sender
                # retires this key while we park — the woken thread
                # must answer "gap" (the sender's old-epoch rewind hits
                # the stale_epoch fence anyway), not KeyError out of
                # the handler.
                cur = self._expected.get(key)
                if cur is None:
                    return False
                if sseq <= cur:
                    return True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(timeout=remaining)

    def applied(self, key: tuple, sseq: int) -> None:
        """Mark `sseq` durably applied; wakes parked successors. Only
        called on success — a failed apply leaves `expected` in place
        so the sender's rewind re-delivers."""
        with self._cond:
            cur = self._expected.get(key)
            # A retired (newer epoch arrived mid-apply) stream must not
            # be resurrected here — the entry would leak until the next
            # same-sender retirement.
            if cur is not None and sseq + 1 > cur:
                self._expected[key] = sseq + 1
            self._cond.notify_all()


class _WaveWaiter:
    """One enqueued control-plane command's handle: the RPC handler
    parks on `event` until the wave carrying the command is PROPOSED
    (`ok` = the propose outcome) — commitment is still observed by the
    handler's own local-apply poll, exactly as on the unbatched path."""

    __slots__ = ("event", "ok")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.ok = False


class BrokerServer:
    """One broker. `net` is an InProcNetwork for single-process clusters
    (tests, single-chip deployments) or None for real TCP sockets."""

    def __init__(
        self,
        broker_id: int,
        config: ClusterConfig,
        net: Optional[InProcNetwork] = None,
        dataplane: Optional[DataPlane] = None,
        engine_mode: str = "local",
        tick_interval_s: float = 0.05,
        duty_interval_s: float = 0.1,
        data_dir: Optional[str] = None,
        engine_workers: Optional[list[str]] = None,
    ) -> None:
        # FIRST: a partially-constructed broker (any raise below) must
        # refuse teardown — harness/cluster cleanup calls stop() on
        # whatever exists, and running it against half-constructed state
        # turns one boot failure into a cascade (advisor round-5
        # finding). Flipped to False as __init__'s last statement.
        self._stopped = True
        self.broker_id = broker_id
        self.config = config
        self.info = config.broker(broker_id)
        if config.lock_witness:
            # Debug lock witness (obs/lockwitness.py): enabled BEFORE
            # any lock below is constructed, so every host-path mutex
            # this broker creates records acquisition orderings.
            # Process-global by design — an in-proc cluster's brokers
            # share one witnessed graph, which is what the chaos
            # cross-check wants.
            from ripplemq_tpu.obs import lockwitness

            lockwitness.enable()
        # --- telemetry plane (obs/): one metrics registry + one flight-
        # recorder ring per broker, created FIRST so every layer below
        # (store, replicator, data plane) threads through the same pair.
        # config.obs=False swaps in no-op metrics (the A/B knob) and
        # silences the process-global codec frame stats; the flight
        # recorder stays on (see obs/trace.py).
        from ripplemq_tpu.obs.metrics import Metrics
        from ripplemq_tpu.obs.trace import FlightRecorder
        from ripplemq_tpu.wire import codec as _codec

        self.metrics = Metrics(enabled=config.obs)
        self.recorder = FlightRecorder()
        # Causal tracing plane (obs/spans.py): one span ring per broker
        # process, serving admin.spans. None when trace_sample_n=0 —
        # every emit site below gates on `self.spans is not None` (or
        # on a None ctx), so the untraced hot path never reads a clock.
        # The ring shares the metrics clock so the engine's round-stage
        # timestamps can be recorded as spans verbatim (same monotonic
        # domain; trace_sample_n > 0 requires obs=True at parse time).
        from ripplemq_tpu.obs.spans import SpanRing
        self.spans = (
            SpanRing(f"broker{broker_id}",
                     capacity=config.span_ring_slots,
                     clock=self.metrics.clock)
            if config.trace_sample_n > 0 else None
        )
        # Produce-ack latency as the CLIENT of this broker experiences
        # it (admission → all pipelined rounds settled), observed in
        # _handle_produce. This is the SLO controller's plant output:
        # the p99 it steers toward slo_p99_ack_ms.
        self._m_ack_us = self.metrics.histogram("produce.ack_us")
        # Consume-ack latency, same contract on the read side (observed
        # in _handle_consume around the whole answer — leader, follower,
        # and refusal paths alike): the p99 the SLO controller's consume
        # twin steers toward slo_p99_consume_ms via read_coalesce_s.
        self._m_consume_ack_us = self.metrics.histogram("consume.ack_us")
        # Codec stats are process-global: set them symmetrically (last
        # constructed broker wins) rather than latching off forever —
        # a one-way disable would freeze the A/B's obs=True arm when an
        # obs=False broker ran earlier in the same process.
        _codec.enable_stats(config.obs)
        self._net = net
        self._engine_mode = engine_mode
        # Multi-host spmd: engine-worker endpoints on the OTHER hosts of
        # the jax.distributed mesh (parallel.worker); the controller's
        # DataPlane broadcasts its engine-call stream to them.
        self._engine_workers = list(engine_workers or [])
        self._duty_interval_s = duty_interval_s
        self._stop = threading.Event()
        self._started = False
        self.data_dir = data_dir

        # --- transports (before the store: boot-time shard refill calls
        # out to live peers) ---
        if net is not None:
            self.client: Transport = net.client(self.addr)
            # Same source address (fault injection must treat raft
            # traffic exactly like data traffic), distinct client object.
            self._raft_client: Transport = net.client(self.addr)
            self._tcp_server = None
        else:
            self.client = TcpClient()
            # The metadata plane gets its OWN connections: raft appends
            # and meta proposals must not queue behind megabyte
            # replication/engine frames on the shared pipelined sockets
            # (head-of-line blocking there stalls commits for seconds
            # under produce load — elections, standby joins, and
            # failover all ride these messages).
            self._raft_client = TcpClient()
            self._tcp_server = TcpServer(
                self.info.host, self.info.port, self.dispatch,
                workers=config.rpc_workers,
                raw_handler=self._raw_produce,
            )

        # --- committed-round store ---
        # EVERY broker holds one, so any broker can serve as a replication
        # standby and take over as controller (broker/replication.py).
        # Disk-backed under data_dir (the role JRaft's storage URIs play
        # for the reference, TopicsRaftServer.java:134-136 — which the
        # reference only half-uses: its FSMs never snapshot, SURVEY.md §5);
        # in-memory otherwise (matching the reference's own durability for
        # partition data: process memory + replication,
        # PartitionStateMachine.java:26-27).
        self._store_dir = None
        self._peer_shard_dir = None
        self._owns_store = dataplane is None
        self._pushed_shards: set[str] = set()
        self._bad_shard_targets: set[int] = set()
        self._pending_shard_drops: list[tuple[int, str]] = []
        self._shard_push_seeded = False
        self._last_shard_push = 0.0
        self._store_quarantined = False
        # How many striped-promotion rebuilds this process ran
        # (admin.stats `stripe_rebuilds`; stripes/recovery.py).
        self._stripe_rebuilds = 0
        # SLO shed machine's empty-standby-set latch (see _slo_degraded:
        # the signal arms only after a standby ever joined — genesis
        # settles member-less by design). Written from the slo control
        # thread only.
        self._slo_had_standbys = False
        # Since the last quarantine, has this broker been observed OUT of
        # the replicated standby set? A broker that died IN the set boots
        # with stale membership still naming it — which proves nothing
        # about its (now emptied) store. Only an out-then-in transition
        # means the controller re-ran the full catch-up stream before
        # re-proposing membership (see _takeover_duty / _handle_repl_rounds).
        self._quarantine_left_set = False
        if dataplane is not None:
            self._round_store = dataplane.store  # may be None
        elif data_dir is not None:
            import os

            from ripplemq_tpu.storage.erasure import repair_store
            from ripplemq_tpu.storage.segment import SegmentStore

            self._store_dir = os.path.join(data_dir, "segments")
            self._peer_shard_dir = os.path.join(data_dir, "rs_peer")
            # Disaster path first: sealed segments whose file AND local
            # shards are gone refill their rs/ sets from peer-held shard
            # copies (best-effort — unreachable peers just skip), then
            # the ordinary local heal rebuilds any missing/corrupt sealed
            # segment from any 3 of its 5 RS shards — all BEFORE opening
            # for append (the open creates a fresh active segment whose
            # index must come after every recovered one). Damage that
            # survives BOTH passes (a flipped record in the active
            # segment, a lost sealed segment with no shard set) is
            # quarantined: the broker reopens empty and re-replicates
            # through standby catch-up instead of crash-looping at its
            # next promotion or serving a CRC-failing row
            # (_validate_or_quarantine_store).
            self._refill_shards_from_peers()
            repair_store(self._store_dir)
            self._validate_or_quarantine_store()
            self._round_store = SegmentStore(
                self._store_dir, erasure=True,
                segment_bytes=config.segment_bytes,
                retention_bytes=config.store_retention_bytes,
                metrics=self.metrics,
            )
        else:
            from ripplemq_tpu.storage.memstore import MemoryRoundStore

            self._round_store = MemoryRoundStore()
        self._repl_last_flush = 0.0

        # --- control plane (the dataplane attaches after, since the
        # restored metadata decides who the controller is) ---
        self.manager = PartitionManager(broker_id, config, None)
        # Group lifecycle events (join/leave/eviction/generation bumps)
        # land in THIS broker's flight recorder — the rebalance timeline
        # chaos verdicts merge.
        self.manager.recorder = self.recorder
        # Volatile heartbeat ledger (consulted only while this broker is
        # the metadata leader — see _group_duty), plus the empty-group
        # retention stamps (group → first seen empty on THIS leader; a
        # leader change restarts every window, the same volatile-grace
        # rule as member sessions).
        self._group_liveness = GroupLiveness()
        self._group_empty_since: dict[str, float] = {}
        # --- control-plane wave batching (_batch_duty) ---
        # Membership/pid commands received by THIS broker queue here and
        # ride ONE OP_BATCH proposal per wave (meta_batch_s cadence, or
        # early at meta_batch_max) instead of one raft proposal each.
        # Each entry carries the waiter its RPC handler blocks on until
        # the wave is proposed. Both locks are leaves: never held across
        # a propose/RPC, so they stay out of every existing lock order.
        self._intake_lock = make_lock("BrokerServer._intake_lock")
        self._intake: list[tuple[dict, _WaveWaiter]] = []
        # Serializes wave formation: waves must reach the metadata
        # leader in FIFO intake order (an enqueue that hits
        # meta_batch_max drains inline, racing the duty tick).
        self._intake_drain_lock = make_lock(
            "BrokerServer._intake_drain_lock"
        )
        self._last_wave = 0.0
        self._wave_count = 0       # waves proposed (OP_BATCH commands)
        self._wave_events = 0      # sub-commands carried by those waves
        self._wave_failures = 0    # waves whose propose ultimately failed
        self._wave_size_hist: dict[str, int] = {}  # pow2 bucket → waves
        # --- heartbeat relay plane (_beats_relay_duty) ---
        # Member heartbeats are ANSWERED locally from the replicated
        # group view and the per-member stamps buffered here; one
        # group.beats frame per heartbeat_relay_s carries them to the
        # metadata leader's liveness ledger — leader heartbeat RPC load
        # is O(brokers), not O(members).
        self._beat_lock = make_lock("BrokerServer._beat_lock")
        self._beat_buffer: set[tuple[str, str]] = set()
        self._beats_relayed = 0    # stamps this LEADER ingested from frames
        self._beat_frames = 0      # frames this broker delivered
        self._heartbeats_local = 0  # member beats answered locally
        self._last_beat_relay = 0.0
        # Producer-id expiry (metadata-leader duty): volatile ledger
        # name → (seen counter, first observed at) — the same per-
        # tenure grace rule as group liveness: cleared on losing the
        # lease, so a re-elected leader grants every pid a full
        # retention window instead of reaping off a previous tenure's
        # stamps. The replicated half is the seen counter itself
        # (bumped by every re-registration; the reap apply re-checks
        # it, manager._apply_retire_producer).
        self._pid_seen_at: dict[str, tuple[int, float]] = {}
        self._last_pid_reconcile = 0.0
        # Broker-stamped idempotence for pid-LESS produces: the leader
        # stamps each forwarded batch with its own metadata-issued pid +
        # a per-slot sequence, so a duplicated leader→controller
        # engine.append RPC (the wire's at-least-once window) collapses
        # in the controller's dedup table even for clients that never
        # opted into idempotence. Registered via the duty loop; until
        # the pid applies, produces flow unstamped (at-least-once, the
        # pre-PR behavior).
        import uuid as _uuid

        self._broker_pid: Optional[int] = None
        # Per-boot nonce shared by the broker stamping pid AND the
        # host-plane workers' per-(worker, generation) pids: restarts
        # and worker respawns must never reuse a pid whose sequence
        # counters they lost (_worker_pid_duty).
        self._pid_nonce = _uuid.uuid4().hex[:12]
        self._broker_pid_name = (
            f"_broker/{broker_id}/{self._pid_nonce}"
        )
        self._broker_pid_proposed = 0.0
        self._broker_pid_refreshed = 0.0
        self._stamp_lock = make_lock("BrokerServer._stamp_lock")
        self._stamp_seqs: dict[int, int] = {}
        # --- multi-core host plane (parallel/hostplane.py) ---
        # host_workers > 1 boots worker subprocesses owning disjoint
        # partition-group slices of the host path: produce validation +
        # pid/seq stamping + payload packing, and settled-mirror consume
        # serving on the controller. Built here, started in start() —
        # worker boots are async (~100 ms spawn of a jax-free module),
        # so construction never blocks on them.
        self.hostplane = None
        self._worker_pid_names: dict[int, tuple[int, str]] = {}
        self._worker_pids: dict[int, int] = {}
        self._worker_pid_proposed: dict[int, float] = {}
        if config.host_workers > 1:
            from ripplemq_tpu.parallel.hostplane import HostPlane

            self.hostplane = HostPlane(
                config.host_workers,
                slot_bytes=config.engine.slot_bytes,
                payload_bytes=config.engine.payload_bytes,
                max_batch=config.engine.max_batch,
                ring_bytes=config.host_ring_bytes,
                recorder=self.recorder,
                spans=self.spans,
            )
        # Pipelined replication stream gate (see _ReplStreamGate): the
        # standby side of repl.rounds applies frames in per-stream
        # sequence order while the sender keeps a window in flight.
        self._repl_gate = _ReplStreamGate()
        # --- follower read plane (broker/follower.py) ---
        # Serve consumes from the bytes replication already shipped
        # here: a floor-fenced row cache fed by the repl handlers
        # (full-copy records + piggybacked floors, or own-stripe frames
        # decoded on read). Gated per ANSWER on the metadata-plane
        # lease (manager.follower_lease) — construction is cheap and
        # unconditional on the knob so ingest starts before the first
        # lease grant lands.
        self.follower_plane = None
        self._follower_cursors: dict[int, list] = {}
        if config.follower_reads:
            from ripplemq_tpu.broker.follower import FollowerReadPlane

            self.follower_plane = FollowerReadPlane(
                config.engine.slot_bytes,
                config.follower_page_cache_bytes,
                fetch_fn=(self._fetch_sibling_stripes
                          if config.replication == "striped" else None),
            )
        persist_fn = None
        if data_dir is not None:
            import os

            from ripplemq_tpu.storage.metastore import MetaStore

            self._metastore = MetaStore(os.path.join(data_dir, "meta.bin"))
            persist_fn = self._metastore.save
        else:
            self._metastore = None
        # Metadata election timeout → hostraft tick counts (randomized in
        # [1x, 2x], Raft-style; the reference's JRaft equivalent is
        # NodeOptions.setElectionTimeoutMs, TopicsRaftServer.java:131).
        etick = max(2, int(round(config.metadata_election_timeout_s
                                 / tick_interval_s)))
        # Controllership-claim provenance (consumed by _takeover_duty):
        # an OP_SET_CONTROLLER that applies at a raft index BEYOND the
        # restored log's end is a live promotion this process witnessed;
        # a claim held without one is recovered (or genesis-config)
        # state. The distinction matters because a restarted
        # controller's own store may have silently lost its acked tail
        # (torn-tail trim is a legitimate crash repair), while a live
        # promotion's store was acked complete by construction.
        self._recovered_raft_end = 0
        self._promoted_live = False
        node = RaftNode(
            broker_id,
            config.broker_ids(),
            apply_fn=self._apply_committed,
            snapshot_fn=self.manager.snapshot,
            restore_fn=self.manager.restore,
            election_ticks=(etick, 2 * etick),
            seed=broker_id * 7919,
            compact_threshold=256,
            persist_fn=persist_fn,
        )
        if self._metastore is not None:
            saved = self._metastore.load()
            if saved is not None:
                node.restore(saved)
                self._recovered_raft_end = node.last_index()
        self.runner = RaftRunner(
            node,
            self._raft_client,
            addr_of=self._addr_of,
            tick_interval_s=tick_interval_s,
            rpc_timeout_s=min(1.0, config.rpc_timeout_s),
        )
        # Liveness horizon in ticks ≈ metadata election timeout.
        self._alive_horizon = max(
            4, int(config.metadata_election_timeout_s / tick_interval_s)
        )

        # --- engine (the CURRENT controller owns the device program;
        # controllership is replicated metadata and moves on failover) ---
        self.dataplane: Optional[DataPlane] = None
        self._owns_dataplane = False
        self._replicator = None
        self._warm_thread: Optional[threading.Thread] = None
        self._catchup_thread: Optional[threading.Thread] = None
        self._boot_failures = 0     # consecutive data-plane boot failures
        if dataplane is not None:
            self.dataplane = dataplane
            self.manager.attach_dataplane(dataplane)
            if self.hostplane is not None:
                dataplane.mirror_fn = self._mirror_publish
            if dataplane.replicate_fn is None and self._round_store is not None:
                self._wire_replicator(dataplane)
        # No construction-time boot when this broker's (possibly
        # RECOVERED) metadata names it controller: recovered metadata can
        # be arbitrarily stale — a broker restarting after a controller
        # failover would resurrect a deposed plane and serve stale reads
        # (and, with an empty persisted standby set, even ACK produces
        # with no fencing proof) until its raft caught up — the
        # split-brain window the seeded chaos soak caught as acked-loss
        # and offset-regression violations. The takeover duty boots the
        # plane instead, gated on _metadata_current(): genesis cold
        # start costs one metadata election (~the existing bootstrap
        # fixpoint); restart-into-a-moved-on-cluster never boots at all.

        self._duty_thread = threading.Thread(
            target=self._duty_loop, daemon=True, name=f"broker-duty-{broker_id}"
        )
        # Ring of recent duty failures. Mutated (append + del-slice
        # trim) from the duty loop AND catch-up threads — the pair of
        # list ops must not interleave across threads (ownership lint,
        # PR 11), so every mutation rides _errors_lock; snapshot reads
        # (admin.stats list()) stay bare.
        self._errors_lock = make_lock("BrokerServer._errors_lock")
        self.duty_errors: list[str] = []
        # Membership-poll cadence (reference: the 10 s membership monitor,
        # TopicsRaftServer.java:216): assignment/controller planning runs
        # at most every membership_poll_s, first pass immediate.
        self._last_membership_poll = 0.0
        # Follower-lease grant debounce (_follower_lease_duty).
        self._last_lease_grant = 0.0
        # Elastic-partition reconfiguration (split/merge) surface:
        # dual-write forwards this broker served as a handoff leader,
        # generation-fence refusals it answered (both land in the
        # admin.stats `reconfig` block), and the reconfig duty's LOCAL
        # first-seen clock per open handoff window — the
        # split_handoff_timeout_s bound is a duty deadline, not
        # replicated state: a controller failover restarts the clock,
        # which delays the cutover but never loses it.
        self._forwarded_writes = 0
        self._gen_fence_refusals = 0
        self._handoff_seen: dict = {}
        # Auto-split heat ranking: (topic, pid) → committed log end at
        # the previous duty pass (duty thread only).
        self._autosplit_prev_ends: dict = {}
        # Repair-scan cadence (see _controller_duty): lag repair needs a
        # device fetch, so it must not ride every duty tick.
        self._last_repair_scan = 0.0
        self._engine_busy_at = 0.0  # last duty tick the plane looked busy
        # Read-index barrier (linearizable_reads; see _BarrierGate).
        self._barrier_gate = _BarrierGate(self._fire_read_barrier)
        # --- SLO autopilot (ripplemq_tpu/slo/) ---
        # Always constructed (admission quotas work without the loop;
        # admin.stats serves the `slo` block either way); the control
        # thread only starts when slo_p99_ack_ms > 0. dataplane_fn
        # resolves lazily to the CURRENT controller's plane — knob
        # adjustment and engine-side shed signals follow controllership
        # the same way engine RPCs do.
        from ripplemq_tpu.slo.controller import SloController

        self.slo = SloController(
            config, metrics=self.metrics, recorder=self.recorder,
            dataplane_fn=self._local_engine,
            degraded_fn=self._slo_degraded,
        )
        # Fully constructed: teardown may now run (see the top of __init__).
        self._stopped = False

    # ------------------------------------------------------------ lifecycle

    @property
    def addr(self) -> str:
        return self.info.address

    @property
    def is_controller(self) -> bool:
        """Whether this broker currently drives the device program (a
        replicated, epoch-fenced metadata fact — not the static config
        role it was before controller failover existed)."""
        return self.manager.current_controller() == self.broker_id

    def _boot_dataplane(self) -> None:
        """Build the device program from the local committed-round store:
        the bootstrap path on the config controller and the TAKEOVER path
        on a promoted standby. Only committed rounds are ever in the
        store, so the replayed image is a valid post-commit state for
        every replica slot."""
        from ripplemq_tpu.broker.dataplane import replay_records

        log.info(
            "broker %d: booting data plane as controller (epoch %d, "
            "engine mode %s)",
            self.broker_id, self.manager.current_epoch(), self._engine_mode,
        )
        self.recorder.record("controller_boot",
                             epoch=self.manager.current_epoch(),
                             engine_mode=self._engine_mode)
        dp = None
        try:
            # The WHOLE boot sequence is one failure domain: a raise from
            # store replay (corrupt record), the DataPlane constructor
            # (boot-time lockstep failure — a worker dead when the plane
            # is (re)built raises from the configure broadcast BEFORE a
            # DataPlane exists, so the mid-call broken-plane path reading
            # dp.broken_reason never engages), install, the replicator,
            # or start must all count toward abdication — guarding only
            # the constructor would retry a doomed boot forever, and a
            # post-constructor raise would leak a constructed plane
            # (for spmd: workers already configured) into the next
            # attempt.
            image = None
            if self._round_store is not None:
                # Flush barrier BEFORE the replay scan: scan() may miss
                # (or stop torn at) a concurrently-appended tail, and a
                # promoted standby can be booting an instant after it
                # acked the deposed controller's LAST settled round —
                # that acked record must be in the replayed image or the
                # handover loses it (the seeded chaos soak caught
                # exactly this as an acked-produce loss: ack and
                # promotion 10 ms apart). After the local epoch bump
                # applied, the repl.rounds fence refuses the stale
                # stream, so nothing new lands mid-scan.
                self._round_store.flush()
                # Striped replication: a PROMOTED standby's store holds
                # stripe frames, not full rows — rebuild the committed
                # record stream from any k surviving stripes (local +
                # peers) and REWRITE the store to full records before
                # replay, so the booted controller serves reads below
                # trim and can catch up fresh standbys exactly like a
                # full-copy one (stripes/recovery.py; a short-of-k
                # non-tail group quarantines via CorruptStoreError, a
                # peers-unreachable shortfall retries the boot).
                self._rebuild_store_from_stripes()
                # Coverage holes in the recovered stream are rounds the
                # writing controller nacked (committed on device, never
                # settled): re-register them as settled gaps so the
                # booted plane keeps refusing to serve them
                # (replay_records gaps_out; ISSUE 4 residual window 2).
                gaps = {}
                # The producer-dedup table rides the same records
                # (REC_PIDSEQ): rebuilding it here is what keeps a
                # producer retry straddling this promotion exactly-once.
                pid_tab = {}
                image = replay_records(
                    self.config.engine, self._round_store.scan(),
                    gaps_out=gaps, pid_tab_out=pid_tab,
                )
            dp = DataPlane(
                self.config.engine, mode=self._engine_mode,
                store=self._round_store,
                workers=self._engine_workers or None,
                coalesce_s=self.config.coalesce_s,
                chain_depth=self.config.chain_depth,
                pipeline_depth=self.config.pipeline_depth,
                read_coalesce_s=self.config.read_coalesce_s,
                durability=self.config.durability,
                obs=self.config.obs,
                metrics=self.metrics,
                recorder=self.recorder,
                spans=self.spans,
            )
            if image is not None:
                dp.install(image, settled_gaps=gaps, pid_table=pid_tab)
            if self.hostplane is not None:
                dp.mirror_fn = self._mirror_publish
            if self._round_store is not None:
                self._wire_replicator(dp)
            self._owns_dataplane = True
            self.dataplane = dp
            self.manager.attach_dataplane(dp)
            if self._started:
                dp.start()
        except Exception as e:
            if self._replicator is not None:
                self._replicator.stop()
                self._replicator = None
            if self.dataplane is dp:
                self.dataplane = None
                self.manager.detach_dataplane()
                self._owns_dataplane = False
            if dp is not None:
                try:
                    dp.stop()
                except Exception:
                    log.exception("stopping partially-booted plane")
            # A corrupt store can NEVER boot a plane, no matter how many
            # times the replay retries — quarantine it now (the boot-time
            # health walk only guards process start; damage surfacing at
            # promotion time otherwise crash-loops the takeover duty
            # forever, observed as ~1000 consecutive boot failures in the
            # proc disk-fault drills). The reopened-empty store routes
            # the next takeover tick through the quarantined-store path:
            # abdicate to a standby holding the real stream, or boot
            # empty as the genesis-equivalent last resort.
            from ripplemq_tpu.storage.segment import CorruptStoreError

            if (isinstance(e, CorruptStoreError) and self._owns_store
                    and self._store_dir is not None
                    and not self._store_quarantined):
                self._quarantine_store_midlife(e)
            # After a few consecutive failures (grace for a worker that
            # is merely still starting), abdicate the same way a
            # mid-call lockstep break does.
            self._boot_failures += 1
            self.recorder.record(
                "boot_failed", consecutive=self._boot_failures,
                error=f"{type(e).__name__}: {e}"[:200],
            )
            log.warning(
                "broker %d: data-plane boot failed (%d consecutive): "
                "%s: %s", self.broker_id, self._boot_failures,
                type(e).__name__, e,
            )
            if self._boot_failures >= 3:
                cmd = self.manager.plan_abdication()
                if cmd is not None:
                    log.warning(
                        "broker %d: abdicating controllership to broker "
                        "%d after repeated boot failures",
                        self.broker_id, cmd["controller"],
                    )
                    self.propose_cmd(cmd)
            raise
        self._boot_failures = 0
        # Compile hot programs before traffic needs them — EVERY bucket
        # this shape can hit, or the first big produce wave charges a
        # multi-second XLA compile to live traffic. On TAKEOVER
        # (epoch > 0) the first election pass is the latency-critical
        # device work — let it win the lock race before warming.
        self._warm_thread = dp.warm_async(
            buckets=dp.all_buckets(),
            delay_s=2.0 if self.manager.current_epoch() > 0 else 0.0,
        )

    def _wire_replicator(self, dp: DataPlane) -> None:
        """Attach a fresh replicator to the plane — the blocking
        replicate_fn plus its begin/wait split, which the plane's settle
        pipeline uses to keep a window of rounds streaming to the
        standbys while the device advances (dataplane settle pipeline)."""
        rep = self._make_replicator()
        rep.spans = self.spans
        dp.replicate_fn = rep.replicate
        dp.replicate_begin_fn = rep.begin
        dp.replicate_wait_fn = rep.wait

    def _make_replicator(self):
        """Replication-plane factory: `replication="full"` streams full
        copies to every standby (RoundReplicator); `"striped"` encodes
        each group commit into k+m RS stripes shipped to distinct
        standbys and settles at any k stripe-acks (StripeReplicator —
        same begin/wait/catchup/suspects surface, (k+m)/k× the bytes
        instead of standby_count×)."""
        kw = dict(
            epoch_fn=self.manager.current_epoch,
            members_fn=self.manager.current_standbys,
            active_fn=lambda: (
                self.manager.current_controller() == self.broker_id
            ),
            rpc_timeout_s=min(2.0, self.config.rpc_timeout_s),
            ack_timeout_s=self.config.rpc_timeout_s,
            metrics=self.metrics,
            sender_id=self.broker_id,
            pipeline_depth=self.config.repl_pipeline_depth,
        )
        if self.config.replication == "striped":
            from ripplemq_tpu.stripes.plane import StripeReplicator

            self._replicator = StripeReplicator(
                self.client, self._addr_of,
                stripe_map_fn=self.manager.current_stripe_map,
                live_fn=self.manager.live_brokers,
                **kw,
            )
        else:
            from ripplemq_tpu.broker.replication import RoundReplicator

            self._replicator = RoundReplicator(
                self.client, self._addr_of,
                # Piggyback the per-slot settled floor (+ gap map) on
                # every repl.rounds frame — the full-copy follower read
                # plane's serve bound (striped frames already carry the
                # encoder's gsn floor in their header).
                floors_fn=self._settle_floors_stamp,
                **kw,
            )
        return self._replicator

    def _settle_floors_stamp(self, slots):
        """RoundReplicator.floors_fn: the CURRENT controller plane's
        per-slot contiguous-settle floors + gap maps (empty when
        deposed — the frame then ships floor-less and standbys simply
        don't advance)."""
        dp = self._local_engine()
        if dp is None:
            return []
        return dp.settle_floors(slots)

    def _local_engine(self) -> Optional[DataPlane]:
        """The device program, iff this broker is the CURRENT controller
        (a deposed controller must not serve engine state it no longer
        replicates — fencing)."""
        dp = self.dataplane
        if dp is not None and self.manager.current_controller() == self.broker_id:
            return dp
        return None

    def _slo_degraded(self) -> bool:
        """The SLO shed machine's quorum-degradation signal. Like every
        shed signal it is ENGINE-SIDE (non-None only on the current
        controller — shedding exists to drain a queueing pipe, and the
        pipe lives here; see slo/controller.py for why a frontend-local
        p99 signal was deliberately removed): an engine partition lost
        its replica quorum, OR controller failover is armed
        (standby_count > 0) and the replicated standby set is EMPTY —
        in that state the settle path refuses every round (the PR 2
        empty-set fence), so refusing cheaply at admission is strictly
        kinder than queueing produces into certain refusal."""
        dp = self._local_engine()
        if dp is None:
            return False
        if dp.degraded_slots():
            return True
        if self.config.standby_count <= 0 or self._round_store is None:
            return False
        if self.manager.current_standbys():
            # Arm the empty-set signal only once a standby EVER joined
            # (the replicator's _had_members rule): genesis settles
            # member-less by design, and shedding a freshly-booted
            # cluster for not yet having standbys would be a
            # self-inflicted outage.
            self._slo_had_standbys = True
            return False
        return self._slo_had_standbys

    def _addr_of(self, broker_id: int) -> str:
        return self.config.broker(broker_id).address

    def start(self) -> None:
        self._started = True
        if self.hostplane is not None:
            self.hostplane.start()
        if self._net is not None:
            self._net.register(self.addr, self.dispatch)
        else:
            self._tcp_server.start()
        if self.dataplane is not None and self._owns_dataplane:
            self.dataplane.start()
        self.runner.start()
        self._duty_thread.start()
        self.slo.start()

    @property
    def stopped(self) -> bool:
        """True once stop() ran (or before __init__ completed) — the
        liveness probe harnesses poll instead of reaching into
        `_stopped` bare."""
        return self._stopped

    def stop(self) -> None:
        # Idempotent: a killed-but-never-restarted broker is stopped
        # again by harness/cluster teardown, and the second pass must
        # not flush the segment store the first one closed. Initialized
        # True at the TOP of __init__ (and flipped False at its end), so
        # teardown after a partial __init__ failure is a no-op instead
        # of a crash against half-constructed state.
        if self._stopped:
            return
        self._stopped = True
        self._stop.set()
        # Release handlers parked on un-proposed waves before joining
        # the duty thread (their RPC workers would otherwise hold the
        # full waiter timeout).
        self._fail_pending_waves()
        self.slo.stop()
        self._duty_thread.join(timeout=2)
        self.runner.stop()
        if self._net is not None:
            self._net.unregister(self.addr)
        else:
            self._tcp_server.stop()
        if self._replicator is not None:
            self._replicator.stop()
        if self.dataplane is not None and self._owns_dataplane:
            self.dataplane.stop()
        if self._owns_store and self._round_store is not None:
            self._round_store.close()
        if self.hostplane is not None:
            self.hostplane.stop()
        self.client.close()
        self._raft_client.close()

    # ------------------------------------------------------------- dispatch

    def dispatch(self, req: dict) -> dict:
        resp = self._dispatch(req)
        if isinstance(resp, dict):
            # Every response names its serving broker: clients and the
            # chaos history checker attribute outcomes to a concrete
            # broker when reconstructing a failure (who acked this
            # produce, whose view served this read).
            resp.setdefault("broker", self.broker_id)
        return resp

    def _dispatch(self, req: dict) -> dict:
        t = req.get("type", "")
        try:
            if t in RAFT_TYPES:
                return self.runner.handle_rpc(req)
            if t == "meta.topics":
                # Topics + broker roster: clients resolve leader broker ids
                # to advertised addresses from here (the reference instead
                # parsed "brokerN" out of hostnames and substituted
                # bootstrap entries — ProducerClientImpl.java:101-107; that
                # hack is deliberately not reproduced).
                return {
                    "ok": True,
                    "topics": topics_to_wire(self.manager.get_topics()),
                    "brokers": [b.to_dict() for b in self.config.brokers],
                    # Follower-read advertisement: which standbys hold a
                    # consume lease, and under which controller epoch —
                    # the client SDK routes explicit-offset consumes to
                    # a leased broker and falls back to the leader on
                    # `not_settled_here:` refusals. Empty dict when the
                    # feature is off or no lease is granted.
                    "follower_leases": {
                        str(b): int(e)
                        for b, e in
                        self.manager.current_follower_leases().items()
                    },
                    "controller_epoch": self.manager.current_epoch(),
                }
            if t == "meta.propose":
                return self._handle_meta_propose(req)
            if t == "produce":
                return self._handle_produce(req)
            if t == "consume":
                return self._handle_consume(req)
            if t == "offset.commit":
                return self._handle_offset_commit(req)
            if t == "producer.register":
                return self._handle_producer_register(req)
            if t.startswith("group."):
                return self._handle_group(t, req)
            if t == "repl.rounds":
                return self._handle_repl_rounds(req)
            if t == "repl.stripes":
                return self._handle_repl_stripes(req)
            if t == "stripe.fetch":
                return self._handle_stripe_fetch(req)
            if t == "admin.split":
                return self._handle_admin_split(req)
            if t == "admin.merge":
                return self._handle_admin_merge(req)
            if t == "admin.stats":
                return self._handle_stats(req)
            if t == "admin.metrics":
                return self._handle_metrics(req)
            if t == "admin.metrics_text":
                return self._handle_metrics_text(req)
            if t == "admin.trace":
                return self._handle_trace(req)
            if t == "admin.spans":
                return self._handle_spans(req)
            if t == "admin.postmortem":
                from ripplemq_tpu.obs.postmortem import collect_postmortem

                return collect_postmortem(self)
            if t.startswith("shard."):
                return self._handle_shard(t, req)
            if t.startswith("engine."):
                return self._handle_engine(t, req)
            return {"ok": False, "error": f"unknown request type {t!r}"}
        except _UpstreamRefusal as e:
            return dict(e.resp)
        except NotCommittedError as e:
            return {"ok": False, "error": f"not_committed: {e}"}
        except ConsumerTableFullError as e:
            # Permanent refusal, NOT retryable (not_committed implies
            # retry): the client must pick a committed-and-released name
            # or the operator must raise max_consumers.
            return {"ok": False, "error": f"consumer_table_full: {e}"}
        except (KeyError, ValueError, TypeError) as e:
            return {"ok": False, "error": f"bad_request: {type(e).__name__}: {e}"}

    # -- observability -----------------------------------------------------

    def _handle_metrics(self, req: dict) -> dict:
        """The metrics-registry snapshot (counters/gauges/log-bucketed
        histogram summaries — obs/metrics.py) plus the process-global
        wire-codec frame stats. Cheap enough to poll; the heavyweight
        one-shot diagnosis surface is admin.postmortem."""
        del req
        from ripplemq_tpu.wire import codec as _codec

        out = {
            "ok": True,
            "obs": self.config.obs,
            "metrics": self.metrics.snapshot(),
            # Codec stats are PROCESS-global (the codec is stateless
            # module functions): in an in-proc multi-broker cluster they
            # aggregate across every broker sharing the process.
            "wire": _codec.codec_stats(),
        }
        dp = self._local_engine()
        if dp is not None and dp.metrics is not self.metrics:
            # An externally-injected plane keeps its own registry.
            out["engine_metrics"] = dp.metrics.snapshot()
        return out

    def _handle_metrics_text(self, req: dict) -> dict:
        """Prometheus-style text exposition of the SAME registry
        admin.metrics snapshots (obs/metrics.py render_prometheus):
        counters as `_total`, gauges bare, histograms as cumulative
        log2 `_bucket{le=...}` series with `_sum`/`_count`. One string
        under "text" so both transports carry it as an ordinary
        response field; scrape adapters write it out verbatim."""
        from ripplemq_tpu.obs.metrics import render_prometheus

        text = render_prometheus(self.metrics)
        dp = self._local_engine()
        if dp is not None and dp.metrics is not self.metrics:
            text += render_prometheus(dp.metrics)
        return {"ok": True, "text": text}

    def _handle_spans(self, req: dict) -> dict:
        """Paged span-ring read (obs/spans.py), the collection half of
        the causal-tracing plane. Same paging contract as stripe.fetch:
        `after` is the last seq the caller saw (-1 from cold),
        `max_spans` bounds the page, and the response's `cursor` is the
        last served record's seq (== `after` when the page is empty).
        Rings are racy-consistent; assemblers page until the cursor
        stops moving. trace_sample_n=0 serves empty pages, not errors."""
        after = int(req.get("after", -1))
        if self.spans is None:
            return {"ok": True, "spans": [], "cursor": after}
        max_spans = req.get("max_spans")
        recs = self.spans.snapshot(
            after=after,
            max_spans=int(max_spans) if max_spans is not None else None,
        )
        return {
            "ok": True,
            "spans": recs,
            "cursor": recs[-1]["seq"] if recs else after,
        }

    def _handle_trace(self, req: dict) -> dict:
        """The flight-recorder window (obs/trace.py), oldest first;
        `last` clips to the most recent N events."""
        last = req.get("last")
        last = int(last) if last is not None else None
        # `now` is this broker's wall clock at snapshot time: the chaos
        # timeline merge pairs it with the caller's send/receive stamps
        # (NTP midpoint) to estimate per-broker clock skew instead of
        # trusting raw wall-clock event ordering across processes.
        out = {"ok": True, "trace": self.recorder.snapshot(last=last),
               "now": time.time()}
        dp = self._local_engine()
        if dp is not None and dp.recorder is not self.recorder:
            out["engine_trace"] = dp.recorder.snapshot(last=last)
        return out

    def _handle_stats(self, req: dict) -> dict:
        """Broker stats/health snapshot: metadata role, controller state,
        per-partition leadership, engine counters (controller only), and
        the duty/erasure error rings. The reference's observability is a
        log4j2 console stack (log4j2.xml:10-14); this adds the health
        endpoint it lacked. `slots` (optional list) selects partitions
        for per-slot engine detail (commit index, absolute end, trim)."""
        node = self.runner.node
        topics = {}
        for t in self.manager.get_topics():
            topics[t.name] = {
                str(a.partition_id): {
                    "leader": a.leader, "term": a.term,
                    "replicas": list(a.replicas),
                    # Elastic-partition surface: reconfiguration
                    # generation, owned key-hash range, lifecycle state
                    # (active | handoff | retired), parent pid for
                    # split children (-1 = configured partition).
                    "generation": a.generation,
                    "range": [a.range_lo, a.range_hi],
                    "state": a.state,
                    "origin": a.origin,
                }
                for a in t.assignments
            }
        stats = {
            "ok": True,
            "broker": self.broker_id,
            "address": self.addr,
            # Consecutive data-plane boot failures (genesis or takeover;
            # reset on success and on losing controllership) — makes a
            # boot-retry loop operator-visible instead of log-only.
            "boot_failures": self._boot_failures,
            # True while the local committed-round store is a fresh
            # replacement for a boot-time-quarantined one (disk damage
            # beyond erasure repair); clears once standby catch-up
            # re-transfers the full prefix.
            "store_quarantined": self._store_quarantined,
            "metadata": {
                "role": node.role,
                "term": node.term,
                "leader_hint": node.leader_hint,
            },
            "controller": {
                "id": self.manager.current_controller(),
                "epoch": self.manager.current_epoch(),
                "standbys": list(self.manager.current_standbys()),
                "is_self": self.is_controller,
            },
            "topics": topics,
            "live": list(self.manager.live),
            # Consumer groups: per-group generation + membership (the
            # coordinator's replicated view — identical on every broker).
            "groups": self.manager.groups_summary(),
            # Idempotent-producer registry size (issued pids, including
            # broker-stamping pids) and recycled slots awaiting reset.
            "producer_ids": len(self.manager.producers),
            "dirty_consumer_slots": self.manager.dirty_slots(),
            "duty_errors": list(self.duty_errors),
            "erasure_errors": list(
                getattr(self._round_store, "erasure_errors", [])
            ),
            # Striped replication surface: the active replication plane,
            # the replicated stripe→member assignment (stripe i held by
            # stripe_holders[i]; empty before a standby joins or in
            # full-copy mode), and how many any-k promotion rebuilds
            # this process has run (stripes/recovery.py).
            "stripe_mode": self.config.replication,
            "stripe_holders": [
                int(b) for b in self.manager.current_stripe_map()
            ],
            "stripe_rebuilds": self._stripe_rebuilds,
        }
        # Multi-core host plane liveness/occupancy (null when
        # host_workers == 1 — no subprocess plane).
        if self.hostplane is None:
            stats["host_plane"] = None
        else:
            stats["host_plane"] = self.hostplane.stats()
        # Control-plane wave batching + heartbeat relay: how many
        # OP_BATCH waves this broker formed, the sub-commands they
        # carried (proposals_saved = events - waves: raft proposals the
        # coalescing avoided), the wave-size histogram (pow2 buckets),
        # and the relay plane's counters — beats answered locally,
        # frames delivered, stamps ingested while leading. `enabled:
        # false` shape (counters intact) when meta_batch_s is 0.
        with self._intake_lock:
            intake_depth = len(self._intake)
        stats["control_plane"] = {
            "enabled": self.config.meta_batch_s > 0,
            "waves": self._wave_count,
            "wave_events": self._wave_events,
            "wave_failures": self._wave_failures,
            "wave_size_hist": dict(self._wave_size_hist),
            "proposals_saved": self._wave_events - self._wave_count,
            "intake_depth": intake_depth,
            "heartbeats_local": self._heartbeats_local,
            "beat_frames": self._beat_frames,
            "beats_relayed": self._beats_relayed,
        }
        # SLO autopilot: mode, current knob values, shed/refusal counts,
        # and the tick/transition history chaos verdicts replay
        # (`enabled: false` shape when the loop is off — the admission
        # counters still live there, quotas work without the loop).
        stats["slo"] = self.slo.stats()
        # Elastic partitions: the replicated split/merge topology
        # (children, retired, open handoff windows, spare-slot pool)
        # plus THIS broker's local reconfiguration counters — dual-
        # write forwards it served as a handoff leader, generation-
        # fence refusals it answered. The chaos reconfig verdict reads
        # this block on every broker and sums the local halves.
        reconfig = self.manager.reconfig_stats()
        reconfig["forwarded_writes"] = self._forwarded_writes
        reconfig["fence_refusals"] = self._gen_fence_refusals
        stats["reconfig"] = reconfig
        # Follower read plane: lease table + this broker's own serving
        # counters (floor lag, cache hit rate, reads served/refused).
        # `enabled: false` shape when the knob is off — the lease keys
        # are still present so dashboards need no conditional.
        follower = {
            "enabled": self.config.follower_reads,
            "lease_epoch": self.manager.follower_lease(self.broker_id),
            "leases": {
                str(b): int(e)
                for b, e in self.manager.current_follower_leases().items()
            },
        }
        if self.follower_plane is not None:
            follower.update(self.follower_plane.stats())
        stats["follower"] = follower
        dp = self._local_engine()
        if dp is None:
            stats["engine"] = None
        else:
            engine = {
                "mode": self._engine_mode,
                "rounds": dp.rounds,
                "dispatches": dp.dispatches,
                "read_queries": dp.read_queries,
                "read_dispatches": dp.read_dispatches,
                "read_cache_hits": dp.read_cache_hits,
                # Slots whose host mirror is gap-disabled (resolve
                # failure; pending trim-passage heal) — a silent cache
                # regression the operator should be able to see. Read
                # through the locked accessor: the resolver mutates the
                # gap dict concurrently.
                "mirror_gap_slots": dp.mirror_gap_slots(),
                # Slots carrying settled gaps (replication-FAILED rounds
                # every read path skips) — same locked-accessor pattern.
                "settled_gap_slots": dp.settled_gap_slots(),
                # Slots whose recent rounds ALL failed to commit on
                # device (the term-skew wedge probe feeding the duty's
                # re-election gate) — non-empty here means the duty is
                # about to heal, or the partition has no engine quorum.
                "stalled_slots": dp.stalled_slots(),
                "committed_entries": dp.committed_entries,
                "step_errors": dp.step_errors,
                # Settle-pipeline occupancy (pipelined standby
                # replication): window width, mean depth at enqueue,
                # and how often dispatch hit the window's backpressure.
                "settle": dp.settle_stats(),
                "partitions": dp.cfg.partitions,
                # Graceful-degradation surface: partitions whose replica
                # quorum is lost fast-fail consumes/commits with
                # `unavailable` instead of hanging; the flag makes that
                # state operator-visible before the first refusal.
                "degraded_slots": dp.degraded_slots(),
                # Producer-dedup table occupancy ((pid, partition) keys):
                # the idempotence plane's memory footprint, and a rough
                # count of distinct producer streams the broker has
                # settled.
                "pid_table_size": dp.pid_table_size(),
            }
            engine["degraded"] = bool(engine["degraded_slots"])
            slots = req.get("slots")
            if slots:
                # One device fetch for ALL requested slots (a per-slot
                # commit_index() loop would sync the device — and stall
                # the round pipeline — once per slot); shadow + trim are
                # snapshotted consistently under the plane's lock.
                engine["slots"] = dp.slot_detail(slots)
            stats["engine"] = engine
        return stats

    # -- distributed erasure shards ---------------------------------------
    # Each broker pushes its sealed segments' RS shards to peers (round-
    # robin over the roster), and on boot refills missing shard sets from
    # peers before the local repair pass — so losing a broker's disk
    # entirely (segments AND local shards) is recoverable from any K of
    # the K+M distributed shard copies. The reference's only equivalent
    # is full per-broker replication (PartitionRaftServer.java:88-90);
    # this gets the same any-K-of-N durability at (K+M)/K x overhead.

    def _peer_dir_for(self, owner: int) -> Optional[str]:
        if self._peer_shard_dir is None:
            return None
        import os

        return os.path.join(self._peer_shard_dir, f"broker-{int(owner)}")

    def _handle_shard(self, t: str, req: dict) -> dict:
        import os

        from ripplemq_tpu.storage.erasure import valid_shard_name

        d = self._peer_dir_for(int(req["owner"]))
        if d is None:
            return {"ok": False, "error": "no_data_dir"}
        if t == "shard.put":
            name = str(req["name"])
            if not valid_shard_name(name):
                return {"ok": False,
                        "error": f"bad_request: shard name {name!r}"}
            os.makedirs(d, exist_ok=True)
            tmp = os.path.join(d, name + ".tmp")
            with open(tmp, "wb") as f:
                f.write(req["data"])
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(d, name))
            return {"ok": True}
        if t == "shard.list":
            names = []
            if os.path.isdir(d):
                names = sorted(
                    f for f in os.listdir(d)
                    if valid_shard_name(f)
                )
            return {"ok": True, "shards": names}
        if t == "shard.get":
            name = str(req["name"])
            if not valid_shard_name(name):
                return {"ok": False,
                        "error": f"bad_request: shard name {name!r}"}
            try:
                with open(os.path.join(d, name), "rb") as f:
                    return {"ok": True, "data": f.read()}
            except OSError:
                return {"ok": False, "error": "not_found"}
        if t == "shard.drop":
            name = str(req["name"])
            if not valid_shard_name(name):
                return {"ok": False,
                        "error": f"bad_request: shard name {name!r}"}
            try:
                os.remove(os.path.join(d, name))
            except OSError:
                pass  # already gone: drop is idempotent
            return {"ok": True}
        return {"ok": False, "error": f"unknown shard op {t!r}"}

    def _validate_or_quarantine_store(self) -> None:
        """Boot-time store health gate (after peer refill + erasure
        repair): a store the scanners would refuse — a CRC-failing
        record beyond the torn-tail contract, or a sealed segment FILE
        still missing after both recovery passes — is moved aside
        (`segments.quarantine-N`) and the broker reopens EMPTY. It then
        rejoins as a standby and re-replicates the full committed-round
        stream through the catch-up protocol; recovered-metadata
        controllership over a quarantined store is refused by the
        takeover duty (an emptied store must never boot a plane that
        would serve an empty history as truth). Never crash-loop, never
        serve a row that fails CRC."""
        from ripplemq_tpu.storage.erasure import segment_index_gaps
        from ripplemq_tpu.storage.segment import (
            CorruptStoreError,
            quarantine_store,
            verify_store,
        )

        try:
            if segment_index_gaps(self._store_dir):
                raise CorruptStoreError(
                    "sealed segment files missing after refill + repair"
                )
            # repair_torn_tail: the reopen below starts a NEW segment, so
            # a merely-tolerated torn tail would seal into a segment every
            # later scan refuses — truncate it off while it is still legal.
            verify_store(self._store_dir, repair_torn_tail=True)
        except CorruptStoreError as e:
            target = quarantine_store(self._store_dir)
            self._store_quarantined = True
            self.recorder.record("store_quarantine", when="boot",
                                 error=str(e)[:200])
            log.warning(
                "broker %d: store failed its boot health walk (%s); "
                "quarantined to %s — reopening empty, will re-replicate "
                "via standby catch-up", self.broker_id, e, target,
            )

    def _quarantine_store_midlife(self, cause: Exception) -> None:
        """Quarantine a store whose damage surfaced AFTER boot (a replay
        scan raising mid-promotion) and reopen it empty. Same contract
        as the boot-time gate: the damaged bytes move aside for
        forensics, `_store_quarantined` keeps the takeover duty from
        booting a plane that would serve the emptied history as truth,
        and the flag clears once standby catch-up re-admits this broker
        with the full stream. Concurrent repl appends against the OLD
        store object fail harmlessly (their segment paths moved) and the
        controller's retry lands on the fresh store."""
        from ripplemq_tpu.storage.segment import (
            SegmentStore,
            quarantine_store,
        )

        try:
            self._round_store.close()
        except Exception:
            log.exception("closing store ahead of mid-life quarantine")
        target = quarantine_store(self._store_dir)
        self._store_quarantined = True
        self._quarantine_left_set = False
        self.recorder.record("store_quarantine", when="midlife",
                             error=f"{type(cause).__name__}: {cause}"[:200])
        self._round_store = SegmentStore(
            self._store_dir, erasure=True,
            segment_bytes=self.config.segment_bytes,
            retention_bytes=self.config.store_retention_bytes,
            metrics=self.metrics,
        )
        log.warning(
            "broker %d: store failed its replay scan mid-life (%s: %s); "
            "quarantined to %s — reopening empty, will re-replicate via "
            "standby catch-up", self.broker_id, type(cause).__name__,
            cause, target,
        )

    def _rebuild_store_from_stripes(self) -> None:
        """Striped-promotion rebuild: if the local store holds
        REC_STRIPE frames (this broker lived as a striped standby),
        gather the missing stripe indices from live peers
        (stripe.fetch), reconstruct every group's records from any k
        of its k+m stripes, and REWRITE the store as a plain full-
        record store (previous bytes kept at `segments.prestripe-N`
        for forensics). No-op when the store has no stripes (ordinary
        controller restart, full-copy mode, genesis).

        Failure ladder (rebuild-or-quarantine, PR 4): a group short of
        k with some peer unreachable raises StripeRecoveryError — the
        takeover duty retries next tick and repeated failures abdicate;
        short of k with EVERY peer consulted raises CorruptStoreError,
        routing into the existing quarantine machinery (non-tail only
        — a torn tail of never-settled groups is dropped)."""
        from ripplemq_tpu.storage.segment import (
            REC_STRIPE,
            CorruptStoreError,
            SegmentStore,
        )
        from ripplemq_tpu.stripes.recovery import (
            StripeDataLossError,
            rebuild_records,
        )

        store = self._round_store
        if store is None:
            return
        if not any(rec[0] == REC_STRIPE for rec in store.scan()):
            return
        self._stripe_rebuilds += 1
        self.recorder.record("stripe_rebuild",
                             epoch=self.manager.current_epoch())

        def mk_fetch(addr):
            def fetch(after):
                resp = self.client.call(
                    addr, {"type": "stripe.fetch", "after": after},
                    timeout=min(10.0, 2 * self.config.rpc_timeout_s),
                )
                if not resp.get("ok"):
                    raise RpcError(
                        f"stripe.fetch refused: {resp.get('error')}"
                    )
                return resp.get("frames", []), resp.get("next")
            return fetch

        fetchers = [
            (b.address, mk_fetch(b.address))
            for b in self.config.brokers
            if b.broker_id != self.broker_id
        ]
        try:
            records = rebuild_records(store.scan(), fetchers,
                                      platform="cpu")
        except StripeDataLossError as e:
            raise CorruptStoreError(f"stripe rebuild: {e}") from e
        log.info(
            "broker %d: rebuilt %d full records from stripe store "
            "(rebuild #%d)", self.broker_id, len(records),
            self._stripe_rebuilds,
        )
        if self._store_dir is None:
            # In-memory store (in-proc cluster without a data dir):
            # rewrite in place.
            from ripplemq_tpu.storage.memstore import MemoryRoundStore

            fresh = MemoryRoundStore()
            for rec in records:
                fresh.append(*rec)
            self._round_store = fresh
            return
        import os

        tmp = self._store_dir + ".restripe"
        if os.path.exists(tmp):
            import shutil

            shutil.rmtree(tmp)
        out = SegmentStore(tmp, segment_bytes=self.config.segment_bytes)
        try:
            for i in range(0, len(records), 256):
                out.append_many(records[i : i + 256])
        finally:
            out.close()
        store.close()
        n = 0
        while os.path.exists(f"{self._store_dir}.prestripe-{n}"):
            n += 1
        os.replace(self._store_dir, f"{self._store_dir}.prestripe-{n}")
        os.replace(tmp, self._store_dir)
        self._round_store = SegmentStore(
            self._store_dir, erasure=True,
            segment_bytes=self.config.segment_bytes,
            retention_bytes=self.config.store_retention_bytes,
            metrics=self.metrics,
        )

    def _refill_shards_from_peers(self) -> None:
        """Boot-time disaster recovery: pull peer-held shard copies for
        sealed segments this store lost (see refill_from_peers). Gated on
        LOCAL loss evidence — a hole in the store's contiguous segment
        numbering — so ordinary boots (including cold cluster starts,
        where peers aren't serving yet) skip the peer round-trips
        entirely. A fully wiped data dir shows no holes and recovers
        through the committed-round replication stream instead
        (broker/replication.py standby catch-up)."""
        from ripplemq_tpu.storage.erasure import (
            refill_from_peers,
            segment_index_gaps,
        )

        if not segment_index_gaps(self._store_dir):
            return
        peers = [
            b for b in self.config.brokers if b.broker_id != self.broker_id
        ]
        if not peers:
            return

        def mk_list(addr):
            def f():
                resp = self.client.call(
                    addr, {"type": "shard.list", "owner": self.broker_id},
                    timeout=2.0,
                )
                return resp.get("shards", []) if resp.get("ok") else []
            return f

        def get(addr, name):
            resp = self.client.call(
                addr,
                {"type": "shard.get", "owner": self.broker_id, "name": name},
                timeout=5.0,
            )
            return resp.get("data") if resp.get("ok") else None

        try:
            refilled = refill_from_peers(
                self._store_dir,
                [(b.address, mk_list(b.address)) for b in peers],
                get,
            )
        except Exception as e:  # never block boot on the disaster path
            log.warning("broker %d: shard refill failed: %s: %s",
                        self.broker_id, type(e).__name__, e)
            return
        if refilled:
            log.info("broker %d: refilled shard sets from peers for %s",
                     self.broker_id, refilled)

    def _seed_pushed_shards(self) -> None:
        """One-time (per boot) sync of the pushed-set with what peers
        already hold, so a restart does not re-transfer the whole sealed
        history. Peer-held shards for segments below our persisted GC
        floor are stale (the drop may have been missed across a
        restart): ask those peers to drop them instead."""
        from ripplemq_tpu.storage.erasure import valid_shard_name
        from ripplemq_tpu.storage.segment import gc_floor, segment_index

        floor = gc_floor(self._store_dir)
        for b in self.config.brokers:
            if b.broker_id == self.broker_id:
                continue
            try:
                resp = self.client.call(
                    b.address,
                    {"type": "shard.list", "owner": self.broker_id},
                    timeout=2.0,
                )
            except RpcError:
                continue  # unreachable: worst case a redundant re-push
            if not resp.get("ok"):
                continue
            for name in resp.get("shards", []):
                if not valid_shard_name(name):
                    continue
                if segment_index(name.rpartition(".shard")[0]) < floor:
                    try:
                        self.client.call(
                            b.address,
                            {"type": "shard.drop",
                             "owner": self.broker_id, "name": name},
                            timeout=2.0,
                        )
                    except RpcError:
                        pass
                else:
                    self._pushed_shards.add(name)

    def _gc_duty(self) -> None:
        """Size-capped store retention: delete the oldest sealed
        segments past store_retention_bytes, prune the controller's
        retention indexes, and tell the peers holding those segments'
        distributed shards to drop their copies."""
        gc = getattr(self._round_store, "gc", None)
        if gc is None:
            return
        deleted = gc()
        if not deleted:
            return
        log.info("broker %d: store GC deleted segments %s",
                 self.broker_id, deleted)
        if self.dataplane is not None:
            self.dataplane.drop_index_segments(set(deleted))
        # Peer copies of the deleted segments' shards are now garbage.
        from ripplemq_tpu.storage.segment import segment_name

        stems = {segment_name(i) for i in deleted}
        gone = {
            n for n in self._pushed_shards
            if n.rpartition(".shard")[0] in stems
        }
        self._pushed_shards -= gone
        # Queue drops for every eligible peer: the push target rotation
        # (including bad-target skips) means we cannot know which peer
        # holds a given shard, and drop is idempotent+cheap — but a big
        # GC can queue hundreds, so the shared duty loop drains them a
        # few per tick (_drain_shard_drops) instead of stalling failover
        # duties behind sequential RPC timeouts.
        for name in gone:
            for b in self.config.brokers:
                if (b.broker_id == self.broker_id
                        or b.broker_id in self._bad_shard_targets):
                    continue
                self._pending_shard_drops.append((b.broker_id, name))

    def _drain_shard_drops(self, budget: int = 4) -> None:
        while budget > 0 and self._pending_shard_drops:
            target, name = self._pending_shard_drops.pop(0)
            budget -= 1
            try:
                self.client.call(
                    self._addr_of(target),
                    {"type": "shard.drop", "owner": self.broker_id,
                     "name": name},
                    timeout=2.0,
                )
            except RpcError:
                pass  # best-effort: peer copies are derived data

    def _shard_duty(self) -> None:
        """Push not-yet-distributed local shard files to their designated
        peers (shard i of a segment goes to the (i+1)-th broker after
        this one in the roster — with K+M=5 shards and >=5 brokers each
        lands on a distinct peer). Work per tick is bounded by ATTEMPTS
        (a partitioned peer's timeouts must not stall the duty loop that
        also runs failover duties), and peers that refuse storage
        (no_data_dir) rotate to the next roster member."""
        if self._store_dir is None:
            return
        now = time.monotonic()
        if now - self._last_shard_push < 2.0:
            return
        protect = getattr(self._round_store, "protect_async", None)
        if protect is not None:
            protect()  # traffic-independent encode trigger (see method)
        if not self._shard_push_seeded:
            # Seed BEFORE the first GC pass: drops for already-GC'd
            # segments are computed from the pushed-set, which must
            # reflect what peers actually hold.
            self._shard_push_seeded = True
            self._seed_pushed_shards()
        self._gc_duty()
        self._drain_shard_drops()
        self._last_shard_push = now
        import os

        from ripplemq_tpu.storage.erasure import shard_file_names

        roster = [b.broker_id for b in self.config.brokers]
        if len(roster) < 2:
            return
        my = roster.index(self.broker_id)
        attempts = 0
        for name in shard_file_names(self._store_dir):
            if name in self._pushed_shards:
                continue
            if attempts >= 4:
                break  # bound per-tick work/stall (duty loop is shared)
            idx = int(name.rpartition(".shard")[2])
            candidates = [
                roster[(my + 1 + idx + k) % len(roster)]
                for k in range(len(roster))
            ]
            targets = [
                t for t in candidates
                if t != self.broker_id and t not in self._bad_shard_targets
            ]
            if not targets:
                break  # every peer refuses storage; nothing to do
            path = os.path.join(self._store_dir, "rs", name)
            try:
                with open(path, "rb") as f:
                    blob = f.read()
            except OSError:
                continue
            attempts += 1
            try:
                resp = self.client.call(
                    self._addr_of(targets[0]),
                    {"type": "shard.put", "owner": self.broker_id,
                     "name": name, "data": blob},
                    timeout=self.config.rpc_timeout_s,
                )
            except RpcError:
                continue  # peer down; retried next pass
            if resp.get("ok"):
                self._pushed_shards.add(name)
            elif resp.get("error") == "no_data_dir":
                # Storage-less peer: never a valid target.
                self._bad_shard_targets.add(targets[0])

    # -- metadata ----------------------------------------------------------

    def _handle_meta_propose(self, req: dict) -> dict:
        node = self.runner.node
        if node.role != LEADER:
            hint = node.leader_hint
            return {
                "ok": False,
                "error": "not_leader",
                "leader": hint,
                "leader_addr": self._addr_of(hint) if hint is not None else None,
            }
        index = self.runner.propose(req["cmd"])
        if index is None:
            return {"ok": False, "error": "not_leader", "leader": None}
        return {"ok": True, "index": index}

    def _propose_retry_policy(self, retries: int) -> RetryPolicy:
        """Retry spacing for leader-forwarded proposals. The backoff CAP
        tracks the metadata election timeout, not just the duty
        interval: a leaderless blip lasts about one metadata election,
        and a cap well below it (the old duty-interval-scaled 0.5 s
        ceiling) burned every attempt back-to-back before a new leader
        could exist. Jitter rides the shared RetryPolicy defaults so
        concurrent proposers decorrelate instead of thundering the
        fresh leader together. Extracted so the spacing is directly
        testable (tests/test_group_waves.py)."""
        return RetryPolicy(
            max_attempts=retries,
            base_backoff_s=max(
                self._duty_interval_s,
                self.config.metadata_election_timeout_s / 8,
            ),
            max_backoff_s=max(
                self._duty_interval_s, 0.5,
                self.config.metadata_election_timeout_s,
            ),
            deadline_s=self.config.rpc_timeout_s * max(1, retries),
        )

    def propose_cmd(self, cmd: dict, retries: int = 3) -> bool:
        """Propose a metadata command, forwarding to the metadata leader if
        this broker is not it (the reference's forwarding-with-retries,
        PartitionManager.java:219-246). Retries ride the same unified
        RetryPolicy as the clients (wire/retry.py): jittered exponential
        backoff spaced to the metadata election timescale
        (_propose_retry_policy), the whole operation bounded by one
        rpc-timeout deadline budget — a partitioned metadata leader
        costs a bounded stall, not retries x timeout."""
        policy = self._propose_retry_policy(retries)
        run = policy.begin()
        while run.attempt():
            node = self.runner.node
            if node.role == LEADER:
                if self.runner.propose(cmd) is not None:
                    return True
                run.note("local propose refused (lost leadership?)")
            else:
                hint = node.leader_hint
                if hint is not None and hint != self.broker_id:
                    try:
                        resp = self._raft_client.call(
                            self._addr_of(hint),
                            {"type": "meta.propose", "cmd": cmd},
                            timeout=run.clip(self.config.rpc_timeout_s),
                        )
                        if resp.get("ok"):
                            return True
                        run.note(str(resp.get("error", "")))
                    except RpcError as e:
                        run.note(str(e))
                else:
                    run.note("no metadata leader hint")
        return False

    # -- control-plane wave batching ---------------------------------------
    # Membership/pid commands coalesce into OP_BATCH waves: each broker
    # queues the commands its own RPC handlers receive and proposes ONE
    # wave per meta_batch_s (early at meta_batch_max), so the metadata
    # leader's raft proposal load under a churn storm is O(brokers) per
    # wave interval instead of O(membership events). The wave apply
    # (PartitionManager.apply) defers each touched group's rebalance to
    # the end of the wave — one generation bump per group per wave —
    # and its sub-op idempotence makes a duplicate wave (leader retry
    # straddling a failover) a no-op.

    def _submit_meta(self, cmd: dict) -> bool:
        """Route one metadata command onto the wave intake (meta_batch_s
        > 0) or propose it directly (batching disabled — the pre-wave
        shape, also the bench's 'before' arm). Returns whether the
        command was proposed; the caller still polls its own local
        apply for commitment, unchanged."""
        if self.config.meta_batch_s <= 0:
            return self.propose_cmd(cmd)
        waiter = _WaveWaiter()
        cap = 4 * self.config.meta_batch_max
        with self._intake_lock:
            if len(self._intake) >= cap:
                # Bounded intake: refuse retryably instead of queueing
                # unboundedly — the client's backoff is the ladder.
                return False
            self._intake.append((cmd, waiter))
            full = len(self._intake) >= self.config.meta_batch_max
        if full:
            # A full wave needn't wait for the duty tick: the enqueuing
            # handler thread forms it inline (it would only block on the
            # waiter otherwise).
            self._drain_intake()
        waiter.event.wait(
            self.config.meta_batch_s + self.config.rpc_timeout_s * 3
        )
        return waiter.ok

    def _drain_intake(self) -> None:
        """Form and propose waves until the intake is empty (FIFO; at
        most meta_batch_max commands per wave). Serialized by the drain
        lock — concurrent triggers (duty tick vs a full-queue enqueue)
        must not reorder waves."""
        with self._intake_drain_lock:
            while True:
                with self._intake_lock:
                    batch = self._intake[: self.config.meta_batch_max]
                    del self._intake[: len(batch)]
                if not batch:
                    return
                self._last_wave = time.monotonic()
                cmds = [c for c, _ in batch]
                # Metadata-plane traces are op-identity rooted (no
                # client carried a ctx here): the wave ordinal seeds the
                # same deterministic sampling predicate the clients use.
                wsp = NULL_SPAN
                if self.spans is not None:
                    tid = derive_trace_id(f"wave/broker{self.broker_id}",
                                          self._wave_count)
                    if sampled(tid, self.config.trace_sample_n):
                        wsp = self.spans.span("meta.wave",
                                              TraceContext(tid, 0),
                                              {"size": len(cmds)})
                ok = self.propose_cmd({"op": OP_BATCH, "cmds": cmds})
                wsp.end(ok=ok)
                self._wave_count += 1
                self._wave_events += len(cmds)
                if not ok:
                    self._wave_failures += 1
                bucket = str(1 << (len(cmds) - 1).bit_length())
                self._wave_size_hist[bucket] = (
                    self._wave_size_hist.get(bucket, 0) + 1
                )
                self.recorder.record(
                    "meta_batch", size=len(cmds), ok=ok,
                )
                for _, w in batch:
                    w.ok = ok
                    w.event.set()

    def _batch_duty(self) -> None:
        """Wave cadence: propose the queued commands once meta_batch_s
        has passed since the last wave (size-triggered waves drain
        inline from the enqueuing thread, see _submit_meta)."""
        if self.config.meta_batch_s <= 0:
            return
        with self._intake_lock:
            pending = len(self._intake)
        if not pending:
            return
        if (time.monotonic() - self._last_wave
                < self.config.meta_batch_s):
            return
        self._drain_intake()

    def _fail_pending_waves(self) -> None:
        """stop(): release every parked handler (propose refused)."""
        with self._intake_lock:
            pending = list(self._intake)
            del self._intake[:]
        for _, w in pending:
            w.ok = False
            w.event.set()

    # -- heartbeat relay ---------------------------------------------------

    def _beats_relay_duty(self) -> None:
        """Forward the locally-buffered member beats to the metadata
        leader's liveness ledger as ONE group.beats frame per
        heartbeat_relay_s. A frame that cannot be delivered (no leader,
        leader moved, wire error) re-merges into the buffer and retries
        next tick — the stamps are idempotent monotonic refreshes, and
        the leader-change grace window (GroupLiveness first-sighting
        seeding) absorbs delivery gaps exactly as it absorbs leader
        churn."""
        now = time.monotonic()
        if now - self._last_beat_relay < self.config.heartbeat_relay_s:
            return
        with self._beat_lock:
            if not self._beat_buffer:
                return
            beats = sorted(self._beat_buffer)
            self._beat_buffer.clear()
        self._last_beat_relay = now
        delivered = False
        node = self.runner.node
        if node.role == LEADER:
            # This broker IS the ledger's owner: stamp directly.
            self._ingest_beats(beats)
            delivered = True
        else:
            hint = node.leader_hint
            if hint is not None and hint != self.broker_id:
                try:
                    resp = self._raft_client.call(
                        self._addr_of(hint),
                        {"type": "group.beats",
                         "beats": [[g, m] for g, m in beats]},
                        timeout=min(2.0, self.config.rpc_timeout_s),
                    )
                    delivered = bool(resp.get("ok"))
                except RpcError:
                    delivered = False
        if delivered:
            self._beat_frames += 1
        else:
            with self._beat_lock:
                self._beat_buffer.update(beats)
        self.recorder.record(
            "beats_relay", beats=len(beats), ok=delivered,
        )

    def _ingest_beats(self, beats) -> None:
        """Metadata leader: stamp each relayed (group, member) beat
        whose membership the replicated table confirms — per-member
        stamps preserved, evicted/unknown members dropped (their
        originating broker answers them unknown_member on the next
        heartbeat once the leave applies there)."""
        stamped = 0
        for group, member in beats:
            st = self.manager.group_state(str(group))
            if st is not None and str(member) in st.members:
                self._group_liveness.beat(str(group), str(member))
                stamped += 1
        if stamped:
            # Reached from RPC handler threads (group.beats frames) AND
            # the duty thread (the leader ingesting its own buffer):
            # the counter shares the beat-buffer leaf lock.
            with self._beat_lock:
                self._beats_relayed += stamped

    def _handle_group_beats(self, req: dict) -> dict:
        """One broker's aggregated heartbeat frame (the relay plane's
        leader-side ingestion point)."""
        node = self.runner.node
        if node.role != LEADER:
            hint = node.leader_hint
            return {"ok": False, "error": "not_leader", "leader": hint}
        self._ingest_beats(
            [(str(g), str(m)) for g, m in req.get("beats", [])]
        )
        return {"ok": True}

    # -- data path ---------------------------------------------------------

    def _check_partition(self, key) -> tuple[Optional[int], Optional[dict]]:
        """(engine slot, refusal). Unknown partitions are a TERMINAL error
        (checked before leadership, so clients don't retry nonexistent
        partitions forever); non-leadership is a retryable refusal with a
        hint — unlike the reference, which answered "Not leader" and then
        appended anyway (MessageAppendRequestProcessor.java:29-33)."""
        slot = self.manager.slot_of(key)
        if slot is None:
            return None, {"ok": False, "error": f"unknown_partition: {key}"}
        leader = self.manager.leader_of(key)
        if leader != self.broker_id:
            return None, {
                "ok": False,
                "error": "not_leader",
                "leader": leader,
                "leader_addr": self._addr_of(leader) if leader is not None else None,
            }
        return slot, None

    def _topic_routing(self, topic: str) -> list[dict]:
        """The topic's current assignments on the wire — what a
        `stale_partition_gen:` refusal carries so the refused client
        re-resolves routing FROM THE REFUSAL (generation, ranges,
        leaders) instead of spending a meta.topics round first."""
        for t in self.manager.get_topics():
            if t.name == topic:
                return [a.to_dict() for a in t.assignments]
        return []

    def _gen_refusal(self, req: dict, key) -> Optional[dict]:
        """Partition-generation fence (elastic partitions): a request
        stamped with `pgen` — the generation its sender resolved
        routing under — draws a typed RETRYABLE `stale_partition_gen:`
        refusal the moment a split/merge has bumped the partition's
        generation, with the topic's current assignments attached (the
        groups plane's fenced_generation discipline reapplied to
        partitions). Replicated state only, so EVERY broker fences
        identically. Unstamped requests keep the legacy contract:
        routed by partition id, with keyed writes to a splitting
        parent dual-write-forwarded instead of refused."""
        pgen = req.get("pgen")
        if pgen is None:
            return None
        gen = self.manager.generation_of(key)
        if gen is None or int(pgen) == gen:
            return None
        self._gen_fence_refusals += 1
        return {
            "ok": False,
            "error": f"stale_partition_gen: {key[0]}/{key[1]} generation "
                     f"{int(pgen)} != current {gen}",
            "generation": gen,
            "routing": self._topic_routing(key[0]),
        }

    def _retired_refusal(self, key) -> Optional[dict]:
        """Produce-side fence for a merge-retired child: its log stays
        readable for draining, but new writes must land in the parent
        that reabsorbed the range — same typed refusal + routing
        payload as the generation fence, so one client re-resolve
        handles both."""
        a = self.manager.assignment_of(key)
        if a is None or a.state != "retired":
            return None
        self._gen_fence_refusals += 1
        return {
            "ok": False,
            "error": f"stale_partition_gen: {key[0]}/{key[1]} is retired "
                     f"(range merged into partition {a.origin})",
            "generation": a.generation,
            "routing": self._topic_routing(key[0]),
        }

    def _handle_produce(self, req: dict) -> dict:
        """Admission + ack-latency instrumentation around the produce
        path. Admission runs FIRST — before partition resolution,
        validation, pid stamping, payload packing, or a worker-ring hop
        — so a shed/quota refusal under overload costs one dict lookup
        (slo/admission.py; typed retryable `overloaded:`, so clients
        jitter-backoff instead of hammering the refusal). Admitted
        requests observe their full wall time (success AND failure —
        timeouts are exactly the overload signal) into `produce.ack_us`,
        the p99 the SLO controller steers against."""
        messages = req.get("messages")
        n = len(messages) if isinstance(messages, list) else 1
        # Causal tracing: a sampled produce carries `tctx` (the client
        # root span's context); rpc.recv covers this broker's whole
        # handling, admission its front-door slice. Unsampled requests
        # (no tctx, or tracing off) pay one dict-get and a None branch.
        sp = (self.spans.span("rpc.recv", ctx_from_wire(req.get("tctx")),
                              {"op": "produce"})
              if self.spans is not None else NULL_SPAN)
        asp = (self.spans.span("admission", sp.ctx)
               if sp.ctx is not None else NULL_SPAN)
        refusal = self.slo.admit(req.get("producer"), n)
        asp.end()
        if refusal is not None:
            sp.end(error="overloaded")
            return {"ok": False, "error": f"overloaded: {refusal}"}
        t0 = self.metrics.clock()
        try:
            return self._produce_admitted(req, tctx=sp.ctx)
        finally:
            self._m_ack_us.observe(self.metrics.clock() - t0)
            sp.end()

    # Fields the raw-dispatch peek materializes: the routing/admission
    # scalars (including the elastic-partition fence/routing stamps
    # pgen + key_hash) plus the message VECTOR's element count (never
    # its bytes). `tctx` is peeked only to DETECT a sampled produce
    # (lists peek as element counts, not values): sampled frames take
    # the canonical decode path below, where the full trace context is
    # materialized — at trace_sample_n-th cadence the one extra decode
    # is exactly the kind of overhead sampling exists to amortize.
    _RAW_PEEK = ("type", "topic", "partition", "producer", "pid", "seq",
                 "pgen", "key_hash", "messages", "tctx")

    def _raw_produce(self, body) -> Optional[dict]:
        """Raw-frame produce dispatch (TcpServer accept path, host-plane
        brokers only): peek the routing scalars off the UNDECODED frame
        and hand the bytes to the owning worker, which performs the
        frame's single full decode — deleting the per-batch broker
        decode → ring re-encode → worker decode hop. Returns None for
        anything that is not a clean host-plane produce; the ordinary
        decode path then produces the canonical behavior (byte parity
        between both paths is pinned in tests/test_hostplane.py)."""
        if self.hostplane is None:
            return None
        peek = codec.peek_fields(body, self._RAW_PEEK)
        if peek is None or peek.get("type") != "produce":
            return None
        if peek.get("tctx") is not None:
            return None  # sampled: canonical path records the spans
        n = peek.get("messages")
        if not isinstance(n, int) or n <= 0:
            return None  # empty/odd batch: canonical path refuses it
        if not isinstance(peek.get("topic"), str) \
                or not isinstance(peek.get("partition"), int):
            return None
        refusal = self.slo.admit(peek.get("producer"), n)
        if refusal is not None:
            return {"ok": False, "error": f"overloaded: {refusal}"}
        t0 = self.metrics.clock()
        try:
            return self._produce_admitted(peek, raw=body, raw_count=n)
        finally:
            self._m_ack_us.observe(self.metrics.clock() - t0)

    def _produce_admitted(self, req: dict, raw=None, raw_count: int = 0,
                          tctx=None) -> dict:
        """Produce semantics: at-least-once by default, EXACTLY-ONCE for
        idempotent producers. A batch larger than max_batch is split into
        pipelined rounds, and some rounds can fail while others commit (a
        failed middle round leaves a gap). ALL pipelined rounds are
        drained before responding; on any failure the error carries the
        total number of messages that did commit in `committed`, so a
        client that retries the whole batch knows it is duplicating that
        many (the reference has the same window one message at a time —
        its closure can fail after the Raft entry committed,
        MessageAppendRequestProcessor.java:36-67).

        Idempotence: a request carrying (`pid`, `seq`) — the client SDK's
        registered producer id + its ack-gated per-partition sequence —
        dedupes at the controller's append path (DataPlane.submit_append):
        a replayed sequence is acked with its original base offset, never
        appended twice, including across controller failover (the dedup
        table replicates through the settle path). A pid-less request is
        STAMPED with this broker's own pid + per-slot sequence before
        forwarding, which collapses duplicated leader→controller RPC
        frames the same way — so clean single-attempt acks are
        exactly-once for every client, opted-in or not. Chunk k of a
        split batch takes `seq + k*max_batch`-adjacent sequence ranges,
        reproducibly (max_batch is config-static), so a full-batch replay
        re-chunks identically and every chunk dedupes."""
        key = group_key(req["topic"], req["partition"])
        refusal = self._gen_refusal(req, key)
        if refusal:
            return refusal
        routed = None
        khash = req.get("key_hash")
        if khash is not None:
            owner = self.manager.route_key(req["topic"], int(khash))
            if owner is not None and owner != key[1]:
                # Elastic routing moved this key's range slice (a split
                # begun, a merge landed) and the sender has not
                # re-resolved: FORWARD the write to the current owner
                # instead of refusing — during a handoff the child's
                # leader IS the parent's, so the dual-write is a local
                # slot redirect, and the ack names the routed partition
                # (`routed_partition`) so the sender's history stays
                # attributable to the log the write actually landed in.
                key = group_key(req["topic"], owner)
                routed = owner
        refusal = self._retired_refusal(key)
        if refusal:
            return refusal
        slot, refusal = self._check_partition(key)
        if refusal:
            return refusal
        if raw is None:
            messages = req["messages"]
            if not isinstance(messages, list) or not messages:
                return {"ok": False, "error": "bad_request: empty messages"}
        else:
            # Raw dispatch: the batch is still undecoded wire bytes;
            # only its element count is known (the peek).
            messages = None
        B = self.config.engine.max_batch
        stamped = None
        if self.hostplane is not None:
            # Multi-core host plane: the owning worker validates, stamps
            # (its own per-(worker, generation) pid + per-slot sequence
            # counters — slices are disjoint) and packs the batch into
            # max_batch-sized row blocks that ride to the engine
            # pre-packed (DataPlane.submit_packed / engine.append_packed
            # — the payload bytes are never re-encoded past the worker).
            from ripplemq_tpu.parallel.hostplane import (
                OversizeBatchError,
                WorkerUnavailableError,
            )

            # worker.hop: the broker-side shm-ring round trip; the
            # worker's serve/validate/stamp/pack spans parent under it
            # (hop.ctx rides the ring frame) and ship back inside the
            # response for the broker ring to adopt.
            hop = (self.spans.span("worker.hop", tctx)
                   if self.spans is not None else NULL_SPAN)
            try:
                if raw is not None:
                    stamped = self.hostplane.submit_raw(
                        slot, raw, raw_count,
                        pid=req.get("pid"), seq=req.get("seq"),
                        timeout_s=self.config.rpc_timeout_s,
                    )
                else:
                    stamped = self.hostplane.submit(
                        slot, messages,
                        pid=req.get("pid"), seq=req.get("seq"),
                        timeout_s=self.config.rpc_timeout_s,
                        tctx=None if hop.ctx is None else hop.ctx.wire(),
                    )
                hop.end()
            except WorkerUnavailableError as e:
                hop.end(error="worker_unavailable")
                # Typed RETRYABLE refusal — never a silent hang: the
                # dispatcher already detected the dead worker and is
                # respawning it; the client's retry lands.
                return {"ok": False, "error": f"worker_unavailable: {e}"}
            except OversizeBatchError:
                # The batch would not fit a ring frame: serve it on the
                # in-process path below (no size bound there) instead
                # of refusing — the single-process semantics are the
                # fallback contract for every worker-plane miss.
                stamped = None
            except ValueError as e:
                return {"ok": False, "error": f"bad_request: {e}"}
        if stamped is None and messages is None:
            # The raw fast path missed (oversize batch, no worker):
            # materialize the frame ONCE and run the canonical path —
            # the fallback contract, identical semantics to the dict
            # route.
            full = codec.decode(raw)
            messages = (full.get("messages")
                        if isinstance(full, dict) else None)
            if not isinstance(messages, list) or not messages:
                return {"ok": False, "error": "bad_request: empty messages"}
        if stamped is not None:
            pid, seq = int(stamped["pid"]), int(stamped["seq"])
            chunk_sizes = [len(lens) for lens, _ in stamped["chunks"]]
            futs = [
                self._engine_append_packed(
                    slot, lens, packed, pid,
                    seq + i * B if pid > 0 else -1,
                    tctx=tctx,
                )
                for i, (lens, packed) in enumerate(stamped["chunks"])
            ]
        else:
            if req.get("pid") is not None:
                pid, seq = int(req["pid"]), int(req.get("seq", -1))
            else:
                pid, seq = self._stamp_pid_seq(slot, len(messages))
            chunks = [messages[i : i + B]
                      for i in range(0, len(messages), B)]
            chunk_sizes = [len(c) for c in chunks]
            futs = [
                self._engine_append(
                    slot, chunk, pid,
                    seq + i * B if pid > 0 else -1,
                    tctx=tctx,
                )
                for i, chunk in enumerate(chunks)
            ]
        base0 = None
        committed = 0
        first_err: Optional[Exception] = None
        for n, fut in zip(chunk_sizes, futs):
            try:
                base = fut()
            except NotCommittedError as e:
                if first_err is None:
                    first_err = e
                continue
            if base0 is None and first_err is None:
                base0 = base
            committed += n
        if first_err is not None:
            return {"ok": False, "error": f"not_committed: {first_err}",
                    "committed": committed}
        if routed is not None:
            self._forwarded_writes += 1
            return {"ok": True, "base_offset": base0, "count": committed,
                    "routed_partition": routed}
        return {"ok": True, "base_offset": base0, "count": committed}

    def _quorum_refusal(self, slot: int) -> Optional[dict]:
        """Graceful degradation: when the partition's replica quorum is
        lost (mask says no round can commit), fail FAST with a typed,
        retryable `unavailable` refusal instead of letting the request
        hang into its RPC timeout (consume's auto-commit and offset
        commits ride quorum rounds that are doomed before dispatch).
        Only the controller can see the mask; non-controller leaders get
        the same refusal from the controller's engine.* handlers."""
        dp = self._local_engine()
        if dp is not None and dp.quorum_lost(slot):
            return {"ok": False,
                    "error": f"unavailable: partition slot {slot} lost "
                             f"its replica quorum (degraded; retry after "
                             f"heal)"}
        return None

    def _handle_consume(self, req: dict) -> dict:
        """Ack-latency instrumentation around the consume path (the
        produce.ack_us twin): every answer — leader serve, follower
        serve, refusal — observes its full wall time into
        `consume.ack_us`, the p99 the SLO controller's consume twin
        steers toward slo_p99_consume_ms (via read_coalesce_s)."""
        t0 = self.metrics.clock()
        sp = (self.spans.span("rpc.recv", ctx_from_wire(req.get("tctx")),
                              {"op": "consume"})
              if self.spans is not None else NULL_SPAN)
        try:
            return self._consume_checked(req, tctx=sp.ctx)
        finally:
            sp.end()
            self._m_consume_ack_us.observe(self.metrics.clock() - t0)

    def _consume_checked(self, req: dict, tctx=None) -> dict:
        key = group_key(req["topic"], req["partition"])
        refusal = self._gen_refusal(req, key)
        if refusal:
            return refusal
        slot, refusal = self._check_partition(key)
        if refusal:
            # Follower read path: a non-leader with a valid lease may
            # still answer an explicit-offset consume from its
            # replicated settled floor (client opt-in via follower_ok;
            # broker/follower.py for the safety contract). Anything it
            # cannot prove settled refuses with the retryable
            # `not_settled_here:` and the client falls back to the
            # leader named in the ordinary hint.
            if req.get("follower_ok") and req.get("offset") is not None:
                answer = self._follower_consume(key, req, refusal,
                                                tctx=tctx)
                if answer is not None:
                    return answer
            return refusal
        refusal = self._quorum_refusal(slot)
        if refusal:
            return refusal
        cslot = self._resolve_consumer(req["consumer"])
        if cslot is None:
            return {"ok": False, "error": "consumer_registration_failed"}
        replica = self.manager.replica_slot(key, self.broker_id)
        if replica is None:
            replica = 0  # leader not in replicas: metadata race; read slot 0
        if req.get("offset") is not None:
            # Explicit read position (the consumer SDK's prefetch
            # pipeline): skips the committed-offset lookup; the read is
            # still leadership-checked and settled-horizon-clamped, and
            # the committed offset only moves on offset.commit.
            offset = int(req["offset"])
            if offset < 0:
                return {"ok": False, "error": "bad_request: negative offset"}
        else:
            # Read the offset from the leader's own replica slot too:
            # replica 0 may be masked dead and hold a stale offset table
            # (commits only apply on acking replicas).
            offset = self._engine_read_offset(slot, cslot, replica)
        limit = req.get("max_messages")
        msgs, next_offset = self._engine_read(
            slot, offset, replica, None if limit is None else int(limit),
            wait_s=float(req.get("wait_s", 0) or 0),
        )
        # Offsets are storage offsets (rounds are alignment-padded), so the
        # committable position is next_offset — NOT offset + len(messages).
        return {"ok": True, "messages": msgs, "offset": offset,
                "next_offset": next_offset}

    def _follower_consume(self, key, req: dict, not_leader: dict,
                          tctx=None) -> Optional[dict]:
        """Serve a consume from the follower read plane, or None when
        this broker is not in a position to even try (feature off, no
        lease, stale generation) — the caller then answers the ordinary
        not_leader hint. The lease AND its epoch are re-checked here,
        per answer: a deposed standby drops to the hint the instant the
        handover applies, before its plane even resets."""
        fp = self.follower_plane
        if fp is None:
            return None
        slot = self.manager.slot_of(key)
        if slot is None:
            return None
        epoch = self.manager.current_epoch()
        if self.manager.follower_lease(self.broker_id) != epoch:
            return None
        fp.note_epoch(epoch)  # fence the plane even before new frames
        if fp.epoch() != epoch:
            return None  # cached bytes are another generation's
        offset = int(req["offset"])
        if offset < 0:
            return {"ok": False, "error": "bad_request: negative offset"}
        limit = req.get("max_messages")
        limit = None if limit is None else int(limit)
        fsp = (self.spans.span("follower.serve", tctx, {"slot": slot})
               if self.spans is not None else NULL_SPAN)
        got = None
        if self.hostplane is not None:
            # Shared fan-out on the worker plane: the owning worker's
            # settled mirror (fed by the repl ingest below) serves the
            # hot window off this process's GIL — one mirror read feeds
            # many cursors. Every mirror answer is re-fenced against
            # the floor/gap map before it leaves (the mirror itself
            # holds rows ahead of the floor).
            mirror = self.hostplane.read(slot, offset, limit)
            if (mirror is not None and mirror[0]
                    and fp.validate_window(slot, offset, mirror[1])):
                got = mirror
        if got is None:
            # A cold striped page pays a reconstruct inside fp.read —
            # attribute it (decoded-counter delta detects one) as a
            # child of follower.serve.
            dec0 = fp._decoded
            t0r = self.metrics.clock()
            got = fp.read(slot, offset, limit)
            if fsp.ctx is not None and fp._decoded > dec0:
                self.spans.span_at(
                    "stripe.reconstruct", fsp.ctx, t0r,
                    self.metrics.clock() - t0r,
                    {"groups": fp._decoded - dec0})
        # Last-line witness: EVERY answer (mirror or cache) re-checks
        # against the floor at the boundary, independent of the serving
        # path's own fence — a failed audit refuses and is counted as
        # a first-class chaos violation (answers_past_floor).
        if got is not None and not fp.audit_answer(slot, offset, got[1]):
            got = None
        if got is None:
            fsp.end(error="not_settled_here")
            return {
                "ok": False,
                "error": f"not_settled_here: slot {slot} offset {offset} "
                         f"is above this standby's settled floor",
                "leader": not_leader.get("leader"),
                "leader_addr": not_leader.get("leader_addr"),
            }
        msgs, next_offset = got
        fsp.end(rows=len(msgs))
        return {"ok": True, "messages": msgs, "offset": offset,
                "next_offset": next_offset, "follower": True}

    def _fetch_sibling_stripes(self, min_gsn: int) -> list:
        """FollowerReadPlane.fetch_fn (striped reconstruct-on-read):
        one page round over the live stripe holders' `stripe.fetch`,
        with a persistent forward-only cursor per peer — decode is
        sequential in gsn, so each call streams the NEXT window of
        sibling frames instead of rescanning. Returns parsed frames;
        the plane filters by epoch/gsn."""
        from ripplemq_tpu.stripes.codec import parse_frame

        holders = set(self.manager.current_stripe_map())
        live = set(self.manager.live_brokers())
        out = []
        for b in sorted(holders):
            if b == self.broker_id or b not in live:
                continue
            cur = self._follower_cursors.get(b)
            try:
                resp = self.client.call(
                    self._addr_of(b),
                    {"type": "stripe.fetch",
                     "after": -1 if cur is None else cur,
                     "min_gsn": int(min_gsn),
                     "budget": 2 << 20},
                    timeout=min(2.0, self.config.rpc_timeout_s),
                )
            except RpcError:
                continue
            if not resp.get("ok"):
                continue
            nxt = resp.get("next") or resp.get("last")
            if nxt is not None:
                self._follower_cursors[b] = list(nxt)
            for raw in resp.get("frames") or ():
                frame = parse_frame(bytes(raw))
                if frame is not None:
                    out.append(frame)
        return out

    def _handle_offset_commit(self, req: dict) -> dict:
        key = group_key(req["topic"], req["partition"])
        refusal = self._gen_refusal(req, key)
        if refusal:
            return refusal
        slot, refusal = self._check_partition(key)
        if refusal:
            return refusal
        refusal = self._quorum_refusal(slot)
        if refusal:
            return refusal
        fenced = req.get("group") is not None
        if fenced:
            refusal = self._fence_group_commit(req, key)
            if refusal:
                return refusal
        cslot = self._resolve_consumer(req["consumer"])
        if cslot is None:
            return {"ok": False, "error": "consumer_registration_failed"}
        self._engine_offsets(slot, [(cslot, int(req["offset"]))])
        if fenced:
            # Re-check AFTER the offset round: the fence read (metadata
            # raft) and the offset write (engine round) are separate
            # replication planes, so a rebalance can apply between them.
            # If it did, answer FENCED even though the write landed —
            # the member then delivers nothing, which is exactly the
            # documented commit-before-deliver at-most-once outcome (a
            # crash between commit and delivery behaves identically);
            # answering ok would let a just-deposed member deliver rows
            # the partition's new owner may also deliver. The landed
            # offset itself is monotone and harmless. Residual window:
            # a rebalance applying after this re-check but before the
            # new owner's first read can still skip-or-duplicate at the
            # handover boundary — closing it fully needs the generation
            # carried INSIDE the offset round (ROADMAP, group plane).
            refusal = self._fence_group_commit(req, key)
            if refusal:
                return refusal
        return {"ok": True}

    def _fence_group_commit(self, req: dict, key) -> Optional[dict]:
        """Generation fencing: a group commit must come from a CURRENT
        member of the CURRENT generation that OWNS the partition. A
        stale-generation member — deposed by a rebalance it has not
        observed yet — gets a typed `fenced_generation` refusal, never a
        silent overwrite of the new owner's progress (the group's
        offsets are shared state; this fence is what makes them safe
        under churn). The check reads replicated state, so ANY broker
        serving the commit fences identically."""
        group = str(req["group"])
        member = str(req.get("member", ""))
        gen = int(req.get("generation", -1))
        st = self.manager.group_state(group)
        why = None
        if st is None:
            why = f"group {group!r} does not exist"
        elif member not in st.members:
            why = f"member {member!r} is not in generation {st.generation}"
        elif gen != st.generation:
            why = f"generation {gen} != current {st.generation}"
        elif key not in st.assignment.get(member, ()):
            why = (f"partition {key} is not assigned to {member!r} in "
                   f"generation {st.generation}")
        if why is None:
            return None
        self.recorder.record(
            "fence", group=group, member=member, generation=gen,
            topic=key[0], partition=key[1],
        )
        return {"ok": False, "error": f"fenced_generation: {why}"}

    # -- elastic partitions (online split/merge) ---------------------------

    def _handle_admin_split(self, req: dict) -> dict:
        """Operator/nemesis surface: begin an online split of one
        partition. The proposal carries the parent's device-committed
        log end as the cutover WATERMARK — every write acked before
        this moment lives at or below it, and the reconfig duty gates
        the cutover on the parent's SETTLED floor crossing it (or the
        split_handoff_timeout_s bound), so the routing flip never
        strands an acked write behind an unreplicated prefix. The
        apply re-validates everything and deterministically no-ops
        when infeasible; the pre-checks here just turn the common
        no-op causes into typed answers instead of a timeout."""
        topic = str(req["topic"])
        pid = int(req["partition"])
        key = group_key(topic, pid)
        a = self.manager.assignment_of(key)
        if a is None:
            return {"ok": False, "error": f"unknown_partition: {key}"}
        if a.state != "active":
            return {"ok": False,
                    "error": f"split_infeasible: {topic}/{pid} is in "
                             f"state {a.state!r}"}
        if a.range_hi - a.range_lo < 2:
            return {"ok": False,
                    "error": f"split_infeasible: {topic}/{pid} range "
                             f"[{a.range_lo}, {a.range_hi}) is too "
                             f"narrow to split"}
        if self.manager.spare_slot_count() <= 0:
            return {"ok": False,
                    "error": "split_infeasible: no spare engine slot "
                             "(engine.partitions is a device-static "
                             "shape; splits spend pre-provisioned "
                             "spares)"}
        slot = self.manager.slot_of(key)
        try:
            watermark = self._engine_log_end(slot)
        except (RpcError, NotCommittedError) as e:
            return {"ok": False,
                    "error": f"not_committed: split watermark "
                             f"unobservable: {e}"}
        gen0 = a.generation
        if not self.propose_cmd({
            "op": OP_SPLIT_PARTITION, "topic": topic, "partition": pid,
            "watermark": int(watermark),
        }):
            return {"ok": False,
                    "error": "not_committed: split not proposed"}
        deadline = time.monotonic() + self.config.rpc_timeout_s
        while time.monotonic() < deadline:
            ho = self.manager.current_handoffs().get(key)
            if ho is not None:
                return {"ok": True, "child": int(ho["child"]),
                        "watermark": int(ho["watermark"]),
                        "generation": self.manager.generation_of(key)}
            na = self.manager.assignment_of(key)
            if na is not None and na.generation > gen0:
                # Begun AND cut over between polls: an idle parent's
                # settled floor is already at the watermark, so the
                # reconfig duty closes the window in one pass. The
                # child is the adjacent assignment this split minted.
                child = next(
                    (c.partition_id
                     for t in self.manager.get_topics() if t.name == topic
                     for c in t.assignments
                     if c.origin == pid and c.range_lo == na.range_hi),
                    None,
                )
                if child is not None:
                    return {"ok": True, "child": int(child),
                            "watermark": int(watermark),
                            "generation": na.generation}
            time.sleep(0.01)
        # Committed but no handoff window: the apply no-opped (a racing
        # split/merge changed feasibility between pre-check and apply).
        return {"ok": False,
                "error": "not_committed: split applied as a no-op "
                         "(feasibility changed in flight); re-resolve "
                         "and retry"}

    def _handle_admin_merge(self, req: dict) -> dict:
        """Reverse op: reabsorb an active split child into its parent.
        Validated against the manager's merge-candidate view (adjacent
        ranges, both active, no open handoff) — the apply re-checks the
        same conditions, so a racing proposal no-ops."""
        topic = str(req["topic"])
        parent = int(req["parent"])
        child = int(req["child"])
        if (topic, parent, child) not in self.manager.merge_candidates():
            return {"ok": False,
                    "error": f"merge_infeasible: {topic}/{parent}+"
                             f"{child} is not an adjacent active "
                             f"split pair"}
        if not self.propose_cmd({
            "op": OP_MERGE_PARTITIONS, "topic": topic,
            "parent": parent, "child": child,
        }):
            return {"ok": False,
                    "error": "not_committed: merge not proposed"}
        deadline = time.monotonic() + self.config.rpc_timeout_s
        while time.monotonic() < deadline:
            ca = self.manager.assignment_of(group_key(topic, child))
            if ca is not None and ca.state == "retired":
                return {"ok": True,
                        "generation": self.manager.generation_of(
                            group_key(topic, parent))}
            time.sleep(0.01)
        return {"ok": False,
                "error": "not_committed: merge applied as a no-op "
                         "(pair no longer mergeable); re-resolve and "
                         "retry"}

    # -- producers / groups ------------------------------------------------

    def _handle_producer_register(self, req: dict) -> dict:
        """Issue (or look up) a producer id: proposes the replicated
        registration and waits for the local apply — the same shape as
        consumer registration, minus the slot table (pids are a counter,
        not a fixed device dimension)."""
        name = str(req["name"])
        pid = self.manager.producer_id(name)
        if pid is not None:
            return {"ok": True, "pid": pid}
        if not self._submit_meta(
            {"op": OP_REGISTER_PRODUCER, "producer": name}
        ):
            return {"ok": False, "error": "not_committed: producer "
                                          "registration not proposed"}
        deadline = time.monotonic() + self.config.rpc_timeout_s
        while time.monotonic() < deadline:
            pid = self.manager.producer_id(name)
            if pid is not None:
                return {"ok": True, "pid": pid}
            time.sleep(0.01)
        return {"ok": False, "error": "not_committed: producer "
                                      "registration timed out"}

    def _handle_group(self, t: str, req: dict) -> dict:
        if t == "group.beats":
            # The relay plane's aggregated frame (no single `group`).
            return self._handle_group_beats(req)
        group = str(req["group"])
        if t == "group.describe":
            st = self.manager.group_state(group)
            if st is None:
                return {"ok": True, "exists": False, "generation": -1,
                        "members": [], "assignment": {}}
            return {
                "ok": True, "exists": True, "generation": st.generation,
                "members": sorted(st.members),
                "assignment": {
                    m: [[tp, p] for tp, p in keys]
                    for m, keys in st.assignment.items()
                },
            }
        member = str(req["member"])
        if t == "group.join":
            topics = [str(x) for x in req.get("topics", [])]
            known = {tp.name for tp in self.config.topics}
            bad = [x for x in topics if x not in known]
            if not topics or bad:
                return {"ok": False,
                        "error": f"bad_request: unknown topics {bad}"}
            st = self.manager.group_state(group)
            if (st is None or st.members.get(member)
                    != tuple(sorted(set(topics)))):
                if not self._submit_meta({
                    "op": OP_GROUP_JOIN, "group": group, "member": member,
                    "topics": topics,
                }):
                    return {"ok": False,
                            "error": "not_committed: join not proposed"}
            deadline = time.monotonic() + self.config.rpc_timeout_s
            while time.monotonic() < deadline:
                st = self.manager.group_state(group)
                if st is not None and member in st.members:
                    return self._member_view(st, member)
                time.sleep(0.01)
            return {"ok": False, "error": "not_committed: join timed out"}
        if t == "group.leave":
            st = self.manager.group_state(group)
            if st is None or member not in st.members:
                return {"ok": True}  # idempotent
            if not self._submit_meta({
                "op": OP_GROUP_LEAVE, "group": group, "member": member,
                "reason": str(req.get("reason", "leave")),
            }):
                return {"ok": False,
                        "error": "not_committed: leave not proposed"}
            deadline = time.monotonic() + self.config.rpc_timeout_s
            while time.monotonic() < deadline:
                st = self.manager.group_state(group)
                if st is None or member not in st.members:
                    return {"ok": True}
                time.sleep(0.01)
            return {"ok": False, "error": "not_committed: leave timed out"}
        if t == "group.heartbeat":
            # Answered LOCALLY: membership/generation/assignment are
            # replicated state, identical on every broker, so the
            # member's view needs no leader round trip. The liveness
            # stamp — which IS the metadata leader's ledger — is
            # buffered and rides this broker's next group.beats frame
            # (_beats_relay_duty): leader heartbeat RPC load collapses
            # from O(members) to O(brokers). A member this broker's
            # replicated view does not (yet) hold gets the same
            # unknown_member refusal the leader gave — a lagging view
            # heals by the member's transparent rejoin, an eviction by
            # the same path as before.
            st = self.manager.group_state(group)
            if st is None or member not in st.members:
                return {"ok": False,
                        "error": f"unknown_member: {member!r} not in "
                                 f"{group!r} (evicted or never joined); "
                                 f"rejoin required"}
            with self._beat_lock:
                self._beat_buffer.add((group, member))
            self._heartbeats_local += 1
            return self._member_view(st, member)
        return {"ok": False, "error": f"unknown request type {t!r}"}

    def _member_view(self, st, member: str) -> dict:
        return {
            "ok": True,
            "generation": st.generation,
            "members": sorted(st.members),
            "assignment": [
                [tp, p] for tp, p in st.assignment.get(member, ())
            ],
        }

    def _resolve_consumer(self, consumer: str) -> Optional[int]:
        """Consumer name → replicated slot, registering on first sight.

        The reference keys offsets by raw consumerId strings inside each
        partition state machine (PartitionStateMachine.java:27); here the
        name→slot binding is cluster metadata and the device table is
        int-indexed."""
        slot = self.manager.consumer_slot(consumer)
        if slot is not None:
            return slot
        cmd = {
            "op": OP_REGISTER_CONSUMER,
            "consumer": consumer,
            "slot": self.manager.next_consumer_slot(),
        }
        if not self.propose_cmd(cmd):
            return None
        deadline = time.monotonic() + self.config.rpc_timeout_s
        while time.monotonic() < deadline:
            slot = self.manager.consumer_slot(consumer)
            if slot is not None:
                return slot
            time.sleep(0.01)
        # Concurrent registrations can fill the table between this
        # broker's pre-proposal slot pick and the replicated apply, which
        # then drops the command (manager._apply_register_consumer); probe
        # fullness so that race surfaces as the same typed refusal as the
        # pre-proposal check instead of a generic registration timeout.
        # Re-check the name on BOTH sides of the probe: its own apply may
        # land just past the poll deadline (even filling the table), and
        # a successful registration must never surface as the permanent,
        # non-retryable refusal.
        slot = self.manager.consumer_slot(consumer)
        if slot is not None:
            return slot
        try:
            self.manager.next_consumer_slot()
        except ConsumerTableFullError:
            slot = self.manager.consumer_slot(consumer)
            if slot is not None:
                return slot
            raise
        return None

    # -- engine access (direct on the controller, RPC from peers) ---------

    def _controller_addr(self) -> str:
        return self._addr_of(self.manager.current_controller())

    def _engine_call(self, req: dict) -> dict:
        resp = self.client.call(
            self._controller_addr(), req, timeout=self.config.rpc_timeout_s
        )
        if not resp.get("ok"):
            err = str(resp.get("error", ""))
            if err.startswith("unavailable:"):
                # Typed degradation refusal (quorum lost): pass it to
                # the client verbatim — a non-controller leader must
                # surface the same `unavailable:` prefix the controller
                # serves directly (_quorum_refusal).
                raise _UpstreamRefusal(resp)
            if "not_committed" in err or "not_controller" in err:
                # not_controller is TRANSIENT (controller booting after
                # restart — gated on metadata freshness — or moving):
                # surface the same retryable refusal as an uncommitted
                # round, not an opaque internal RpcError.
                raise NotCommittedError(err)
            raise RpcError(f"engine call failed: {err}")
        return resp

    def _stamp_pid_seq(self, slot: int, n: int) -> tuple[int, int]:
        """Broker-side idempotence stamp for a pid-less produce: this
        broker's own pid (once its registration applied — see the duty)
        plus `n` sequence numbers from the per-slot counter. (0, -1)
        while the pid is still registering: the produce flows unstamped
        rather than stall behind the metadata raft."""
        # The pid adopt and the sequence stamp share ONE critical
        # section (_stamp_lock): the duty's reap-adoption also writes
        # _broker_pid, and an unguarded lazy write here could stamp a
        # sequence against a pid the duty was swapping out from under
        # it (ownership lint, PR 11 — the stamp and its pid must be one
        # consistent pair).
        with self._stamp_lock:
            pid = self._broker_pid
            if pid is None:
                pid = self.manager.producer_id(self._broker_pid_name)
                if pid is None:
                    return 0, -1
                self._broker_pid = pid
            seq = self._stamp_seqs.get(slot, 0)
            self._stamp_seqs[slot] = seq + n
        return pid, seq

    def _producer_pid_duty(self) -> None:
        """Register this broker's stamping pid with the metadata plane
        (once; re-proposed at 1 s spacing until the apply lands). The
        name embeds a per-boot nonce, so a restarted broker gets a FRESH
        pid — its in-memory sequence counters restart at zero, and
        reusing the old pid would collide with the table the cluster
        still holds for it. A registered pid then RE-REGISTERS at a
        third of pid_retention_s: the registration apply bumps the
        replicated seen counter, which is the session refresh the
        pid reaper keys on — a live broker's stamping pid never
        expires."""
        now = time.monotonic()
        cur = self.manager.producer_id(self._broker_pid_name)
        if cur is not None and cur != self._broker_pid:
            # ADOPT whatever pid the registry holds for our name: if the
            # old pid was reaped while this broker was partitioned past
            # the retention window, the refresh below re-registered the
            # name under a FRESH pid — stamping must move to it, or
            # every stamp would ride a reaped pid whose dedup entries
            # the reconciler deletes each tick (a silent duplicate
            # window on the forwarded hop). Sequence counters carry
            # over safely: the fresh pid's table is empty, so every
            # current counter value is above its settled end. Adopted
            # under _stamp_lock — the stamping path reads pid + seq as
            # one pair under the same lock (ownership lint, PR 11).
            with self._stamp_lock:
                self._broker_pid = cur
        if cur is not None:
            retention = self.config.pid_retention_s
            if retention <= 0:
                return
            if now - self._broker_pid_refreshed < max(1.0, retention / 3):
                return
            self._broker_pid_refreshed = now
            self.propose_cmd(
                {"op": OP_REGISTER_PRODUCER,
                 "producer": self._broker_pid_name},
                retries=1,
            )
            return
        if now - self._broker_pid_proposed < 1.0:
            return
        self._broker_pid_proposed = now
        self._broker_pid_refreshed = now
        self.propose_cmd(
            {"op": OP_REGISTER_PRODUCER, "producer": self._broker_pid_name},
            retries=1,
        )

    def _pid_reap_duty(self) -> None:
        """Producer-id expiry (the PR 7 grow-forever residual closed):
        pids get sessions like groups got. The metadata LEADER stamps
        each pid's replicated seen counter into a volatile per-tenure
        ledger; a pid whose counter has not moved for pid_retention_s
        is reaped via OP_RETIRE_PRODUCER — whose apply re-checks the
        counter, so a racing re-registration (ProducerClient refreshes
        at pid_refresh_s; the broker stamping pid at retention/3)
        always wins. The CONTROLLER side reconciles its dedup table
        against the registry on the same cadence: boot replay rebuilds
        REC_PIDSEQ entries for pids reaped while it was down, and
        those must not linger (admin.stats `pid_table_size` stops
        growing monotonically under client churn — the directed test's
        assertion)."""
        retention = self.config.pid_retention_s
        if retention <= 0:
            return
        now = time.monotonic()
        # Controller-side reconciliation (any broker with the plane).
        dp = self._local_engine()
        if dp is not None and now - self._last_pid_reconcile >= max(
            1.0, min(5.0, retention / 4)
        ):
            self._last_pid_reconcile = now
            keep, next_pid = self.manager.registered_pids()
            dp.retain_pids(keep | {0}, below=next_pid)
        node = self.runner.node
        if node.role != LEADER:
            # Stamps from a previous tenure are stale the moment the
            # lease moves (the group-liveness rule): clear, so a fresh
            # leader grants every pid a full retention window.
            self._pid_seen_at.clear()
            return
        sessions = self.manager.producer_sessions()
        for name in list(self._pid_seen_at):
            if name not in sessions:
                del self._pid_seen_at[name]
        for name, (pid, seen) in sessions.items():
            prev = self._pid_seen_at.get(name)
            if prev is None or prev[0] != seen:
                self._pid_seen_at[name] = (seen, now)
                continue
            if now - prev[1] > retention:
                self._pid_seen_at.pop(name, None)
                log.info("broker %d: reaping idle producer id %d (%s)",
                         self.broker_id, pid, name)
                self.propose_cmd(
                    {"op": OP_RETIRE_PRODUCER, "producer": name,
                     "seen": seen},
                    retries=1,
                )

    def _engine_append(self, slot: int, messages: list[bytes],
                       pid: int = 0, seq: int = -1,
                       tctx=None) -> Callable[[], int]:
        """Returns a waiter so multi-chunk produces pipeline their rounds
        (both paths submit WITHOUT blocking: local futures, or pipelined
        RPC frames when a TcpClient with call_async is underneath).
        `tctx` (a sampled produce's TraceContext) rides into the local
        plane's pending entry — the settle release emits the six stage
        spans under it — or onto the forwarded engine.append frame for
        the controller to do the same."""
        dp = self._local_engine()
        if dp is not None:
            fut = dp.submit_append(slot, messages, pid=pid, seq=seq,
                                   tctx=tctx)
            return lambda: int(fut.result(timeout=self.config.rpc_timeout_s))
        req = {"type": "engine.append", "slot": slot, "messages": messages,
               "pid": pid, "seq": seq}
        if tctx is not None:
            req["tctx"] = tctx.wire()
        call_async = getattr(self.client, "call_async", None)
        if call_async is None:  # in-proc transport: synchronous by design
            resp = self._engine_call(req)
            return lambda: int(resp["base_offset"])
        rpc_fut = call_async(self._controller_addr(), req)

        def wait() -> int:
            resp = rpc_fut.result(timeout=self.config.rpc_timeout_s)
            if not resp.get("ok"):
                if "not_committed" in str(resp.get("error", "")):
                    raise NotCommittedError(resp["error"])
                raise RpcError(f"engine call failed: {resp.get('error')}")
            return int(resp["base_offset"])

        return wait

    def _engine_append_packed(self, slot: int, lens: list[int], packed,
                              pid: int = 0, seq: int = -1,
                              tctx=None) -> Callable[[], int]:
        """The pre-packed twin of _engine_append: the host-plane worker
        already validated + packed the rows, so the local path hands the
        block to DataPlane.submit_packed and the forwarded path ships it
        as ONE engine.append_packed frame — the payload bytes cross the
        leader→controller hop exactly once, in engine row format."""
        dp = self._local_engine()
        if dp is not None:
            fut = dp.submit_packed(slot, packed, lens, pid=pid, seq=seq,
                                   tctx=tctx)
            return lambda: int(fut.result(timeout=self.config.rpc_timeout_s))
        req = {"type": "engine.append_packed", "slot": slot,
               "lens": list(lens), "packed": packed,
               "pid": pid, "seq": seq}
        if tctx is not None:
            req["tctx"] = tctx.wire()
        call_async = getattr(self.client, "call_async", None)
        if call_async is None:  # in-proc transport: synchronous by design
            resp = self._engine_call(req)
            return lambda: int(resp["base_offset"])
        rpc_fut = call_async(self._controller_addr(), req)

        def wait() -> int:
            resp = rpc_fut.result(timeout=self.config.rpc_timeout_s)
            if not resp.get("ok"):
                if "not_committed" in str(resp.get("error", "")):
                    raise NotCommittedError(resp["error"])
                raise RpcError(f"engine call failed: {resp.get('error')}")
            return int(resp["base_offset"])

        return wait

    def _mirror_publish(self, slot: int, base: int, payload) -> None:
        """DataPlane.mirror_fn: fan settled REC_APPEND rows out to the
        owning host worker (settle thread; HostPlane.publish never
        blocks — drops degrade to engine-read fallbacks)."""
        hp = self.hostplane
        if hp is not None:
            hp.publish(slot, base, payload)

    def _worker_pid_duty(self) -> None:
        """Host-plane stamping pids: register one metadata pid per
        (worker, generation) and install it in the worker. A RESPAWNED
        worker restarts its sequence counters at zero, so it must stamp
        under a FRESH pid (gen is in the name) — riding the old pid
        would collapse fresh batches as replays in the cluster dedup
        table. Until its pid applies, a fresh worker stamps (0, -1)
        and produces flow unstamped (at-least-once, the pre-stamping
        behavior). Registered pids re-register at a third of
        pid_retention_s, the same session-refresh rule as the broker's
        own stamping pid."""
        hp = self.hostplane
        if hp is None:
            return
        now = time.monotonic()
        retention = self.config.pid_retention_s
        for idx, gen in enumerate(hp.generations()):
            known = self._worker_pid_names.get(idx)
            if known is None or known[0] != gen:
                self._worker_pid_names[idx] = (gen, (
                    f"_broker/{self.broker_id}/{self._pid_nonce}"
                    f"/w{idx}g{gen}"
                ))
                self._worker_pids.pop(idx, None)
                self._worker_pid_proposed.pop(idx, None)
            _, name = self._worker_pid_names[idx]
            pid = self.manager.producer_id(name)
            if pid is None:
                if now - self._worker_pid_proposed.get(idx, 0.0) >= 1.0:
                    self._worker_pid_proposed[idx] = now
                    self.propose_cmd(
                        {"op": OP_REGISTER_PRODUCER, "producer": name},
                        retries=1,
                    )
                continue
            if self._worker_pids.get(idx) != pid:
                self._worker_pids[idx] = pid
                # gen-fenced: a respawn since the snapshot above must
                # drop this install (the pid belongs to the OLD
                # generation's counters; the next duty tick registers
                # the fresh generation's own pid).
                hp.set_worker_pid(idx, pid, gen=gen)
            elif (retention > 0 and
                  now - self._worker_pid_proposed.get(idx, 0.0)
                  >= max(1.0, retention / 3)):
                # Session refresh: the re-registration apply bumps the
                # replicated seen counter the pid reaper keys on.
                self._worker_pid_proposed[idx] = now
                self.propose_cmd(
                    {"op": OP_REGISTER_PRODUCER, "producer": name},
                    retries=1,
                )

    def _read_barrier(self) -> None:
        """linearizable_reads: confirm this broker still commands the
        current controller epoch before serving committed data (off by
        default — see ClusterConfig.linearizable_reads for semantics
        and cost)."""
        if not self.config.linearizable_reads:
            return
        self._barrier_gate.wait(
            timeout_s=min(5.0, self.config.rpc_timeout_s)
        )

    def _fire_read_barrier(self) -> None:
        rep = self._replicator
        if rep is None:
            # No standby stream configured (standby_count 0): controller
            # failover is disabled, so no newer epoch can exist to fence
            # against — the local engine is trivially current.
            return
        rep.replicate([], timeout_s=min(2.0, self.config.rpc_timeout_s))

    # Long-poll ceiling: a waiting consume parks one RPC worker, so the
    # server-side wait is clipped well below any client RPC timeout (and
    # the worker pool size bounds how many can park at once).
    _LONG_POLL_CAP_S = 10.0

    def _engine_read(self, slot: int, offset: int, replica: int,
                     max_msgs: Optional[int] = None,
                     wait_s: float = 0.0):
        dp = self._local_engine()
        if dp is not None:
            self._read_barrier()
            if self.hostplane is not None:
                # Settled-mirror fast path: the owning worker serves the
                # hot window off this process's GIL. Only a NON-EMPTY
                # answer short-circuits — empty/behind/unavailable all
                # fall through to the plane, which stays the authority
                # (and owns the long-poll park below).
                got = self.hostplane.read(slot, offset, max_msgs)
                if got is not None and got[0]:
                    return got
            msgs, end = dp.read(slot, offset, replica, max_msgs)
            if msgs or wait_s <= 0:
                return msgs, end
            # Long-poll: an empty fetch parks here until rows settle
            # past `offset` or the window lapses, so a tail consumer
            # costs one RPC per DELIVERY instead of one per poll. The
            # re-read fires off the settled-horizon watermark — a
            # host-RAM check per tick, no device dispatch (the barrier
            # above stays valid: rows arriving during the wait are
            # NEWER than the proof, never staler).
            deadline = time.monotonic() + min(wait_s, self._LONG_POLL_CAP_S)
            # Park RELATIVE to the read's advance: an empty-but-advanced
            # answer (offset below a settled gap or an all-padding tail)
            # moves the wake watermark to its end, so the wait arms on
            # rows settling PAST the dead range instead of re-reading
            # the same advance every tick for the whole window — and the
            # window still parks (one RPC per delivery, not one per
            # client poll) when the tail past the advance is idle. The
            # advance itself reaches the client in `end` either way.
            wait_from = max(offset, end)
            while time.monotonic() < deadline:
                if self._stop.wait(timeout=0.01):
                    break
                if self._local_engine() is not dp:
                    break  # deposed mid-wait: refuse via the normal path
                # Locked accessor (the mirror_gap_slots advisor
                # pattern): the settle thread mutates the horizon and
                # the gap table together, and a bare array reach-in
                # here was the one read-side consumer of plane
                # internals outside the plane's own lock discipline.
                if dp.settled_end(slot) > wait_from:
                    msgs, end = dp.read(slot, wait_from, replica, max_msgs)
                    if msgs:
                        break
                    wait_from = max(wait_from, end)
            return msgs, end
        resp = self._engine_call(
            {"type": "engine.read", "slot": slot, "offset": offset,
             "replica": replica, "max_msgs": max_msgs,
             # The forwarded wait must finish inside the engine-call RPC
             # timeout or the long poll would read as a dead controller.
             "wait_s": min(wait_s, max(0.0, self.config.rpc_timeout_s - 1))}
        )
        return list(resp["messages"]), int(resp["end"])

    def _engine_log_end(self, slot: int) -> int:
        """The slot's device-committed absolute log end, from the local
        plane or the controller's (the split watermark observation —
        admin.split can be served by any broker)."""
        dp = self._local_engine()
        if dp is not None:
            return dp.log_end(slot)
        resp = self._engine_call({"type": "engine.log_end", "slot": slot})
        return int(resp["end"])

    def _engine_read_offset(self, slot: int, cslot: int, replica: int = 0) -> int:
        dp = self._local_engine()
        if dp is not None:
            return dp.read_offset(slot, cslot, replica)
        resp = self._engine_call(
            {"type": "engine.read_offset", "slot": slot, "cslot": cslot,
             "replica": replica}
        )
        return int(resp["offset"])

    def _engine_offsets(self, slot: int, updates: list[tuple[int, int]]) -> None:
        dp = self._local_engine()
        if dp is not None:
            dp.submit_offsets(slot, updates).result(
                timeout=self.config.rpc_timeout_s
            )
            return
        self._engine_call(
            {"type": "engine.offsets", "slot": slot,
             "updates": [[s, o] for s, o in updates]}
        )

    def _handle_engine(self, t: str, req: dict) -> dict:
        dp = self._local_engine()
        if dp is None:
            return {"ok": False, "error": "not_controller",
                    "controller_addr": self._controller_addr()}
        if t in ("engine.append", "engine.append_packed"):
            # Forwarded append from a non-controller leader: a sampled
            # produce's tctx rode the frame — the controller's rpc.recv
            # span closes the leader→controller cross-process edge and
            # parents the engine stage spans (settle release emits them
            # under the pending entry's tctx).
            sp = (self.spans.span("rpc.recv", ctx_from_wire(req.get("tctx")),
                                  {"op": t})
                  if self.spans is not None else NULL_SPAN)
            try:
                if t == "engine.append":
                    fut = dp.submit_append(
                        int(req["slot"]), list(req["messages"]),
                        pid=int(req.get("pid", 0) or 0),
                        seq=int(req.get("seq", -1)
                                if req.get("seq") is not None else -1),
                        tctx=sp.ctx,
                    )
                else:
                    fut = dp.submit_packed(
                        int(req["slot"]), req["packed"],
                        [int(x) for x in req["lens"]],
                        pid=int(req.get("pid", 0) or 0),
                        seq=int(req.get("seq", -1)
                                if req.get("seq") is not None else -1),
                        tctx=sp.ctx,
                    )
                return {"ok": True, "base_offset":
                        int(fut.result(self.config.rpc_timeout_s))}
            finally:
                sp.end()
        if t == "engine.read":
            limit = req.get("max_msgs")
            msgs, end = self._engine_read(
                int(req["slot"]), int(req["offset"]), int(req["replica"]),
                None if limit is None else int(limit),
                wait_s=float(req.get("wait_s", 0) or 0),
            )
            return {"ok": True, "messages": msgs, "end": end}
        if t == "engine.read_offset":
            return {"ok": True, "offset": dp.read_offset(
                int(req["slot"]), int(req["cslot"]),
                int(req.get("replica", 0)))}
        if t == "engine.log_end":
            return {"ok": True, "end": dp.log_end(int(req["slot"]))}
        if t == "engine.offsets":
            refusal = self._quorum_refusal(int(req["slot"]))
            if refusal:
                return refusal
            fut = dp.submit_offsets(
                int(req["slot"]), [(int(s), int(o)) for s, o in req["updates"]]
            )
            fut.result(self.config.rpc_timeout_s)
            return {"ok": True}
        return {"ok": False, "error": f"unknown engine op {t!r}"}

    def _handle_repl_rounds(self, req: dict) -> dict:
        """Standby side of committed-round replication
        (broker/replication.py). Epoch-fenced: rejecting a stale epoch is
        what deposes an old controller — its resolver fails the round
        with FencedError and producers re-route."""
        epoch = int(req["epoch"])
        cur = self.manager.current_epoch()
        if epoch < cur:
            return {"ok": False, "error": "stale_epoch", "epoch": cur}
        if (
            self.dataplane is not None
            and self.manager.current_controller() == self.broker_id
        ):
            # Our metadata lags a newer epoch (or a deposed peer streams
            # at ours): refuse non-fatally; the sender retries until the
            # fence duty on one side resolves it.
            return {"ok": False, "error": "active_controller"}
        if self._store_quarantined and not self._quarantine_left_set:
            # This broker's store was quarantined (reopened EMPTY) while
            # the replicated metadata still lists it as a standby from
            # BEFORE it died. Acking live rounds now would keep that
            # stale membership looking healthy — and a later promotion
            # would serve the suffix-only store as the full history
            # (observed in the proc disk-fault drills as a total acked-
            # history reset). Refuse until the controller prunes us from
            # the set (the sender flags us suspect on this error) and
            # re-admits via the full catch-up stream.
            return {"ok": False, "error": "store_quarantined"}
        store = self._round_store
        if store is None:
            return {"ok": False, "error": "no_store"}
        sseq = req.get("sseq")
        gate_key = None
        if sseq is not None:
            # Pipelined stream: apply strictly in per-stream sequence
            # order (see _ReplStreamGate — duplicates re-apply, gaps
            # refuse with the expected counter so the sender rewinds).
            sseq = int(sseq)
            gate_key = (int(req.get("sender", -1)), epoch)
            if not self._repl_gate.enter(gate_key, sseq):
                return {"ok": False,
                        "error": "repl_seq_gap: pipelined predecessor "
                                 "frame missing; rewind onto expected",
                        "expected": self._repl_gate.expected(gate_key)}
        recs = [(int(t), int(s), int(b), p) for t, s, b, p in req["records"]]
        # Standby-side apply spans: one repl.apply per sampled produce
        # whose tctx rode the frame — the cross-process child the
        # assembler pairs with the sender's repl.send for this edge's
        # clock-skew estimate.
        sps = ([self.spans.span("repl.apply", ctx_from_wire(raw),
                                {"records": len(recs)})
                for raw in req.get("tctx", ())]
               if self.spans is not None else ())
        append_many = getattr(store, "append_many", None)
        if append_many is not None:
            append_many(recs)  # one batched write per frame (group commit)
        else:
            for rec in recs:
                store.append(*rec)
        if gate_key is not None:
            self._repl_gate.applied(gate_key, sseq)
        for s in sps:
            s.end()
        fp = self.follower_plane
        if fp is not None:
            # Feed the follower read plane: this frame's rows plus the
            # leader's piggybacked floor stamp (missing on frames from
            # pre-floor senders — the plane then holds rows it cannot
            # yet serve, which is the safe direction).
            fp.ingest_rounds(epoch, recs, req.get("floors"))
            if self.hostplane is not None:
                # Worker-plane fan-out: mirror the replicated rows into
                # the owning worker so follower reads ride the same
                # settled-mirror path leader reads do. Mirror answers
                # are floor-fenced per read (_follower_consume); the
                # rows themselves are exactly the store's, so a later
                # promotion of this broker serves them identically.
                for t, s, b, p in recs:
                    if t == REC_APPEND:
                        self._mirror_publish(int(s), int(b), p)
        if self.config.durability == "strict":
            # durability=strict: this ack gates a settled round's
            # producer ack, so the records must be ON DISK before it
            # returns — strict deployments opt out of the flush_async
            # one-interval lag on the standby path too (the controller's
            # settle-side persist honors the same knob,
            # DataPlane._persist_round).
            store.flush()
            return {"ok": True}
        now = time.monotonic()
        if now - self._repl_last_flush >= 0.05:
            # Deferred fsync (SegmentStore.flush_async): the ack this
            # handler returns gates the controller's settle pipeline, so
            # it must not wait out the filesystem's fsync latency. The
            # promoted-standby boot path still runs its OWN synchronous
            # flush barrier before the replay scan (_boot_dataplane).
            flush = getattr(store, "flush_async", store.flush)
            flush()
            self._repl_last_flush = now
        return {"ok": True}

    def _handle_repl_stripes(self, req: dict) -> dict:
        """Standby side of STRIPED replication (stripes/plane.py): the
        repl.rounds fences verbatim, then each frame is CRC-validated
        and persisted as a REC_STRIPE record — a frame damaged in
        flight REFUSES (`bad_stripe_frame`; the sender re-sends from
        its in-memory copy), never lands, so the store only ever holds
        frames the recovery path can trust byte-for-byte."""
        from ripplemq_tpu.storage.segment import REC_STRIPE
        from ripplemq_tpu.stripes.codec import parse_frame

        epoch = int(req["epoch"])
        cur = self.manager.current_epoch()
        if epoch < cur:
            return {"ok": False, "error": "stale_epoch", "epoch": cur}
        if (
            self.dataplane is not None
            and self.manager.current_controller() == self.broker_id
        ):
            return {"ok": False, "error": "active_controller"}
        if self._store_quarantined and not self._quarantine_left_set:
            # Same stale-membership fence as repl.rounds: an emptied
            # store must not ack stripes under pre-death membership.
            return {"ok": False, "error": "store_quarantined"}
        store = self._round_store
        if store is None:
            return {"ok": False, "error": "no_store"}
        recs = []
        frames = []
        for raw in req["frames"]:
            raw = bytes(raw)
            frame = parse_frame(raw)
            if frame is None:
                return {"ok": False, "error": "bad_stripe_frame"}
            frames.append(frame)
            recs.append(
                (REC_STRIPE, frame.idx, int(frame.gsn) & 0x7FFFFFFF, raw)
            )
        # Holder-side apply spans (stripe.apply), one per sampled
        # produce whose tctx rode the batch — pairs with the sender's
        # stripe.send for the skew estimate on this edge.
        sps = ([self.spans.span("stripe.apply", ctx_from_wire(raw),
                                {"frames": len(frames)})
                for raw in req.get("tctx", ())]
               if self.spans is not None else ())
        append_many = getattr(store, "append_many", None)
        if append_many is not None:
            append_many(recs)
        else:
            for rec in recs:
                store.append(*rec)
        for s in sps:
            s.end()
        fp = self.follower_plane
        if fp is not None:
            # Feed the follower read plane's own-stripe window + gsn
            # floor (decode is lazy — reconstruct-on-read).
            for frame in frames:
                fp.ingest_stripe(epoch, frame)
        if self.config.durability == "strict":
            store.flush()
            return {"ok": True}
        now = time.monotonic()
        if now - self._repl_last_flush >= 0.05:
            flush = getattr(store, "flush_async", store.flush)
            flush()
            self._repl_last_flush = now
        return {"ok": True}

    def _handle_stripe_fetch(self, req: dict) -> dict:
        """Serve this broker's persisted stripe frames to a PROMOTED
        peer rebuilding the full stream (stripes/recovery.py): paged
        scan of REC_STRIPE records, cursor = ordinal among them. Served
        by any broker with a store, unfenced — recovery runs exactly
        when controllership is in flux."""
        from ripplemq_tpu.storage.segment import REC_STRIPE

        store = self._round_store
        if store is None:
            return {"ok": False, "error": "no_store"}

        def stripe_records():
            # The LIVE store first, then any `.prestripe-N` snapshots a
            # previous promotion of THIS broker preserved: the rebuild
            # rewrites the store to full records, and without serving
            # the preserved stripes a later promotion elsewhere could
            # find the cluster short of k (observed in the first smoke
            # as an unrecoverable-group boot loop). Yields (cursor,
            # payload) where cursor = [phase, segment, offset] — a
            # STABLE position (segments GC whole; surviving locators
            # never shift), unlike a flat ordinal, which retention trim
            # between two pages would slide under the requester,
            # silently skipping frames. A store without stable locators
            # (MemoryRoundStore) never GCs, so its record ordinal is
            # stable too.
            if hasattr(store, "scan_indexed"):
                it = store.scan_indexed()
            else:
                it = ((t, s, b, p, i) for i, (t, s, b, p)
                      in enumerate(store.scan()))
            for j, (t, _s, _b, payload, loc) in enumerate(it):
                if t != REC_STRIPE:
                    continue
                if isinstance(loc, tuple):
                    yield [0, int(loc[0]), int(loc[1])], int(_b), payload
                else:
                    yield [0, 0, j], int(_b), payload
            if self._store_dir is not None:
                import glob as _glob
                import os as _os

                from ripplemq_tpu.storage.segment import scan_store_indexed

                def _n(p):
                    try:
                        return int(p.rsplit("-", 1)[1])
                    except ValueError:
                        return 1 << 30
                dirs = sorted(
                    _glob.glob(self._store_dir + ".prestripe-*"), key=_n
                )
                for phase, d in enumerate(dirs, start=1):
                    if not _os.path.isdir(d):
                        continue
                    try:
                        for t, _s, _b, payload, loc in scan_store_indexed(d):
                            if t == REC_STRIPE:
                                yield ([phase, int(loc[0]), int(loc[1])],
                                       int(_b), payload)
                    except Exception:
                        continue  # forensic snapshot rot: best-effort

        after = req.get("after", -1)
        after = None if after in (-1, None) else list(after)
        budget = int(req.get("budget") or (32 << 20))
        # Optional gsn floor (follower reconstruct-on-read pager): skip
        # frames below it CHEAPLY off the persisted record's base field
        # — REC_STRIPE stores `gsn & 0x7FFFFFFF` there, so both sides
        # compare masked. Skipped frames still advance the served
        # cursor (`last`), keeping the pager forward-only.
        min_gsn = req.get("min_gsn")
        min_gsn = None if min_gsn is None else int(min_gsn) & 0x7FFFFFFF
        frames: list[bytes] = []
        nxt = None
        last = None
        for cursor, gsn, payload in stripe_records():
            if after is not None and cursor <= after:
                continue
            last = cursor
            if min_gsn is not None and gsn < min_gsn:
                continue
            frames.append(payload)
            budget -= len(payload)
            if budget <= 0:
                nxt = cursor
                break
        # `next` keeps its recovery-pager meaning (set only when the
        # budget clipped the scan); `last` is the cursor of the final
        # record CONSIDERED, so an incremental pager can resume past
        # everything already seen even on a short page.
        return {"ok": True, "frames": frames, "next": nxt, "last": last}

    # ---------------------------------------------------------------- duty

    def _duty_loop(self) -> None:
        while not self._stop.wait(self._duty_interval_s):
            try:
                self._batch_duty()
                self._beats_relay_duty()
                self._metadata_leader_duty()
                self._producer_pid_duty()
                self._worker_pid_duty()
                self._pid_reap_duty()
                self._group_duty()
                self._abdicate_duty()
                self._fence_duty()
                self._takeover_duty()
                self._controller_duty()
                self._slot_clean_duty()
                self._standby_duty()
                self._quota_share_duty()
                self._follower_lease_duty()
                self._reconfig_duty()
                self._autosplit_duty()
                self._shard_duty()
            except Exception as e:  # duties must never kill the loop
                log.warning("broker %d duty error: %s: %s",
                            self.broker_id, type(e).__name__, e)
                with self._errors_lock:
                    self.duty_errors.append(f"{type(e).__name__}: {e}")
                    del self.duty_errors[:-20]

    def _quota_share_duty(self) -> None:
        """Cluster-level quotas: rescale this broker's per-tenant
        admission buckets by its CURRENT share of partition leaderships
        (slo/admission.py set_leadership_share) — a tenant's quota is a
        cluster rate, not rate × brokers. Floored at one partition's
        worth even with zero leaderships: admission runs before the
        leadership check in the produce handler, and a zero-rate bucket
        would answer stale-routed produces `overloaded:` instead of the
        `not_leader` redirect that re-resolves the client's routing."""
        if not self.config.slo_quotas:
            return
        total = 0
        led = 0
        for t in self.manager.get_topics():
            for a in t.assignments:
                if a.state == "retired":
                    continue
                total += 1
                if a.leader == self.broker_id:
                    led += 1
        if total <= 0:
            return
        self.slo.admission.set_leadership_share(max(led, 1) / total)

    def _follower_lease_duty(self) -> None:
        """Metadata-leader duty: keep the follower-read lease table
        equal to {standby: current epoch}. Proposed (not written) — the
        grant is replicated state, so every broker fences reads against
        the SAME table, and the OP_SET_CONTROLLER apply clearing it is
        what revokes a deposed generation everywhere at once."""
        if not self.config.follower_reads:
            return
        if self.runner.node.role != LEADER:
            return
        epoch = self.manager.current_epoch()
        desired = {int(b): epoch for b in self.manager.current_standbys()}
        if desired == self.manager.current_follower_leases():
            return
        now = time.monotonic()
        if now - self._last_lease_grant < 1.0:
            return  # debounce: a failed propose retries next tick
        self._last_lease_grant = now
        if self.propose_cmd({
            "op": OP_SET_FOLLOWER_LEASES,
            "epoch": epoch,
            "leases": {str(b): int(e) for b, e in desired.items()},
        }, retries=1):
            self.recorder.record(
                "follower_lease", epoch=epoch,
                brokers=sorted(desired),
            )

    def _reconfig_duty(self) -> None:
        """Controller: drive every open split-handoff window to
        cutover, plus every broker's local follower-plane slot prune.
        The cutover gate is the parent's SETTLED floor crossing the
        split-begin watermark — every write acked before the split
        began is then replicated to the full standby set, so the
        final routing flip survives a controller death the next
        instant. A floor that cannot advance (quorum loss mid-handoff)
        falls back to the split_handoff_timeout_s LOCAL deadline so
        the window is always bounded; the deadline clock restarts on
        failover, which delays — never loses — the cutover, because
        the handoff window itself is replicated metadata the promoted
        controller sees on its first duty pass."""
        if self.follower_plane is not None:
            # Satellite of the same transition: serve state for slots
            # the topic table no longer maps must not dangle (and a
            # reused slot must not inherit a dead partition's floor).
            self.follower_plane.prune_slots(self.manager.mapped_slots())
        dp = self._local_engine()
        if dp is None:
            self._handoff_seen.clear()
            return
        open_ho = self.manager.current_handoffs()
        for k in list(self._handoff_seen):
            if k not in open_ho:
                del self._handoff_seen[k]
        now = time.monotonic()
        for (topic, pid), ho in open_ho.items():
            first = self._handoff_seen.setdefault((topic, pid), now)
            slot = self.manager.slot_of(group_key(topic, pid))
            if slot is None:
                continue
            timed_out = (now - first
                         >= self.config.split_handoff_timeout_s)
            if dp.settled_end(slot) < int(ho["watermark"]) \
                    and not timed_out:
                continue
            csp = NULL_SPAN
            if self.spans is not None:
                tid = derive_trace_id(f"cutover/{topic}/{pid}",
                                      int(ho["watermark"]))
                if sampled(tid, self.config.trace_sample_n):
                    csp = self.spans.span(
                        "meta.cutover", TraceContext(tid, 0),
                        {"topic": topic, "partition": pid})
            ok = self.propose_cmd({
                "op": OP_SPLIT_CUTOVER, "topic": topic,
                "partition": pid, "watermark": int(ho["watermark"]),
            }, retries=1)
            csp.end(ok=ok)
            if ok and timed_out:
                log.warning(
                    "broker %d: split cutover for %s/%d forced by "
                    "handoff timeout (settled %d < watermark %d)",
                    self.broker_id, topic, pid,
                    dp.settled_end(slot), int(ho["watermark"]),
                )

    def _autosplit_duty(self) -> None:
        """Controller broker: the SLO→topology closed loop. When the
        SloController's tick history arms a split (`split_auto` with a
        sustained produce-SLO breach), propose an online split of the
        HOTTEST splittable partition — ranked by committed log-end
        growth between duty passes, a host-side observation off the
        local device plane, no device work. When the history arms a
        merge instead (deep comfortable/idle hysteresis), reabsorb one
        split child. Runs only where the device plane lives — the same
        broker whose engine-side signals feed the shed machine — so
        exactly one broker arbitrates; the apply's deterministic no-op
        guards make a raced duplicate proposal harmless regardless."""
        if not self.config.split_auto:
            return
        dp = self._local_engine()
        if dp is None:
            self._autosplit_prev_ends = {}
            return
        # Snapshot log ends EVERY pass (the ranking must already have a
        # baseline the moment the evidence arms), and rank while at it.
        prev = self._autosplit_prev_ends
        cur: dict = {}
        hottest = None
        hottest_delta = -1
        for t in self.manager.get_topics():
            for a in t.assignments:
                if a.state != "active":
                    continue
                key = group_key(t.name, a.partition_id)
                slot = self.manager.slot_of(key)
                if slot is None:
                    continue
                cur[key] = end = dp.log_end(slot)
                if a.range_hi - a.range_lo < 2:
                    continue  # too narrow to split: never a candidate
                delta = end - prev.get(key, end)
                if delta > hottest_delta:
                    hottest_delta, hottest = delta, key
        self._autosplit_prev_ends = cur
        if self.manager.current_handoffs():
            return  # one reconfiguration window in flight at a time
        if self.slo.split_wanted():
            if hottest is None or self.manager.spare_slot_count() <= 0:
                return  # stay armed; feasibility may return
            topic, pid = hottest
            if self.propose_cmd({
                "op": OP_SPLIT_PARTITION, "topic": topic,
                "partition": pid, "watermark": int(cur[hottest]),
            }, retries=1):
                self.slo.note_reconfig()
                log.warning(
                    "broker %d: auto-split %s/%d (SLO breach; log-end "
                    "delta %d this duty pass)",
                    self.broker_id, topic, pid, hottest_delta,
                )
        elif self.slo.merge_wanted():
            cands = self.manager.merge_candidates()
            if not cands:
                self.slo.note_reconfig()  # nothing to merge: disarm
                return
            topic, parent, child = cands[0]
            if self.propose_cmd({
                "op": OP_MERGE_PARTITIONS, "topic": topic,
                "parent": parent, "child": child,
            }, retries=1):
                self.slo.note_reconfig()
                log.info("broker %d: auto-merge %s/%d+%d (idle "
                         "hysteresis)", self.broker_id, topic, parent,
                         child)

    def _metadata_leader_duty(self) -> None:
        node = self.runner.node
        if node.role != LEADER:
            return
        now = time.monotonic()
        if now - self._last_membership_poll < self.config.membership_poll_s:
            return
        self._last_membership_poll = now
        with self.runner.lock:
            alive = node.alive_peers(self._alive_horizon)
        if not alive:
            return
        cmd = self.manager.plan_assignment(alive)
        if cmd is not None:
            self.runner.propose(cmd)
        # Controller failover: promote a live standby when the controller
        # is dead; prune dead standbys otherwise.
        ctrl_cmd = self.manager.plan_controller(alive)
        if ctrl_cmd is not None:
            self.runner.propose(ctrl_cmd)

    def _group_duty(self) -> None:
        """Metadata leader: evict group members whose heartbeat session
        lapsed (liveness-flap → rebalance). Eviction is an ordinary
        OP_GROUP_LEAVE — the apply bumps the generation and reassigns,
        and the member's next heartbeat/commit sees `unknown_member` /
        `fenced_generation` and rejoins. A fresh leader grants every
        member a full grace window (volatile ledger; see GroupLiveness)."""
        node = self.runner.node
        if node.role != LEADER:
            # Both ledgers are only meaningful while CONTINUOUSLY
            # leading: stamps recorded during a previous tenure are
            # stale the moment leadership is lost (members beat the new
            # leader; emptiness may have been interrupted). Clearing
            # them here is what makes re-election grant a full grace
            # window — otherwise a re-elected leader's first tick could
            # mass-evict healthy members (last beats predate the
            # interregnum) or reap a group after seconds of REAL
            # emptiness (an empty-since stamp from the previous
            # tenure).
            self._group_empty_since.clear()
            self._group_liveness.clear()
            return
        with self.manager.lock:
            table = self.manager.groups
            evict = self._group_liveness.plan_evictions(
                table, self.config.group_session_timeout_s
            )
        evict_cmds = []
        for group, member in evict:
            log.info("broker %d: evicting group member %s/%s "
                     "(session lapsed)", self.broker_id, group, member)
            self._group_liveness.forget(group, member)
            evict_cmds.append(
                {"op": OP_GROUP_LEAVE, "group": group, "member": member,
                 "reason": "evicted"}
            )
        if len(evict_cmds) == 1:
            self.propose_cmd(evict_cmds[0], retries=1)
        elif evict_cmds:
            # A session-timeout storm evicts as ONE wave: the batch
            # apply defers each group's rebalance to the wave end, so a
            # mass eviction costs one generation bump per group, not
            # one per member (the same collapse the join path gets from
            # _submit_meta).
            self.propose_cmd(
                {"op": OP_BATCH, "cmds": evict_cmds}, retries=1
            )
        # Empty-group retention: a group with zero members keeps its
        # generation and shared offsets (transient total-churn must not
        # reset the group's identity — see GroupTable.leave); only
        # after group_retention_s of CONTINUOUS emptiness on this
        # leader is it reaped, releasing the offset slot for recycling.
        # The apply re-checks emptiness, so a rejoin racing the reap
        # proposal wins.
        now = time.monotonic()
        empty = set(self.manager.empty_groups())
        for g in list(self._group_empty_since):
            if g not in empty:
                del self._group_empty_since[g]
        for g in empty:
            t0 = self._group_empty_since.setdefault(g, now)
            if now - t0 > self.config.group_retention_s:
                self._group_empty_since.pop(g, None)
                self.propose_cmd(
                    {"op": OP_GROUP_DELETE, "group": g}, retries=1
                )

    def _slot_clean_duty(self) -> None:
        """Controller: drain the recycled-consumer-slot reset queue. A
        released slot's device offset row still holds the OLD consumer's
        positions; this duty zeroes it through ordinary replicated
        offset rounds (partition by partition, only where the shadow is
        nonzero) and then proposes OP_CONSUMER_SLOT_CLEAN, returning the
        slot to the allocatable pool. Work is bounded per tick (one
        slot), and a partition that cannot commit right now (quorum
        lost) just retries next tick — the slot stays dirty, never
        allocatable, so correctness is never racing the reset."""
        dp = self._local_engine()
        if dp is None:
            return
        dirty = self.manager.dirty_slots()
        if not dirty:
            return
        cslot = dirty[0]
        futs = []
        for slot in range(dp.cfg.partitions):
            if dp.read_offset(slot, cslot) == 0:
                continue
            if dp.quorum_lost(slot):
                return  # retry the whole slot next tick
            futs.append(dp.submit_offsets(slot, [(cslot, 0)]))
        try:
            for fut in futs:
                fut.result(timeout=self.config.rpc_timeout_s)
        except Exception as e:
            log.info("broker %d: slot-clean reset for cslot %d deferred: "
                     "%s: %s", self.broker_id, cslot, type(e).__name__, e)
            return  # offsets stay dirty; retried next tick
        self.propose_cmd(
            {"op": OP_CONSUMER_SLOT_CLEAN, "slot": cslot}, retries=1
        )

    def _abdicate_duty(self) -> None:
        """Controller whose data plane broke PERMANENTLY (lockstep mesh
        break: an engine-worker process died mid-call) while the broker
        itself is alive: the metadata leader's dead-controller planning
        never fires, so the controller must surrender. Propose promotion
        of a live standby under a bumped epoch; the fence duty then
        releases the broken plane and the promoted standby's takeover
        duty boots from its copy of the committed-round stream — zero
        settled-append loss, the same guarantee as controller death
        (every settled round was acked by the full standby set)."""
        dp = self.dataplane
        if dp is None or not self._owns_dataplane:
            return
        reason = dp.broken_reason
        if reason is None:
            return
        if self.manager.current_controller() != self.broker_id:
            return  # already deposed; fence duty will release the plane
        cmd = self.manager.plan_abdication()
        if cmd is None:
            log.warning(
                "broker %d: data plane broken (%s) but no live standby "
                "to abdicate to; plane stays down", self.broker_id, reason,
            )
            return
        log.warning(
            "broker %d: data plane broken (%s); abdicating controllership "
            "to broker %d (epoch %d)",
            self.broker_id, reason, cmd["controller"], cmd["epoch"],
        )
        self.recorder.record("abdicate", reason=str(reason)[:200],
                             successor=cmd["controller"],
                             epoch=cmd["epoch"])
        self.propose_cmd(cmd)
        # The apply flips current_controller; the fence duty (same duty
        # pass) releases the broken plane.

    def _fence_duty(self) -> None:
        """Deposed controller: release the device program and revert to a
        plain frontend (its round store keeps its copy of the stream; the
        new controller re-admits it to the standby set via catch-up)."""
        if self.dataplane is None or not self._owns_dataplane:
            return
        if self.manager.current_controller() == self.broker_id:
            return
        log.info(
            "broker %d: deposed as controller (epoch %d now at broker %s); "
            "releasing the device program",
            self.broker_id, self.manager.current_epoch(),
            self.manager.current_controller(),
        )
        self.recorder.record(
            "deposed", epoch=self.manager.current_epoch(),
            successor=self.manager.current_controller(),
        )
        dp = self.dataplane
        self.dataplane = None
        self.manager.detach_dataplane()
        if self._replicator is not None:
            self._replicator.stop()
            self._replicator = None
        dp.stop()  # fails queued/in-flight rounds → producers re-route
        self._owns_dataplane = False

    def _metadata_current(self) -> bool:
        """Freshness gate for acting on metadata that names THIS broker
        controller: True once the locally applied metadata provably
        includes every entry the cluster committed before this process
        (re)booted. As metadata leader, winning the election proves the
        log is complete (Raft §5.4.1) and the election no-op barrier
        drives commit to the log end — require it applied. As follower,
        require application up to the highest commit the current leader
        advertised (`max_commit_seen`, volatile per process lifetime —
        recovered state never satisfies it by itself). Until contact
        with the current metadata quorum, recovered controllership is
        treated as a CLAIM, not a fact."""
        node = self.runner.node
        with self.runner.lock:
            if node.role == LEADER:
                return node.last_applied >= node.last_index()
            return (node.leader_hint is not None
                    and node.max_commit_seen > 0
                    and node.last_applied >= node.max_commit_seen)

    def _apply_committed(self, index: int, cmd: dict) -> None:
        """Metadata apply hook (RaftNode.apply_fn): delegates to the
        manager, then records whether this process has WITNESSED a live
        transition into its own controllership. Judged by state change
        rather than op shape so OP_BATCH wrapping and future op forms
        stay covered; gated on the apply index so entries replayed out
        of the restored log (index <= _recovered_raft_end) never count
        as a live promotion."""
        prev = self.manager.current_controller()
        self.manager.apply(index, cmd)
        if (not self._promoted_live
                and index > self._recovered_raft_end
                and prev != self.broker_id
                and self.manager.current_controller() == self.broker_id):
            self._promoted_live = True

    def _takeover_duty(self) -> None:
        """Promoted standby (and genesis/restarted controller): boot the
        device program from the local copy of the committed-round
        stream. Every settled round was acked by every standby-set
        member before its producer saw success, so no committed entry
        is lost across the handover. Gated on metadata freshness: a
        restarted broker's recovered metadata may name it controller in
        an epoch the cluster has already left (see __init__)."""
        if self._store_quarantined:
            in_set = self.broker_id in self.manager.current_standbys()
            if not in_set:
                self._quarantine_left_set = True
            elif self._quarantine_left_set:
                # Out-then-in: the controller pruned this broker after
                # the quarantine (repl acks refused until then) and
                # re-admitted it through the full catch-up stream — set
                # membership is proposed only after the whole store
                # prefix (plus buffered live rounds) transferred, so the
                # reopened store is whole again. Cleared HERE — while
                # still a standby — because the promotion that might
                # follow removes the promoted broker from the standby
                # list in the same apply. Membership WITHOUT the
                # out-transition is stale pre-death metadata and proves
                # nothing (a promoted stale member served an emptied
                # history as truth in the proc disk-fault drills).
                self._store_quarantined = False
                self._quarantine_left_set = False
        if self.dataplane is not None:
            return
        if self.manager.current_controller() != self.broker_id:
            # Not (or no longer) the controller: any FUTURE promotion
            # starts with the full boot-failure grace — without this
            # reset, a broker that once abdicated over boot failures
            # would re-abdicate on its first hiccup when re-promoted.
            self._boot_failures = 0
            return
        if self._round_store is None:
            return
        if not self._metadata_current():
            return  # recovered claim unconfirmed; retry next duty tick
        if self._store_quarantined:
            # The local stream was quarantined at boot (disk damage
            # beyond repair) and the store reopened EMPTY: booting a
            # plane from it would serve an empty history as truth —
            # acked loss by construction. Hand controllership to a
            # standby holding the real stream; this broker rejoins as a
            # standby and the flag clears once catch-up re-admits it
            # (the check at the top of this duty).
            cmd = self.manager.plan_abdication()
            if cmd is not None:
                log.warning(
                    "broker %d: refusing to boot a plane from a "
                    "quarantined store; abdicating to broker %d",
                    self.broker_id, cmd["controller"],
                )
                self.propose_cmd(cmd)
                return
            # No live standby to hand to: the quarantined copy was the
            # best anyone has — boot empty rather than stall the whole
            # cluster forever (genesis-equivalent restart).
            log.warning(
                "broker %d: quarantined store and no standby to "
                "abdicate to; booting empty", self.broker_id,
            )
            self._store_quarantined = False
        if not self._promoted_live and self._recovered_raft_end > 0:
            # This controllership claim was RECOVERED from disk, not
            # won while running (genesis boots restore nothing; every
            # live promotion flips _promoted_live in _apply_committed).
            # A restarted controller's stream may have silently lost
            # its acked tail — a torn tail is repaired by DROPPING it,
            # a legitimate crash artifact — so booting from it can
            # serve a shorter history than what producers were acked
            # against (the proc split-chaos drill caught this as an
            # offset regression: a commit acked 12 ms before SIGKILL
            # vanished across the restart). Every settled round was
            # acked by every standby-set member first, so hand
            # controllership to one and let the whole copy win; this
            # broker rejoins through catch-up like any abdication.
            cmd = self.manager.plan_abdication()
            if cmd is not None:
                log.warning(
                    "broker %d: restarted into a recovered controller "
                    "claim; abdicating to broker %d rather than boot "
                    "from a possibly torn local stream",
                    self.broker_id, cmd["controller"],
                )
                self.propose_cmd(cmd)
                return
            # No live standby to hand to (or the recovered standby set
            # is empty): the local copy is the best anyone has — adopt
            # the claim and boot, same fallback as quarantine.
            self._promoted_live = True
        self._boot_dataplane()

    def _controller_duty(self) -> None:
        dp = self._local_engine()
        if dp is None:
            return
        # Touch the device ONLY when there is work: the log-ends fetch
        # holds the device lock for a full host-device RTT, and a duty
        # loop fetching every tick starves the dispatch pipeline (~4
        # rounds/s measured behind a tunnel vs ~20+ without). Elections
        # have a cheap host-side pre-check; repairs run on their own
        # cadence.
        # Repair scans defer while the plane is busy (the fetch would
        # drain the dispatch pipeline; see DataPlane.busy) — but never
        # beyond 30 s, so lagging replicas still catch up under
        # sustained load. Busy is judged with hysteresis: under
        # intermittent traffic (e.g. a consume drain whose offset
        # commits ride spaced quorum rounds) a POINT sample of busy()
        # flickers False between rounds, and a repair scan fired into
        # that gap stalls the next ~1 s of dispatches behind its fetch
        # (measured: the r4 consume drain spent more time in duty-loop
        # log_ends fetches than in its own commit rounds). The plane
        # must have looked idle for 10 consecutive duty ticks before an
        # optional scan touches the device.
        now = time.monotonic()
        if dp.busy():
            self._engine_busy_at = now
        since_repair = now - self._last_repair_scan
        idle_for = now - self._engine_busy_at
        due_repairs = since_repair >= max(1.0, self._duty_interval_s * 10)
        if (due_repairs and since_repair < 30.0
                and idle_for < max(0.5, self._duty_interval_s * 10)):
            due_repairs = False
        if not self.manager.needs_elections() and not due_repairs:
            return
        # One [R, P] log-ends snapshot per pass, shared by both planners
        # (elections don't move log ends, so the snapshot stays valid).
        log_ends = dp.log_ends()
        cands, drafts = self.manager.plan_elections(log_ends)
        if drafts:
            winners = dp.elect(cands) if cands else {}
            won = [drafts[slot] for slot, w in winners.items() if w]
            # Vote-less drafts (device-term-skew heals): the device
            # already granted the term; only the advert is missing.
            won += [d for slot, d in drafts.items() if slot not in cands]
            # ONE replicated command advertises every winner of the
            # batched ballot (chunked to bound the entry size): a
            # thousand-partition election wave — bootstrap or failover —
            # must not pay a thousand per-proposal broadcast costs.
            for i in range(0, len(won), 512):
                chunk = won[i : i + 512]
                if len(chunk) == 1:
                    self.propose_cmd(chunk[0], retries=1)
                else:
                    self.propose_cmd({"op": OP_BATCH, "cmds": chunk},
                                     retries=1)
        # Periodic lag repair: catch up alive followers that trail their
        # leader (covers post-election catch-up and slots that came alive
        # while the partition was leaderless).
        if due_repairs:
            self._last_repair_scan = time.monotonic()
            for (src, dst), slots in self.manager.plan_repairs(log_ends).items():
                dp.resync(src, dst, slots)

    def _standby_duty(self) -> None:
        """Controller: maintain the standby set — drop suspects stalling
        the settle path, admit new members after catch-up (the join
        protocol of broker/replication.py)."""
        rep = self._replicator
        if rep is None or self._local_engine() is None:
            return
        rep.sync_members()
        suspects = rep.take_suspects()
        if suspects:
            members = [
                s for s in self.manager.current_standbys()
                if s not in suspects
            ]
            self.propose_cmd(
                {"op": OP_SET_STANDBYS,
                 "epoch": self.manager.current_epoch(),
                 "standbys": members},
                retries=1,
            )
        if self._catchup_thread is not None:
            if self._catchup_thread.is_alive():
                return
            self._catchup_thread = None
        if self._round_store is None:
            return
        cand = self.manager.plan_standby_add(self.config.standby_count)
        if cand is None or rep.is_joining(cand):
            return
        t = threading.Thread(
            target=self._run_catchup, args=(cand,), daemon=True,
            name=f"catchup-{self.broker_id}-to-{cand}",
        )
        self._catchup_thread = t
        t.start()

    def _run_catchup(self, cand: int) -> None:
        """Stream the full store prefix to `cand`, then propose its
        standby-set membership (live rounds buffer behind the scan and
        flow to the joiner meanwhile, so the stream is gap-free)."""
        rep = self._replicator
        epoch = self.manager.current_epoch()
        joined = False
        try:
            rep.catchup(cand, self._round_store)
            members = sorted(set(self.manager.current_standbys()) | {cand})
            # The joiner holds the full prefix AND keeps receiving live
            # rounds (it stays in the joining set), so a lagging
            # membership commit is retried by RE-PROPOSING — never by
            # re-streaming the store (under produce load the metadata
            # apply can trail by seconds, and a from-scratch catch-up
            # retry loop would amplify exactly the load that caused the
            # lag).
            for _ in range(5):
                if not self.propose_cmd(
                    {"op": OP_SET_STANDBYS, "epoch": epoch,
                     "standbys": members},
                    retries=3,
                ):
                    continue
                deadline = time.monotonic() + max(
                    10.0, self.config.rpc_timeout_s
                )
                while time.monotonic() < deadline:
                    if cand in self.manager.current_standbys():
                        joined = True
                        break
                    if self.manager.current_epoch() != epoch:
                        return  # deposed mid-join; fence duty cleans up
                    time.sleep(0.02)
                if joined:
                    break
            if joined:
                self.recorder.record("standby_joined", standby=cand,
                                     epoch=epoch)
                log.info("broker %d: standby %d caught up and joined the "
                         "standby set", self.broker_id, cand)
            else:
                log.warning("broker %d: catchup(%d) membership proposal "
                            "failed; will retry", self.broker_id, cand)
                with self._errors_lock:
                    self.duty_errors.append(
                        f"catchup({cand}): membership proposal failed; "
                        f"will retry")
                    del self.duty_errors[:-20]
        except Exception as e:
            log.warning("broker %d: catchup(%d) failed: %s: %s",
                        self.broker_id, cand, type(e).__name__, e)
            with self._errors_lock:
                self.duty_errors.append(
                    f"catchup({cand}): {type(e).__name__}: {e}"
                )
                del self.duty_errors[:-20]
        finally:
            # Success AND failure both leave the joining state: a joined
            # member now acks via the set; a failed join is fully unwound
            # (sync_members prunes the sender) so the next duty pass
            # retries the catch-up from scratch — replay is later-record-
            # wins, so re-streamed duplicates are harmless.
            rep.finish_join(cand)
