"""Follower read plane: serve consumes from the bytes replication
already paid for.

Every consume used to be served by the partition leader, so at high
subscriber counts the leader's host path is the throughput ceiling no
matter how fast the engine gets. But the bytes are already elsewhere:
full-copy standbys hold every committed round's REC_APPEND rows, and
striped standbys hold k-reconstructible stripes of them. This module is
the read-side counterpart of the replication planes — it turns those
replicated bytes into a servable, floor-fenced row cache on every
standby.

Safety contract (the whole point — fan-out is worthless if a follower
can hand out a row the leader would not):

- **Serve strictly below the replicated settled floor.** Full-copy
  frames piggyback `[[slot, settled_end, gaps], ...]` stamped by the
  leader's `DataPlane.settle_floors` (one pass under the plane lock, so
  a floor is never newer than the gap map it ships with); striped
  frames already carry the encoder's contiguous-settle gsn watermark in
  their header. Anything at-or-above the local floor is REFUSED (the
  caller maps refusal to the retryable `not_settled_here:` error and
  the client falls back to the leader) — never answered empty, never
  answered stale.
- **Settled gaps replicate with the floor.** A round that committed on
  the device but failed replication is a gap on the leader; the floor
  stamp carries the leader's gap map verbatim (full copy), and in
  striped mode a base jump between sequentially-decoded groups can only
  be the span of tombstoned (never-settled) groups — both are served as
  the same `([], skip_to)` skip the leader serves, never as rows.
- **Generation-fenced.** All state is keyed to the controller epoch:
  ingest from an older epoch is dropped, a newer epoch resets the plane
  (floors, caches, decode cursor), and the owning server re-checks its
  metadata-plane lease (manager.follower_lease) against the SAME epoch
  per answered read — a deposed standby's cache can never serve past a
  newer generation's trim/gap map.

Striped mode decodes on read ("stripe-reconstruct-on-read"): the plane
keeps its OWN stripe of each recent group; on a cache miss below the
gsn floor it pulls sibling stripes via the existing `stripe.fetch`
paging (one forward-only cursor per peer, owned by the server closure),
runs ONE `rs_reconstruct` per group, and feeds the decoded rows into
the shared page cache — N consumer cursors are then served from that
one decode. The cache is bounded by `follower_page_cache_bytes`
(plane-wide, oldest-page eviction); an evicted page re-decodes on the
next miss in striped mode and refuses to the leader in full-copy mode.

Row framing is the engine's own: each cached page is the REC_APPEND
payload verbatim — packed `slot_bytes`-wide rows whose first 4 bytes
are the little-endian payload length (length-0 rows are alignment
padding and are walked over), byte-identical to what the leader's
mirror serves.
"""

from __future__ import annotations

from collections import OrderedDict, deque
from typing import Callable, Optional

from ripplemq_tpu.core.config import ROW_HEADER as _ROW_HDR
from ripplemq_tpu.obs.lockwitness import make_lock
from ripplemq_tpu.storage.segment import REC_APPEND
from ripplemq_tpu.stripes.codec import (
    RS_K,
    StripeFrame,
    StripeShortError,
    reconstruct_group,
)
from ripplemq_tpu.utils.logs import get_logger

log = get_logger("follower")

# Striped-mode working-set bounds. The local-stripe window and sibling
# stash are COUNT-bounded (raw frames are small next to decoded pages);
# the decoded page cache is the byte-bounded one.
_LOCAL_FRAME_CAP = 4096
_SIBLING_FRAME_CAP = 4096
# Per-read decode work bounds: one consume may pull the decode cursor
# forward at most this many groups / fetch pages, so a cold follower
# amortizes its catch-up across reads instead of stalling one.
_MAX_DECODE_PER_READ = 64
_MAX_FETCH_ROUNDS_PER_READ = 8
_MAX_GAPS_PER_SLOT = 128


class _SlotRun:
    """One slot's newest contiguous run of replicated settled rows —
    the same window discipline as the host plane's `_SlotMirror`: a
    publish landing past the end restarts the run (correctness lives in
    the refusal upstream), eviction raises the start."""

    __slots__ = ("start", "end", "frames", "nbytes", "slot_bytes")

    def __init__(self, slot_bytes: int) -> None:
        self.start = 0
        self.end = 0
        # (seq, base, end, rows): seq is the plane-wide publish counter
        # the eviction FIFO names frames by.
        self.frames: list[tuple[int, int, int, bytes]] = []
        self.nbytes = 0
        self.slot_bytes = slot_bytes

    def publish(self, seq: int, base: int, rows: bytes) -> int:
        """Append a page; returns the net byte delta (a gap restart can
        free more than it adds). The caller checks `frames[-1][0] ==
        seq` to learn whether the page was actually retained."""
        nrows = len(rows) // self.slot_bytes
        if nrows <= 0:
            return 0
        delta = 0
        if not self.frames or base != self.end:
            if base < self.start:
                return 0  # stale duplicate below the window
            delta -= self.nbytes
            self.frames = []
            self.nbytes = 0
            self.start = base
        self.frames.append((seq, base, base + nrows, rows))
        self.end = base + nrows
        self.nbytes += len(rows)
        return delta + len(rows)

    def evict_if_head(self, seq: int) -> int:
        """Drop the oldest page iff it is the one `seq` names (the FIFO
        entry may be stale after a gap restart); returns bytes freed."""
        if self.frames and self.frames[0][0] == seq:
            _, _, _, rows = self.frames.pop(0)
            self.nbytes -= len(rows)
            self.start = self.frames[0][1] if self.frames else self.end
            return len(rows)
        return 0

    def read(self, offset: int, max_msgs: Optional[int], floor: int
             ) -> Optional[tuple[list[bytes], int]]:
        """(messages, next_offset) STRICTLY below `floor`, or None when
        the window cannot answer (evicted below, or not yet ingested up
        to the offset) — None means refuse, never "empty"."""
        if offset < self.start:
            return None
        lim = min(self.end, floor)
        if offset >= lim:
            return None  # rows not ingested yet: the leader has them
        SB = self.slot_bytes
        cap = SB - _ROW_HDR
        msgs: list[bytes] = []
        pos = offset
        for _, base, end, rows in self.frames:
            if end <= pos:
                continue
            if base >= lim:
                break
            i = pos - base
            stop = min(end, lim) - base
            while i < stop:
                off = i * SB
                n = min(int.from_bytes(rows[off : off + 4], "little"), cap)
                if n > 0:
                    msgs.append(
                        bytes(rows[off + _ROW_HDR : off + _ROW_HDR + n])
                    )
                    if max_msgs is not None and len(msgs) >= max_msgs:
                        return msgs, base + i + 1
                i += 1
            pos = min(end, lim)
        # All-padding walks still advance (the caller's answer moves the
        # cursor): pos > offset by construction here.
        return msgs, pos


class FollowerReadPlane:
    """Per-standby settled-row cache + floor/fence state (module doc)."""

    def __init__(
        self,
        slot_bytes: int,
        cache_bytes: int,
        fetch_fn: Optional[Callable[[int], list[StripeFrame]]] = None,
        decode_kw: Optional[dict] = None,
    ) -> None:
        self._slot_bytes = int(slot_bytes)
        self._cache_bytes = int(cache_bytes)
        # Sibling-stripe pager (server closure over stripe.fetch): one
        # call = one page round across the live holders, returning
        # parsed frames with gsn >= the argument. None = full-copy-only
        # deployment (no reconstruct-on-read).
        self._fetch_fn = fetch_fn
        self._decode_kw = dict(decode_kw or ())
        self._lock = make_lock("FollowerReadPlane._lock")
        # Serializes striped decode so N concurrent cursors missing on
        # the same cold page pay ONE reconstruct. Always acquired
        # BEFORE _lock, never while holding it.
        self._decode_lock = make_lock("FollowerReadPlane._decode_lock")
        self._epoch = -1
        self._mode: Optional[str] = None  # "full" | "striped"
        # Serve state: slot -> exclusive contiguous-settle end, and the
        # replicated/derived settled-gap spans below it.
        self._floor: dict[int, int] = {}
        self._gaps: dict[int, list[list[int]]] = {}
        # Decoded-page cache: slot -> contiguous run, plane-wide byte
        # budget, FIFO eviction by publish order.
        self._runs: dict[int, _SlotRun] = {}
        self._order: deque = deque()  # (seq, slot) in publish order
        self._seq = 0
        self._nbytes = 0
        # Striped decode state: own-stripe window, sibling stash, dense
        # gsn decode cursor (-1 = not attached yet), gsn floor.
        self._local: "OrderedDict[int, StripeFrame]" = OrderedDict()
        self._sibling: dict[int, dict[int, StripeFrame]] = {}
        self._sibling_n = 0
        self._decode_next = -1
        self._floor_gsn = 0
        # Counters (persist across generations; stats()).
        self._served = 0
        self._refused = 0
        self._rows = 0
        self._hits = 0
        self._misses = 0
        self._evictions = 0
        self._decoded = 0
        self._fetch_rounds = 0
        # Safety witness (never incremented by correct code): answers
        # that reached the serve boundary ABOVE the settled floor and
        # were refused there. The chaos harness treats any nonzero as
        # a first-class violation — see audit_answer.
        self._past_floor = 0

    # --------------------------------------------------------- fencing

    def _adopt_epoch_locked(self, epoch: int) -> bool:
        """False = stale-generation ingest, drop it. A newer epoch
        resets every floor/cache/cursor: the new generation's trim and
        gap map owe nothing to the old one's bytes."""
        if epoch < self._epoch:
            return False
        if epoch > self._epoch:
            self._epoch = epoch
            self._floor = {}
            self._gaps = {}
            self._runs = {}
            self._order.clear()
            self._nbytes = 0
            self._local = OrderedDict()
            self._sibling = {}
            self._sibling_n = 0
            self._decode_next = -1
            self._floor_gsn = 0
        return True

    def note_epoch(self, epoch: int) -> None:
        """Observe the metadata plane's controller epoch (the server
        calls this when it sees a handover): fences the plane even
        before the new generation's first frame arrives."""
        with self._lock:
            self._adopt_epoch_locked(int(epoch))

    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    # ---------------------------------------------------------- ingest

    def ingest_rounds(self, epoch: int, records, floors) -> None:
        """Full-copy path: one `repl.rounds` frame's committed records
        plus the leader's piggybacked floor stamp (replication.py). The
        stream is sseq-gated upstream, so pages arrive in commit order
        and per-slot runs stay contiguous except at genuine leader
        gaps — which the floor stamp names."""
        with self._lock:
            if not self._adopt_epoch_locked(int(epoch)):
                return
            self._mode = "full"
            for rec in records:
                if int(rec[0]) != REC_APPEND:
                    continue
                self._publish_locked(int(rec[1]), int(rec[2]), bytes(rec[3]))
            for ent in floors or ():
                slot, end = int(ent[0]), int(ent[1])
                if end > self._floor.get(slot, -1):
                    self._floor[slot] = end
                # The leader's gap list is authoritative and already
                # pruned below its trim: replace, don't merge.
                self._gaps[slot] = [
                    [int(a), int(b)] for a, b in ent[2]
                ][-_MAX_GAPS_PER_SLOT:]
            self._evict_locked()

    def ingest_stripe(self, epoch: int, frame: StripeFrame) -> None:
        """Striped path: stash THIS standby's stripe of a group and
        advance the gsn floor from the frame header. Decode is lazy
        (reconstruct-on-read); catch-up frames are skipped — a joining
        standby serves from its attach point forward."""
        with self._lock:
            if not self._adopt_epoch_locked(int(epoch)):
                return
            self._mode = "striped"
            if frame.catchup:
                return
            g = int(frame.gsn)
            if self._decode_next < 0:
                self._decode_next = g
            if g >= self._decode_next and g not in self._local:
                self._local[g] = frame
                while len(self._local) > _LOCAL_FRAME_CAP:
                    self._local.popitem(last=False)
            if int(frame.settled_floor) > self._floor_gsn:
                self._floor_gsn = int(frame.settled_floor)

    def _publish_locked(self, slot: int, base: int, rows: bytes) -> None:
        run = self._runs.get(slot)
        if run is None:
            run = self._runs[slot] = _SlotRun(self._slot_bytes)
        self._seq += 1
        seq = self._seq
        self._nbytes += run.publish(seq, base, rows)
        if run.frames and run.frames[-1][0] == seq:
            self._order.append((seq, slot))

    def _evict_locked(self) -> None:
        while self._nbytes > self._cache_bytes and self._order:
            seq, slot = self._order.popleft()
            run = self._runs.get(slot)
            if run is None:
                continue
            freed = run.evict_if_head(seq)
            if freed:
                self._nbytes -= freed
                self._evictions += 1

    # ----------------------------------------------------------- serve

    def read(self, slot: int, offset: int, max_msgs: Optional[int]
             ) -> Optional[tuple[list[bytes], int]]:
        """Answer a consume from replicated bytes, strictly below the
        slot's settled floor. Returns (messages, next_offset) — empty
        messages always advance (a replicated-gap skip or padding walk)
        — or None: REFUSE, the caller sends `not_settled_here:` and the
        client falls back to the leader."""
        slot, offset = int(slot), int(offset)
        res = self._read_cached(slot, offset, max_msgs)
        if res is None and self._mode == "striped":
            self._advance_striped(slot, offset)
            res = self._read_cached(slot, offset, max_msgs)
        with self._lock:
            if res is None:
                self._refused += 1
            else:
                self._served += 1
                self._rows += len(res[0])
        return res

    def _read_cached(self, slot: int, offset: int, max_msgs: Optional[int]
                     ) -> Optional[tuple[list[bytes], int]]:
        with self._lock:
            floor = self._floor.get(slot)
            if floor is None or offset >= floor:
                return None
            for s, e in self._gaps.get(slot, ()):
                if s <= offset < e:
                    # Same skip answer the leader's gap clamp serves.
                    return [], min(int(e), floor)
            run = self._runs.get(slot)
            if run is None:
                self._misses += 1
                return None
            got = run.read(offset, max_msgs, floor)
            if got is None:
                self._misses += 1
            else:
                self._hits += 1
            return got

    def audit_answer(self, slot: int, offset: int, next_offset: int
                     ) -> bool:
        """Last-line safety witness at the answer boundary: True iff
        the window ABOUT TO BE SERVED lies at-or-below the slot's
        settled floor. Every follower answer passes through here
        regardless of which path produced it (own cache, gap skip, or
        the worker-plane mirror) — a False means some serving path's
        own fence failed; the caller must refuse, and the miss is
        counted (`answers_past_floor` in stats()) so the chaos harness
        can hold the run to follower-answers-≤-floor as a first-class
        violation rather than trusting the fences it is testing."""
        with self._lock:
            floor = self._floor.get(int(slot))
            ok = (floor is not None and int(offset) < floor
                  and int(next_offset) <= floor)
            if not ok:
                self._past_floor += 1
            return ok

    def validate_window(self, slot: int, offset: int, next_offset: int
                        ) -> bool:
        """True iff [offset, next_offset) lies strictly below the
        slot's floor and outside every known gap — the fence applied to
        answers served from the shared worker-plane mirror instead of
        this plane's own cache."""
        with self._lock:
            floor = self._floor.get(int(slot))
            if floor is None or offset >= floor or next_offset > floor:
                return False
            for s, e in self._gaps.get(int(slot), ()):
                if s < next_offset and offset < e:
                    return False
            return True

    # --------------------------------------- striped reconstruct-on-read

    def _advance_striped(self, slot: int, offset: int) -> None:
        """Pull the dense gsn decode cursor toward the gsn floor until
        the (slot, offset) miss is covered or the per-read work bound
        runs out. The decode lock serializes concurrent missers, so N
        cold cursors share one reconstruct per group."""
        if self._fetch_fn is None:
            return
        with self._decode_lock:
            fetch_rounds = 0
            for _ in range(_MAX_DECODE_PER_READ):
                with self._lock:
                    epoch = self._epoch
                    if offset < self._floor.get(slot, 0):
                        return  # covered: the cached read will serve
                    g = self._decode_next
                    if g < 0 or g > self._floor_gsn:
                        return
                    frames: dict[int, StripeFrame] = dict(
                        self._sibling.get(g, ())
                    )
                    mine = self._local.get(g)
                    if mine is not None:
                        frames[mine.idx] = mine
                if any(f.tombstone for f in frames.values()):
                    # Never settled: producers saw a refusal. Skip the
                    # group; the NEXT decoded group's base jump records
                    # the span as a served gap (sound because the
                    # cursor is dense — every earlier gsn was decoded
                    # or tombstoned, so the jump can only be
                    # never-settled rows).
                    self._finish_group(g, epoch, None)
                    continue
                while (len(frames) < RS_K
                       and fetch_rounds < _MAX_FETCH_ROUNDS_PER_READ):
                    fetch_rounds += 1
                    try:
                        got = self._fetch_fn(g)
                    except Exception as e:
                        log.debug("sibling fetch failed: %s", e)
                        return
                    if not got:
                        break
                    with self._lock:
                        if self._epoch != epoch:
                            return
                        self._fetch_rounds += 1
                        self._stash_siblings_locked(got)
                        frames = dict(self._sibling.get(g, ()))
                        mine = self._local.get(g)
                        if mine is not None:
                            frames[mine.idx] = mine
                if any(f.tombstone for f in frames.values()):
                    self._finish_group(g, epoch, None)
                    continue
                if len(frames) < RS_K:
                    return  # cannot prove the group either way: refuse
                try:
                    records = reconstruct_group(frames, **self._decode_kw)
                except (StripeShortError, ValueError) as e:
                    log.debug("group %d reconstruct failed: %s", g, e)
                    return
                self._finish_group(g, epoch, records)

    def _finish_group(self, g: int, epoch: int, records) -> None:
        """Advance the dense cursor past group `g` — applying its
        decoded records (None = tombstone skip) — unless a newer
        generation reset the plane meanwhile."""
        with self._lock:
            if self._epoch != epoch or self._decode_next != g:
                return
            if records is not None:
                self._apply_group_locked(records)
                self._decoded += 1
            self._decode_next = g + 1
            self._local.pop(g, None)
            dropped = self._sibling.pop(g, None)
            if dropped:
                self._sibling_n -= len(dropped)
            self._evict_locked()

    def _stash_siblings_locked(self, frames) -> None:
        for f in frames:
            g = int(f.gsn)
            # gsn restarts at 0 per controller generation: a fetched
            # frame from another epoch must never satisfy this one's
            # group (same-gsn collision would decode garbage — the
            # blob CRC would catch it, but refusing early is free).
            if int(f.epoch) != self._epoch or f.catchup:
                continue
            if g < self._decode_next:
                continue
            by_idx = self._sibling.setdefault(g, {})
            if f.idx not in by_idx:
                by_idx[f.idx] = f
                self._sibling_n += 1
        while self._sibling_n > _SIBLING_FRAME_CAP and self._sibling:
            # Shed the FARTHEST groups first: the near ones are what
            # the dense cursor needs next.
            g = max(self._sibling)
            self._sibling_n -= len(self._sibling.pop(g))

    def _apply_group_locked(self, records) -> None:
        """Feed one decoded group's REC_APPEND pages into the cache and
        advance per-slot floors. A base jump past the current floor is
        the span of tombstoned groups (see _advance_striped) and is
        recorded as a served gap."""
        for rtype, slot, base, payload in records:
            if int(rtype) != REC_APPEND:
                continue
            slot, base = int(slot), int(base)
            nrows = len(payload) // self._slot_bytes
            if nrows <= 0:
                continue
            cur = self._floor.get(slot)
            if cur is None:
                cur = base  # first coverage this epoch: serve from here
            elif base < cur:
                continue  # duplicate/old replay below the floor
            elif base > cur:
                gaps = self._gaps.setdefault(slot, [])
                gaps.append([cur, base])
                if len(gaps) > _MAX_GAPS_PER_SLOT:
                    del gaps[: len(gaps) - _MAX_GAPS_PER_SLOT]
            self._publish_locked(slot, base, bytes(payload))
            self._floor[slot] = base + nrows

    def prune_slots(self, valid) -> int:
        """Drop serve state for engine slots the metadata plane no
        longer maps (a topic table replace that deleted or renumbered
        a partition): a dangling floor/gap/run entry would otherwise
        survive until the next controller handover resets the whole
        plane — and a slot REUSED by a later topic table would inherit
        the dead partition's floor as its own. Called from the broker's
        duty loop with the manager's current slot set; returns how many
        slots were pruned. Stale `_order` FIFO entries for pruned runs
        are harmless — eviction already skips missing runs."""
        valid = {int(s) for s in valid}
        with self._lock:
            stale = (set(self._floor) | set(self._gaps)
                     | set(self._runs)) - valid
            for s in stale:
                self._floor.pop(s, None)
                self._gaps.pop(s, None)
                run = self._runs.pop(s, None)
                if run is not None:
                    self._nbytes -= run.nbytes
            return len(stale)

    # ----------------------------------------------------------- stats

    def floors(self) -> dict[int, int]:
        with self._lock:
            return dict(self._floor)

    def stats(self) -> dict:
        with self._lock:
            lag = 0
            for slot, f in self._floor.items():
                run = self._runs.get(slot)
                if run is not None and run.end > f:
                    lag = max(lag, run.end - f)
            hits, misses = self._hits, self._misses
            total = hits + misses
            return {
                "epoch": self._epoch,
                "mode": self._mode,
                "slots": len(self._floor),
                "floor_lag_rows": int(lag),
                "reads_served": self._served,
                "reads_refused": self._refused,
                "rows_served": self._rows,
                "answers_past_floor": self._past_floor,
                "cache": {
                    "bytes": int(self._nbytes),
                    "budget_bytes": int(self._cache_bytes),
                    "hits": hits,
                    "misses": misses,
                    "hit_rate": (hits / total) if total else None,
                    "evictions": self._evictions,
                },
                "striped": {
                    "decoded_groups": self._decoded,
                    "fetch_rounds": self._fetch_rounds,
                    "floor_gsn": self._floor_gsn,
                    "decode_next": self._decode_next,
                },
            }
