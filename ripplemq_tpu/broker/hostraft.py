"""Metadata-plane Raft: a deterministic, message-driven host implementation.

Fills the role JRaft plays for the reference's cluster metadata group
(reference: mq-broker/src/main/java/metadata/raft/TopicsRaftServer.java —
group "topics_cluster": election, replicated topic table, liveness). The
data plane does NOT go through this: partition replication rides the
device mesh (ripplemq_tpu.core / .parallel). Metadata is low-rate (leader
changes, membership, assignment rewrites), so a host Raft is the right
tool (SURVEY.md §7, layer 3).

Design: `RaftNode` is a pure-ish state machine — time arrives as `tick()`
calls, network input as `handle()` (RPCs in) and `on_reply()` (responses
in), and every method returns the list of outbound `(dst, message)`
pairs to send. No threads, no sockets, no clocks inside. This makes the
whole consensus layer deterministically testable: a test pumps messages
in any order, drops or delays any subset, and asserts on state — the
fault-injection capability the reference entirely lacked (SURVEY.md §4).

`RaftRunner` binds a node to real time and a Transport for production.

Implements: elections (randomized-but-seeded timeouts), log replication
with conflict backtracking, quorum commit, leader liveness tracking
(alive_peers — the reference's CliService.getAlivePeers equivalent,
TopicsRaftServer.java:162-164), log compaction with snapshot install,
and persistence hooks for durable term/vote/log state.
"""

from __future__ import annotations

import random
import threading

from ripplemq_tpu.obs.lockwitness import make_rlock
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Optional

from ripplemq_tpu.utils.logs import get_logger
from ripplemq_tpu.wire.transport import RpcError, Transport

log = get_logger("hostraft")

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"

Outbound = tuple[int, dict]  # (destination node id, message)

VOTE = "raft.vote"
APPEND = "raft.append"
SNAPSHOT = "raft.snapshot"

RAFT_TYPES = (VOTE, APPEND, SNAPSHOT)


class RaftNode:
    """One metadata-Raft participant (see module docstring for the model).

    `apply_fn(index, cmd)` is called exactly once per committed entry, in
    index order, on every node (the TopicsStateMachine.onApply equivalent,
    reference TopicsStateMachine.java:64-78).

    `snapshot_fn()`/`restore_fn(state)` capture/install the applied state
    for log compaction — the hooks the reference never implemented on its
    state machines (SURVEY.md §5 checkpoint: recovery there is full
    replay; here the log stays bounded).
    """

    def __init__(
        self,
        node_id: int,
        peer_ids: list[int],
        apply_fn: Callable[[int, Any], None],
        *,
        election_ticks: tuple[int, int] = (10, 20),
        heartbeat_ticks: int = 3,
        seed: int = 0,
        snapshot_fn: Optional[Callable[[], Any]] = None,
        restore_fn: Optional[Callable[[Any], None]] = None,
        compact_threshold: int = 1024,
        persist_fn: Optional[Callable[[dict], None]] = None,
    ) -> None:
        self.id = node_id
        self.peers = [p for p in peer_ids if p != node_id]
        self.apply_fn = apply_fn
        self.snapshot_fn = snapshot_fn
        self.restore_fn = restore_fn
        self.compact_threshold = compact_threshold
        self.persist_fn = persist_fn

        self.role = FOLLOWER
        self.term = 0
        self.voted_for: Optional[int] = None
        self.leader_hint: Optional[int] = None

        # Log: entries[i] has global index first_index + i. Index 0 is the
        # empty-log sentinel (last_included starts at 0, term 0).
        self.entries: list[dict] = []       # each {"term": int, "cmd": Any}
        self.first_index = 1                # global index of entries[0]
        self.snap_last_index = 0            # last index covered by snapshot
        self.snap_last_term = 0
        self.snap_state: Any = None
        self.commit_index = 0
        self.last_applied = 0
        # Highest commit index the CURRENT cluster has advertised to us
        # this process lifetime (unclipped — a restarted node's log may
        # trail it). Volatile by design: `last_applied >= max_commit_seen
        # > 0` proves the locally applied metadata includes every entry
        # committed before (re)boot — the freshness gate a restarted
        # broker needs before trusting recovered metadata that names it
        # controller (see BrokerServer._metadata_current).
        self.max_commit_seen = 0

        # Leader state.
        self.next_index: dict[int, int] = {}
        self.match_index: dict[int, int] = {}
        self.last_ack_tick: dict[int, int] = {}

        self._rng = random.Random((seed << 16) ^ node_id)
        self._election_ticks = election_ticks
        self._heartbeat_ticks = heartbeat_ticks
        self._ticks = 0
        self._ticks_since_heard = 0
        self._election_deadline = self._new_deadline()
        self._votes: set[int] = set()

    # ------------------------------------------------------------------ util

    @property
    def quorum(self) -> int:
        return (len(self.peers) + 1) // 2 + 1

    def _new_deadline(self) -> int:
        lo, hi = self._election_ticks
        return self._rng.randint(lo, hi)

    def last_index(self) -> int:
        return self.first_index + len(self.entries) - 1 if self.entries else self.snap_last_index

    def _term_at(self, index: int) -> int:
        if index == self.snap_last_index:
            return self.snap_last_term
        i = index - self.first_index
        if 0 <= i < len(self.entries):
            return self.entries[i]["term"]
        return -1  # unknown (compacted away or beyond the log)

    def _entry(self, index: int) -> dict:
        return self.entries[index - self.first_index]

    def _persist(self) -> None:
        if self.persist_fn is not None:
            self.persist_fn(
                {
                    "term": self.term,
                    "voted_for": self.voted_for,
                    "entries": self.entries,
                    "first_index": self.first_index,
                    "snap_last_index": self.snap_last_index,
                    "snap_last_term": self.snap_last_term,
                    "snap_state": self.snap_state,
                }
            )

    def restore(self, saved: dict) -> None:
        """Reload persisted state (before any traffic)."""
        self.term = saved["term"]
        self.voted_for = saved["voted_for"]
        self.entries = list(saved["entries"])
        self.first_index = saved["first_index"]
        self.snap_last_index = saved["snap_last_index"]
        self.snap_last_term = saved["snap_last_term"]
        self.snap_state = saved.get("snap_state")
        if self.snap_state is not None and self.restore_fn is not None:
            self.restore_fn(self.snap_state)
        self.commit_index = self.snap_last_index
        self.last_applied = self.snap_last_index

    # ------------------------------------------------------------------ time

    def tick(self) -> list[Outbound]:
        """Advance logical time by one tick; returns messages to send."""
        self._ticks += 1
        if self.role == LEADER:
            if self._ticks % self._heartbeat_ticks == 0:
                return self._broadcast_appends()
            return []
        self._ticks_since_heard += 1
        if self._ticks_since_heard >= self._election_deadline:
            return self._start_election()
        return []

    def _start_election(self) -> list[Outbound]:
        self.role = CANDIDATE
        self.term += 1
        self.voted_for = self.id
        self.leader_hint = None
        self._votes = {self.id}
        self._ticks_since_heard = 0
        self._election_deadline = self._new_deadline()
        self._persist()
        if self._votes_reached():  # single-node cluster
            return self._become_leader()
        req = {
            "type": VOTE,
            "term": self.term,
            "cand": self.id,
            "last_log_index": self.last_index(),
            "last_log_term": self._term_at(self.last_index()),
        }
        return [(p, dict(req)) for p in self.peers]

    def _votes_reached(self) -> bool:
        return len(self._votes) >= self.quorum

    def _become_leader(self) -> list[Outbound]:
        log.info("node %d: metadata leader at term %d", self.id, self.term)
        self.role = LEADER
        self.leader_hint = self.id
        nxt = self.last_index() + 1
        self.next_index = {p: nxt for p in self.peers}
        self.match_index = {p: 0 for p in self.peers}
        self.last_ack_tick = {p: self._ticks for p in self.peers}
        # No-op barrier entry: commits everything from prior terms
        # (Raft §5.4.2 — a leader may only count replicas for entries of
        # its own term; the no-op makes progress immediate).
        self.entries.append({"term": self.term, "cmd": {"noop": True}})
        self._persist()
        self._advance_commit()  # quorum of 1: single-node commits instantly
        return self._broadcast_appends()

    # ------------------------------------------------------------- proposals

    def propose(self, cmd: Any) -> tuple[Optional[int], list[Outbound]]:
        """Leader: append `cmd`; returns (assigned index, messages).
        Non-leader: (None, []) — caller redirects to `leader_hint`."""
        if self.role != LEADER:
            return None, []
        self.entries.append({"term": self.term, "cmd": cmd})
        self._persist()
        index = self.last_index()
        self._advance_commit()  # commits instantly iff quorum == 1
        return index, self._broadcast_appends()

    # ------------------------------------------------------------- messaging

    def _append_for(self, peer: int) -> dict:
        nxt = self.next_index[peer]
        if nxt <= self.snap_last_index:
            # Peer is behind the compacted prefix → install snapshot.
            return {
                "type": SNAPSHOT,
                "term": self.term,
                "leader": self.id,
                "last_index": self.snap_last_index,
                "last_term": self.snap_last_term,
                "state": self.snap_state,
            }
        prev = nxt - 1
        entries = [self._entry(i) for i in range(nxt, self.last_index() + 1)]
        return {
            "type": APPEND,
            "term": self.term,
            "leader": self.id,
            "prev_index": prev,
            "prev_term": self._term_at(prev),
            "entries": entries,
            "commit": self.commit_index,
        }

    def _broadcast_appends(self) -> list[Outbound]:
        return [(p, self._append_for(p)) for p in self.peers]

    def _step_down(self, term: int, leader: Optional[int] = None) -> None:
        if self.role == LEADER:
            log.info("node %d: stepping down at term %d (leader now %s)",
                     self.id, term, leader)
        if term > self.term:
            self.term = term
            self.voted_for = None
            self._persist()
        self.role = FOLLOWER
        if leader is not None:
            self.leader_hint = leader
        self._ticks_since_heard = 0
        self._election_deadline = self._new_deadline()

    # RPC input ---------------------------------------------------------

    def handle(self, msg: dict) -> dict:
        t = msg["type"]
        if t == VOTE:
            return self._on_vote(msg)
        if t == APPEND:
            return self._on_append(msg)
        if t == SNAPSHOT:
            return self._on_snapshot(msg)
        raise ValueError(f"not a raft message: {t}")

    def _on_vote(self, msg: dict) -> dict:
        if msg["term"] > self.term:
            self._step_down(msg["term"])
        granted = False
        if msg["term"] == self.term and self.voted_for in (None, msg["cand"]):
            my_last, my_term = self.last_index(), self._term_at(self.last_index())
            up_to_date = msg["last_log_term"] > my_term or (
                msg["last_log_term"] == my_term
                and msg["last_log_index"] >= my_last
            )
            if up_to_date:
                granted = True
                self.voted_for = msg["cand"]
                self._ticks_since_heard = 0  # granting resets our timeout
                self._persist()
        return {"ok": True, "type": VOTE, "term": self.term, "granted": granted}

    def _on_append(self, msg: dict) -> dict:
        if msg["term"] < self.term:
            return {"ok": True, "type": APPEND, "term": self.term,
                    "success": False, "match_index": 0}
        if msg["term"] > self.term or self.role != FOLLOWER:
            self._step_down(msg["term"], msg["leader"])
        self.leader_hint = msg["leader"]
        self._ticks_since_heard = 0
        # UNCLIPPED leader commit: the freshness horizon a restarted
        # node must apply up to before its metadata is current.
        self.max_commit_seen = max(self.max_commit_seen, int(msg["commit"]))

        prev = msg["prev_index"]
        # Reject on a gap or a conflicting prev entry; leader backtracks.
        # A prev below the snapshot cannot conflict (the compacted prefix
        # is committed, hence consistent) — the write loop below just
        # skips already-snapshotted entries.
        if prev > self.last_index() or (
            prev >= self.snap_last_index and self._term_at(prev) != msg["prev_term"]
        ):
            return {"ok": True, "type": APPEND, "term": self.term,
                    "success": False, "match_index": self.last_index()}

        new = msg["entries"]
        # Skip entries we already hold that fall inside the snapshot/log.
        write_at = prev + 1
        for e in new:
            if write_at <= self.snap_last_index:
                write_at += 1
                continue
            if write_at <= self.last_index():
                if self._term_at(write_at) != e["term"]:
                    # conflict: truncate from here
                    del self.entries[write_at - self.first_index :]
                    self.entries.append(dict(e))
            else:
                self.entries.append(dict(e))
            write_at += 1
        if new:
            self._persist()

        match = prev + len(new)
        if msg["commit"] > self.commit_index:
            self.commit_index = min(msg["commit"], self.last_index())
            self._apply_committed()
        return {"ok": True, "type": APPEND, "term": self.term,
                "success": True, "match_index": match}

    def _on_snapshot(self, msg: dict) -> dict:
        if msg["term"] < self.term:
            return {"ok": True, "type": SNAPSHOT, "term": self.term, "success": False}
        self._step_down(msg["term"], msg["leader"])
        self.leader_hint = msg["leader"]
        self._ticks_since_heard = 0
        # A snapshot covers only committed entries: its last_index is a
        # lower bound on the leader's commit (freshness horizon).
        self.max_commit_seen = max(self.max_commit_seen,
                                   int(msg["last_index"]))
        if msg["last_index"] <= self.commit_index:
            # Stale/reordered snapshot (we already committed past it):
            # installing would roll the state machine back and re-apply
            # committed entries. Ack our actual progress instead.
            return {"ok": True, "type": SNAPSHOT, "term": self.term,
                    "success": True, "match_index": self.commit_index}
        if msg["last_index"] > self.snap_last_index:
            self.snap_last_index = msg["last_index"]
            self.snap_last_term = msg["last_term"]
            self.snap_state = msg["state"]
            self.entries = []
            self.first_index = self.snap_last_index + 1
            self.commit_index = max(self.commit_index, self.snap_last_index)
            self.last_applied = self.snap_last_index
            if self.restore_fn is not None:
                self.restore_fn(msg["state"])
            self._persist()
        return {"ok": True, "type": SNAPSHOT, "term": self.term, "success": True,
                "match_index": self.snap_last_index}

    # Reply input -------------------------------------------------------

    def on_reply(self, src: int, req: dict, resp: dict) -> list[Outbound]:
        if not resp.get("ok"):
            return []
        if resp["term"] > self.term:
            self._step_down(resp["term"])
            return []
        rtype = req["type"]
        if rtype == VOTE and self.role == CANDIDATE and resp["term"] == self.term:
            if resp.get("granted"):
                self._votes.add(src)
                if self._votes_reached():
                    return self._become_leader()
            return []
        if rtype in (APPEND, SNAPSHOT) and self.role == LEADER:
            self.last_ack_tick[src] = self._ticks
            if rtype == SNAPSHOT:
                if resp.get("success"):
                    # max-guard: a reordered duplicate reply must not
                    # regress the peer's replication progress.
                    self.match_index[src] = max(
                        self.match_index.get(src, 0), resp["match_index"]
                    )
                    self.next_index[src] = self.match_index[src] + 1
                return []
            if resp.get("success"):
                self.match_index[src] = max(self.match_index.get(src, 0),
                                            resp["match_index"])
                self.next_index[src] = self.match_index[src] + 1
                old_commit = self.commit_index
                self._advance_commit()
                if self.commit_index > old_commit:
                    # Push the new commit index out immediately instead of
                    # waiting for the next heartbeat: one round shorter
                    # commit visibility on followers.
                    return self._broadcast_appends()
            else:
                # Conflict backtrack: jump to the follower's log end + 1
                # (capped below current next).
                hint = resp.get("match_index", 0)
                self.next_index[src] = max(
                    1, min(self.next_index[src] - 1, hint + 1)
                )
                return [(src, self._append_for(src))]
        return []

    def _advance_commit(self) -> None:
        for n in range(self.last_index(), self.commit_index, -1):
            if self._term_at(n) != self.term:
                break  # only current-term entries commit by counting (§5.4.2)
            acks = 1 + sum(1 for p in self.peers if self.match_index.get(p, 0) >= n)
            if acks >= self.quorum:
                self.commit_index = n
                self._apply_committed()
                break

    def _apply_committed(self) -> None:
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            cmd = self._entry(self.last_applied)["cmd"]
            if not (isinstance(cmd, dict) and cmd.get("noop")):
                self.apply_fn(self.last_applied, cmd)
        self._maybe_compact()

    def _maybe_compact(self) -> None:
        if self.snapshot_fn is None:
            return
        if self.last_applied - self.snap_last_index < self.compact_threshold:
            return
        keep_from = self.last_applied + 1
        self.snap_last_term = self._term_at(self.last_applied)
        self.snap_state = self.snapshot_fn()
        self.entries = self.entries[keep_from - self.first_index :]
        self.first_index = keep_from
        self.snap_last_index = keep_from - 1
        self._persist()

    # Introspection -----------------------------------------------------

    def alive_peers(self, horizon_ticks: int = 10) -> list[int]:
        """Leader's view of live membership: peers acked within the horizon
        (the CliService.getAlivePeers role, TopicsRaftServer.java:162-164).
        Non-leaders return [] — only the leader runs membership logic."""
        if self.role != LEADER:
            return []
        alive = [self.id]
        alive += [
            p
            for p in self.peers
            if self._ticks - self.last_ack_tick.get(p, -(10**9)) <= horizon_ticks
        ]
        return sorted(alive)


class RaftRunner:
    """Binds a RaftNode to wall-clock time and a Transport.

    A pump thread ticks the node every `tick_interval_s`; outbound
    messages fan out on a worker pool (never blocking the pump), replies
    re-enter the node under the node lock. The node itself stays
    single-threaded: every touch happens under `self.lock`.
    """

    def __init__(
        self,
        node: RaftNode,
        transport: Transport,
        addr_of: Callable[[int], str],
        tick_interval_s: float = 0.1,
        rpc_timeout_s: float = 1.0,
    ) -> None:
        self.node = node
        self.transport = transport
        self.addr_of = addr_of
        self.tick_interval_s = tick_interval_s
        self.rpc_timeout_s = rpc_timeout_s
        self.lock = make_rlock("RaftRunner.lock")
        self._stop = threading.Event()
        self._pool = ThreadPoolExecutor(
            max_workers=max(4, 2 * len(node.peers)), thread_name_prefix="raft-io"
        )
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"raft-pump-{node.id}")

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)
        self._pool.shutdown(wait=False)

    def handle_rpc(self, msg: dict) -> dict:
        """Plug into the broker's request dispatcher for raft.* types."""
        with self.lock:
            return self.node.handle(msg)

    def propose(self, cmd: Any) -> Optional[int]:
        with self.lock:
            index, out = self.node.propose(cmd)
        self._send_all(out)
        return index

    def _run(self) -> None:
        while not self._stop.wait(self.tick_interval_s):
            with self.lock:
                out = self.node.tick()
            self._send_all(out)

    def _send_all(self, out: list[Outbound]) -> None:
        for dst, msg in out:
            self._pool.submit(self._send_one, dst, msg)

    def _send_one(self, dst: int, msg: dict) -> None:
        try:
            resp = self.transport.call(
                self.addr_of(dst), msg, timeout=self.rpc_timeout_s
            )
        except RpcError:
            return  # unreachable peer: Raft's timeouts own recovery
        with self.lock:
            more = self.node.on_reply(dst, msg, resp)
        self._send_all(more)
