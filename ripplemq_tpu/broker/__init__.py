"""Broker host runtime.

The reference broker is a JVM process wrapping two tiers of JRaft plus an
RPC server (reference: mq-broker/src/main/java/broker/BrokerServer.java).
Here the broker host process owns:

- `hostraft` — the metadata-plane Raft (replicated topics/assignments
  table) between broker processes; low-rate, host-side by design
  (SURVEY.md §7 layer 3).
- `batcher` — coalesces produce/offset-commit requests into the
  (partition × entry) StepInput tensor of one device round.
- `driver` — the device-step loop thread stepping the replication engine.
- `manager` — PartitionManager equivalent: topic→program-slot mapping,
  leader bookkeeping, membership reconcile, assignment refresh.
- `server` — request dispatch for the client-facing surface (the
  reference's five processors, TopicsRaftServer.java:109-120).
"""

from ripplemq_tpu.broker.hostraft import RaftNode, RaftRunner
from ripplemq_tpu.broker.dataplane import (
    DataPlane,
    NotCommittedError,
    PartitionFullError,
)
from ripplemq_tpu.broker.manager import (
    ConsumerTableFullError,
    PartitionManager,
)
from ripplemq_tpu.broker.server import BrokerServer

__all__ = [
    "RaftNode",
    "RaftRunner",
    "DataPlane",
    "NotCommittedError",
    "PartitionFullError",
    "ConsumerTableFullError",
    "PartitionManager",
    "BrokerServer",
]
