"""Committed-round replication: controller → standby set, with fencing.

The reference tolerates the loss of ANY broker because every broker runs
its own JRaft groups with their own durable logs and elections move
leadership wherever replicas survive (reference:
mq-broker/src/main/java/metadata/raft/PartitionRaftServer.java:83-93).
In the TPU design the whole partition data plane is ONE device program
driven by one controller broker, so that fault-tolerance property must be
rebuilt around the program: this module chain-replicates the controller's
committed-round record stream — the exact (rec_type, slot, base, payload)
frames the segment store persists (storage/segment.py REC_APPEND /
REC_OFFSETS) — to a *standby set* recorded in the replicated metadata
(PartitionManager: controller broker + controller epoch + standby list).

Protocol invariants:

- **Settle-after-ack.** The DataPlane resolver calls `replicate()`
  BEFORE local persistence and BEFORE settling producer futures;
  `replicate()` blocks until every broker in the current standby set
  acked the round (an empty set refuses once members ever existed — no
  durable copy, no ack). Hence every *settled* append exists on every
  standby — promoting any set member loses no acked entry (zero
  committed-entry loss) — and the local store only ever holds
  standby-acked records (recovery cannot resurrect a history the
  standbys never saw).
- **Epoch fencing.** Every `repl.rounds` RPC carries the controller
  epoch. A standby whose replicated metadata knows a newer epoch rejects
  with `stale_epoch`; the deposed controller's rounds then fail with
  FencedError (⊂ NotCommittedError), producers retry, and the metadata
  routes them to the new controller. The sender also fences locally the
  moment its own metadata shows another controller.
- **Ordered per-standby stream.** Each standby has one sender thread
  with a FIFO queue, so records arrive in commit order (duplicates are
  harmless: replay is later-record-wins per slot, dataplane.replay_records).
- **Catch-up join.** A broker enters the standby set only after
  receiving the controller's full store prefix: the sender is switched
  to *buffering* (live rounds hold in a side buffer), the store is
  scanned into catch-up batches on the primary queue, then the buffer
  flushes behind them. Any record the scan missed (including a torn
  concurrent tail) was persisted after buffering began, so its live copy
  is buffered — order and completeness both hold; only then is the
  OP_SET_STANDBYS membership proposed.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FuturesTimeoutError
from typing import Callable, Optional

from ripplemq_tpu.broker.dataplane import NotCommittedError
from ripplemq_tpu.obs.lockwitness import make_lock
from ripplemq_tpu.obs.spans import ctx_from_wire
from ripplemq_tpu.utils.logs import get_logger
from ripplemq_tpu.wire.transport import RpcError, Transport

log = get_logger("replication")


class FencedError(NotCommittedError):
    """This controller's epoch is stale: a newer controller exists."""


class ReplicationError(NotCommittedError):
    """A standby stream died under a round (sender stopped while its
    target was still a set member): the round MUST NOT settle — acking
    without the member's copy would break the zero-loss invariant."""


_CATCHUP_BATCH_RECORDS = 256
_CATCHUP_BATCH_BYTES = 1 << 20

# Sender group-commit caps: one repl.rounds RPC carries the sender's
# whole queued backlog up to these bounds (well under the 64 MB frame
# cap). Each queued round pays one sequential RPC otherwise, and under
# load the per-RPC latency — not bandwidth — becomes the replication
# stream's capacity (measured: the settle pipeline queuing behind
# ~10 rounds/s/sender while each RPC idled in standby scheduling).
_GROUP_COMMIT_BYTES = 8 << 20
_GROUP_COMMIT_ROUNDS = 128


class ReplicationTicket:
    """One round's in-flight replication: the per-member ack futures of a
    `RoundReplicator.begin()` plus the begin timestamp the ack-timeout
    counts from. Opaque to callers — pass it back to `wait()`."""

    __slots__ = ("records", "senders", "futs", "start")

    def __init__(self, records: list, senders: dict, futs: dict,
                 start: float) -> None:
        self.records = records
        self.senders = senders
        self.futs = futs
        self.start = start


class _Sender(threading.Thread):
    """Ordered record stream to one standby broker."""

    def __init__(self, rep: "RoundReplicator", broker_id: int) -> None:
        super().__init__(daemon=True, name=f"repl-sender-{broker_id}")
        self.broker_id = broker_id
        self._rep = rep
        # Witness-named mutex; the Condition ALIASES it (one lock, two
        # handles) — the static graph models the alias the same way.
        self._lock = make_lock("_Sender._lock")
        self._cond = threading.Condition(self._lock)
        # Entries are (records, fut, tctxs) — tctxs the wire-form trace
        # contexts of the round's sampled produces (None when untraced),
        # stamped onto the frame so standby apply spans join the trace.
        self._queue: list[tuple[list, Future, Optional[list]]] = []
        self._buffer: Optional[list] = None
        self._stopped = False
        self.unreachable = False  # consecutive send failures observed

    # -- enqueue (any thread) --

    def enqueue(self, records: list, tctxs: Optional[list] = None) -> Future:
        """Live round: behind the catch-up stream while buffering."""
        fut: Future = Future()
        with self._cond:
            if self._stopped:
                fut.set_exception(ReplicationError("sender stopped"))
                return fut
            if self._buffer is not None:
                self._buffer.append((records, fut, tctxs))
            else:
                self._queue.append((records, fut, tctxs))
                self._cond.notify()
        return fut

    def enqueue_catchup(self, records: list) -> Future:
        """Catch-up batch: primary queue, ahead of buffered live rounds."""
        fut: Future = Future()
        with self._cond:
            if self._stopped:
                fut.set_exception(ReplicationError("sender stopped"))
                return fut
            self._queue.append((records, fut, None))
            self._cond.notify()
        return fut

    def cancel(self, fut: Future) -> bool:
        """Remove a still-queued entry by its future (a timed-out read
        barrier must not leave its batch behind: during a partition,
        refused-and-retried reads would otherwise grow the queue without
        bound, and a healed standby would have to drain the stale
        backlog before any real round). Returns False if the entry
        already left the queue (in flight or done) — those resolve into
        an abandoned future, which is harmless."""
        with self._cond:
            for q in (self._queue, self._buffer if self._buffer is not None
                      else []):
                for i, entry in enumerate(q):
                    if entry[1] is fut:
                        del q[i]
                        return True
        return False

    def begin_buffer(self) -> None:
        with self._cond:
            if self._buffer is None:
                self._buffer = []

    def end_buffer(self) -> None:
        with self._cond:
            if self._buffer is not None:
                self._queue.extend(self._buffer)
                self._buffer = None
                self._cond.notify()

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            leftovers = self._queue + (self._buffer or [])
            self._queue = []
            self._buffer = None
            self._cond.notify()
        for entry in leftovers:
            if not entry[1].done():
                entry[1].set_exception(ReplicationError("sender stopped"))

    # -- send loop --

    def _take_group(self) -> Optional[list]:
        """Pop one bounded group-commit [(records, fut, tctxs), ...] off
        the queue (caller holds self._cond)."""
        if not self._queue:
            return None
        group = [self._queue.pop(0)]
        nbytes = sum(len(r[3]) for r in group[0][0])
        while (self._queue and len(group) < _GROUP_COMMIT_ROUNDS
               and nbytes < _GROUP_COMMIT_BYTES):
            recs = self._queue[0][0]
            nbytes += sum(len(r[3]) for r in recs)
            group.append(self._queue.pop(0))
        return group

    @staticmethod
    def _settle_group(group: list, result) -> None:
        for entry in group:
            f = entry[1]
            if not f.done():
                if isinstance(result, BaseException):
                    f.set_exception(result)
                else:
                    f.set_result(result)

    def _send_frame(self, group: list, epoch: int, sseq: int):
        """Fire one epoch-stamped, stream-sequenced repl.rounds frame;
        returns a Future of the response dict (pipelined when the
        transport supports call_async, an already-resolved future
        otherwise — the in-proc network is synchronous by design)."""
        records = [r for entry in group for r in entry[0]]
        req = {
            "type": "repl.rounds",
            "epoch": epoch,
            "sender": self._rep.sender_id,
            "sseq": sseq,
            "records": [[t, s, b, p] for t, s, b, p in records],
        }
        tctxs = [t for entry in group for t in (entry[2] or ())]
        if tctxs:
            # Trace contexts of the frame's sampled produces: the standby
            # records its repl.apply span under these (server
            # _handle_repl_rounds), closing the cross-process edge the
            # assembler's skew estimate keys on.
            req["tctx"] = tctxs
        if self._rep.floors_fn is not None and records:
            # Piggyback the per-slot settled floor (+ gap map) for the
            # slots this frame touches: the standby publishes it as its
            # follower-read horizon. Stamped at send time, so it is
            # conservative — it can only name rounds whose acks already
            # landed cluster-wide, never this frame's own rows.
            try:
                req["floors"] = self._rep.floors_fn(
                    sorted({r[1] for r in records})
                )
            except Exception:
                pass  # floor stamp is best-effort; the frame still ships
        call_async = getattr(self._rep.client, "call_async", None)
        if call_async is not None:
            return call_async(self._rep.addr_of(self.broker_id), req)
        fut: Future = Future()
        try:
            fut.set_result(self._rep.client.call(
                self._rep.addr_of(self.broker_id), req,
                timeout=self._rep.rpc_timeout_s,
            ))
        except Exception as e:
            fut.set_exception(e)
        return fut

    def run(self) -> None:
        """PIPELINED group-commit stream: up to `pipeline_depth`
        epoch-stamped frames in flight, each carrying a per-stream
        sequence number (`sseq`) the standby's stream gate applies in
        order (BrokerServer._handle_repl_rounds). This is what kills
        the PR 3 sender's head-of-line blocking: one slow ack used to
        cap the stream at one group per round trip — now later groups
        are already on the wire (and applied, in sseq order) while the
        oldest ack is outstanding; acks still release in order here.
        On ANY failure the whole in-flight window rewinds: un-acked
        groups requeue at the head in order and re-send under their
        ORIGINAL sseqs — a frame that did apply before the failure is
        re-applied harmlessly (duplicate records are later-record-wins
        at replay; the gate acks `sseq < expected` after re-applying)."""
        backoff = 0.05
        failures = 0
        next_sseq = 0
        # In-flight window entries: [group, sseq, rpc_fut, t_frame].
        inflight: list = []

        def fail_inflight(result) -> None:
            while inflight:
                self._settle_group(inflight.pop(0)[0], result)

        def rewind_inflight(reset_to=None) -> None:
            """Requeue every un-acked in-flight group (head, in order)
            for a re-send under its original sseq — or under the
            standby's advertised `expected` counter (`reset_to`, from a
            repl_seq_gap refusal): a RESTARTED standby's gate restarts
            at zero, and re-sending under the old numbering would gap
            forever. Renumbering is safe — frame content never depends
            on its sseq."""
            nonlocal next_sseq
            if not inflight:
                return
            next_sseq = (int(reset_to) if reset_to is not None
                         else inflight[0][1])
            with self._cond:
                self._queue[0:0] = [
                    pair for entry in inflight for pair in entry[0]
                ]
            inflight.clear()

        while True:
            depth = max(1, int(self._rep.pipeline_depth))
            with self._cond:
                while (not self._queue and not inflight
                       and not self._stopped):
                    self._cond.wait(timeout=0.2)
                if self._stopped:
                    break
                groups = []
                while len(inflight) + len(groups) < depth:
                    g = self._take_group()
                    if g is None:
                        break
                    groups.append(g)
            # -- fire new frames (top up the window) --
            fenced = False
            for group in groups:
                # Epoch is stamped ONCE per delivery attempt from the
                # ACTIVE view. It must never be re-read after a
                # deposition: a deposed sender re-stamping its stale
                # backlog with the NEW epoch would walk it straight
                # through the standby's fence (the seeded chaos soak
                # caught that as an acked produce the promoted
                # controller had never seen). The double-check closes
                # the check/stamp race.
                if fenced or not self._rep.active():
                    fenced = True
                    self._settle_group(
                        group,
                        FencedError("controller deposed (local metadata)"),
                    )
                    continue
                epoch = self._rep.epoch_fn()
                if not self._rep.active():
                    fenced = True
                    self._settle_group(
                        group,
                        FencedError("controller deposed (local metadata)"),
                    )
                    continue
                t_frame = (self._rep._clock()
                           if self._rep._h_frame_us is not None else 0.0)
                inflight.append(
                    [group, next_sseq,
                     self._send_frame(group, epoch, next_sseq), t_frame,
                     time.monotonic()]
                )
                next_sseq += 1
            if not inflight:
                continue
            # -- wait on the OLDEST in-flight frame --
            group, sseq, rpc_fut, t_frame, t_sent = inflight[0]
            try:
                resp = rpc_fut.result(timeout=0.1)
            except (TimeoutError, FuturesTimeoutError):
                if self._stopped:
                    fail_inflight(ReplicationError("sender stopped"))
                    return
                if not self._rep.active():
                    fail_inflight(
                        FencedError("controller deposed (local metadata)")
                    )
                    continue
                if time.monotonic() - t_sent > self._rep.rpc_timeout_s:
                    # call_async carries no transport deadline: a hung
                    # (connected but unresponsive) standby must hit the
                    # same rpc-timeout retry path the synchronous
                    # sender had — rewind and re-send; the duplicate
                    # delivery, if the first one eventually lands, is
                    # absorbed like any other (gate dup path).
                    failures += 1
                    if self._rep._c_retries is not None:
                        self._rep._c_retries.inc()
                    if failures >= 3:
                        self.unreachable = True
                    rewind_inflight()
                    time.sleep(min(0.5, backoff * failures))
                continue
            except RpcError:
                failures += 1
                if self._rep._c_retries is not None:
                    self._rep._c_retries.inc()
                if failures >= 3:
                    self.unreachable = True
                rewind_inflight()
                time.sleep(min(0.5, backoff * failures))
                continue
            if resp.get("ok"):
                inflight.pop(0)
                failures = 0
                self.unreachable = False
                records = [r for entry in group for r in entry[0]]
                # Group-commit telemetry: rounds per acked frame is the
                # batching factor the PR 3 sender bought; the frame RPC
                # time is the raw standby round trip the settle stage's
                # standby_ack_us overlaps away (and pipelining overlaps
                # across frames too).
                if self._rep._h_group is not None:
                    self._rep._h_group.observe_int(len(group))
                    self._rep._h_frame_us.observe(
                        self._rep._clock() - t_frame
                    )
                    self._rep._c_records.inc(len(records))
                    self._rep._c_frames.inc()
                    self._rep._c_bytes.inc(sum(len(r[3]) for r in records))
                log.debug("standby %d acked %d records (%d rounds, sseq "
                          "%d)", self.broker_id, len(records), len(group),
                          sseq)
                self._settle_group(group, True)
                continue
            if resp.get("error") == "stale_epoch":
                fail_inflight(FencedError("standby reports newer epoch"))
                continue
            if resp.get("error") == "store_quarantined":
                # The standby quarantined its store (reopened empty)
                # and is refusing acks under its stale pre-death
                # membership. Flag it suspect NOW — waiting out the
                # full ack timeout just stalls every round in the
                # window — so the duty loop prunes it from the set;
                # the ordinary standby-add then re-admits it through
                # the full catch-up stream, after which it acks again.
                with self._rep._lock:
                    self._rep._suspects.add(self.broker_id)
            # Transient standby-side refusal (active_controller until
            # its fence duty runs, a repl_seq_gap after wire loss):
            # rewind the window and retry in order.
            failures += 1
            reset = None
            if str(resp.get("error", "")).startswith("repl_seq_gap"):
                reset = resp.get("expected")
            rewind_inflight(reset)
            time.sleep(min(0.5, backoff * failures))
        # Stopped: nothing in flight may settle (stop() already failed
        # the queued backlog; in-flight rounds must fail the same way).
        fail_inflight(ReplicationError("sender stopped"))


class RoundReplicator:
    """Controller-side fan-out of the committed-round stream.

    `members_fn` returns the CURRENT replicated standby set (acks
    required); `epoch_fn` the current controller epoch; `active_fn`
    whether this broker still is the controller (local fencing).
    """

    def __init__(
        self,
        client: Transport,
        addr_of: Callable[[int], str],
        epoch_fn: Callable[[], int],
        members_fn: Callable[[], tuple],
        active_fn: Callable[[], bool],
        rpc_timeout_s: float = 3.0,
        ack_timeout_s: float = 5.0,
        metrics=None,
        sender_id: int = -1,
        pipeline_depth: int = 1,
        floors_fn: Optional[Callable[[list], list]] = None,
    ) -> None:
        self.client = client
        self.addr_of = addr_of
        self.epoch_fn = epoch_fn
        self.members_fn = members_fn
        self.active = active_fn
        self.rpc_timeout_s = rpc_timeout_s
        self.ack_timeout_s = ack_timeout_s
        # Settled-floor stamp (follower reads): called with the sorted
        # slot list of each outgoing frame, returns the per-slot
        # [[slot, floor, gaps], ...] the standby publishes as its local
        # serve horizon (DataPlane.settle_floors). None → frames carry
        # no floor and standbys never advance one off this stream —
        # the wire stays compatible in both directions.
        self.floors_fn = floors_fn
        # Stream identity + window for the pipelined sender (_Sender.run):
        # (sender_id, epoch) keys the standby's per-stream sequence gate,
        # pipeline_depth bounds the frames in flight per stream.
        self.sender_id = int(sender_id)
        self.pipeline_depth = max(1, int(pipeline_depth))
        # Sender-side group-commit telemetry (obs.Metrics, usually the
        # owning broker's registry). None or a disabled registry → the
        # handles stay None and the send loop skips the clock reads too.
        if metrics is not None and getattr(metrics, "enabled", True):
            self._h_group = metrics.histogram("repl.group_rounds")
            self._h_frame_us = metrics.histogram("repl.frame_us")
            self._c_records = metrics.counter("repl.records")
            self._c_frames = metrics.counter("repl.frames")
            # Replication payload bytes ACKED across all standby
            # streams — the numerator of the bench's
            # repl_bytes_per_acked_byte accounting (full-copy mode
            # counts every member's copy; the striped twin counts
            # stripe frame bytes under stripes.bytes).
            self._c_bytes = metrics.counter("repl.bytes")
            self._c_retries = metrics.counter("repl.send_retries")
            self._clock = metrics.clock
        else:
            self._h_group = self._h_frame_us = None
            self._c_records = self._c_frames = self._c_retries = None
            self._c_bytes = None
            self._clock = time.perf_counter
        # Causal-tracing hook (obs/spans.py): the owning broker sets
        # this to its SpanRing when trace sampling is configured; begin()
        # then records one repl.send span per (sampled produce, standby)
        # covering queue time + frame round trip — the sender-side half
        # of the replication edge whose standby half is repl.apply.
        self.spans = None
        self._lock = make_lock("RoundReplicator._lock")
        self._senders: dict[int, _Sender] = {}
        self._joining: set[int] = set()
        self._suspects: set[int] = set()
        # Latched once members_fn() was ever non-empty: from then on an
        # EMPTY set refuses to settle (see replicate) instead of acking
        # rounds with no durable copy. Genesis — before the first
        # standby joins — keeps the bootstrap behavior.
        self._had_members = False
        self._stopped = False

    # -- sender management --

    def _sender(self, bid: int) -> _Sender:
        with self._lock:
            if self._stopped:
                # A racing caller (the read barrier fires from arbitrary
                # RPC threads) must not resurrect sender threads after
                # stop() — they would never be stopped again and leak.
                raise ReplicationError("replicator stopped")
            s = self._senders.get(bid)
            if s is None:
                s = _Sender(self, bid)
                self._senders[bid] = s
                s.start()
            return s

    def sync_members(self) -> None:
        """Drop senders for brokers neither in the set nor joining."""
        members = set(self.members_fn())
        with self._lock:
            drop = [
                bid for bid in self._senders
                if bid not in members and bid not in self._joining
            ]
            dropped = [self._senders.pop(bid) for bid in drop]
        for s in dropped:
            s.stop()

    def is_joining(self, bid: int) -> bool:
        with self._lock:
            return bid in self._joining

    def take_suspects(self) -> set[int]:
        """Standbys that stalled a round past ack_timeout (the server's
        duty loop proposes their removal from the set)."""
        with self._lock:
            out = self._suspects
            self._suspects = set()
            return out

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            senders = list(self._senders.values())
            self._senders.clear()
        for s in senders:
            s.stop()

    # -- hot path (DataPlane resolver/settle threads) --

    def begin(self, records: list,
              tctxs: Optional[list] = None) -> "ReplicationTicket":
        """Enqueue one round's records on every current-set member's
        ordered stream WITHOUT waiting for acks. Returns the ticket
        `wait()` later blocks on — the two halves of `replicate()`, split
        so the DataPlane's pipelined settle can keep a window of rounds
        streaming to the standbys while the device advances (acks are
        then released strictly in round order by `wait`ing the tickets
        in order; see broker/dataplane.py settle pipeline). Raises
        FencedError if deposed, ReplicationError on the empty-set
        refusal — both BEFORE anything is enqueued. `tctxs` carries the
        wire-form trace contexts of the round's sampled produces (see
        obs/spans.py): stamped onto the outgoing frames and recorded as
        sender-side repl.send spans that end when the member acks."""
        if not self.active():
            raise FencedError("controller deposed (local metadata)")
        targets = set(self.members_fn())
        if targets:
            self._had_members = True
        elif self._had_members:
            # The set was non-empty once and is now EMPTY: settling would
            # ack a round with zero durable copies beyond this broker —
            # an assertion the next promotion instantly falsifies. The
            # seeded chaos soak caught this as an acked loss: a liveness
            # flap pruned the set to [] while a promotion was already in
            # flight, and the old controller settled rounds the promoted
            # plane had never seen ("round settled ... members now []").
            # Refusing is the graceful-degradation contract: producers
            # get a retryable refusal until a standby rejoins (or
            # until genesis-style no-failover deployments, which never
            # grow a member, keep the old behavior).
            raise ReplicationError(
                "standby set empty (failover armed): no durable copy to "
                "settle against"
            )
        with self._lock:
            targets |= self._joining
        senders = {bid: self._sender(bid) for bid in targets}
        futs = {bid: s.enqueue(records, tctxs)
                for bid, s in senders.items()}
        if tctxs and self.spans is not None:
            for raw in tctxs:
                ctx = ctx_from_wire(raw)
                if ctx is None:
                    continue
                for bid, fut in futs.items():
                    sp = self.spans.span("repl.send", ctx, {"standby": bid})
                    fut.add_done_callback(lambda _f, s=sp: s.end())
        return ReplicationTicket(records, senders, futs, time.monotonic())

    def replicate(self, records: list,
                  timeout_s: Optional[float] = None) -> None:
        """Block until every current-set member acked this round. Raises
        FencedError if deposed. A member removed from the set mid-wait is
        skipped; an unreachable member is flagged suspect (duty loop
        proposes removal) while the wait continues. `timeout_s` bounds
        the whole wait (a settled round MUST have every member's ack, so
        round settling passes None; the linearizable-read barrier passes
        a bound, since an unconfirmable read should refuse, not hang)."""
        self.wait(self.begin(records), timeout_s=timeout_s)

    def wait(self, ticket: "ReplicationTicket",
             timeout_s: Optional[float] = None) -> None:
        """Second half of replicate(): block until every member acked the
        ticket's round, with the full waiver/fence discipline (see
        replicate). The ack deadline counts from begin() — queue time on
        a stalled stream charges the suspect timer exactly as before."""
        records = ticket.records
        senders = ticket.senders
        futs = ticket.futs
        start = ticket.start
        acked: list[int] = []
        waived: list[int] = []
        for bid, fut in futs.items():
            suspected = False
            while True:
                if bid not in self.members_fn():
                    # Distinguish WHY the member left the set before
                    # waiving its ack. A same-epoch prune (suspect
                    # removal, committed through metadata raft) is safe:
                    # any future promotion plans from the pruned set. But
                    # an OP_SET_CONTROLLER apply removes the PROMOTED
                    # broker from the standby list while deposing us —
                    # settling without ITS ack hands an acked round to a
                    # controller that never stored it (the seeded chaos
                    # soak caught this as an acked-produce loss: probe
                    # acked 3 ms after the deposition applied, absent
                    # from the promoted plane's replay). Deposed ⇒ fence.
                    if not self.active():
                        raise FencedError(
                            "controller deposed (local metadata)"
                        )
                    waived.append(bid)
                    break  # joiner or same-epoch prune: no ack needed
                if (timeout_s is not None
                        and time.monotonic() - start > timeout_s):
                    # Withdraw every still-queued entry of this timed-out
                    # round before refusing (see _Sender.cancel).
                    for b, f in futs.items():
                        if not f.done():
                            senders[b].cancel(f)
                    raise ReplicationError(
                        f"standby {bid} unconfirmed after {timeout_s}s"
                    )
                try:
                    fut.result(timeout=0.05)
                    acked.append(bid)
                    break
                # concurrent.futures.TimeoutError is a distinct class from
                # the builtin before Python 3.11 — catching only the
                # builtin let ack-poll timeouts escape as round failures.
                except (TimeoutError, FuturesTimeoutError):
                    if not self.active():
                        raise FencedError("controller deposed (local metadata)")
                    if (
                        not suspected
                        and time.monotonic() - start > self.ack_timeout_s
                    ):
                        suspected = True
                        log.warning(
                            "standby %d not acking after %.1fs; flagged "
                            "suspect", bid, self.ack_timeout_s,
                        )
                        with self._lock:
                            self._suspects.add(bid)
                except FencedError:
                    raise
                except ReplicationError:
                    if bid in self.members_fn():
                        # Sender died (replicator stopping) while its
                        # target is still a member: without this member's
                        # ack the round may exist nowhere but here — fail
                        # it. (This is exactly the shutdown race: a
                        # partitioned controller being stopped must not
                        # settle its stranded in-flight rounds.)
                        # Withdraw the round's still-queued copies from
                        # the OTHER senders first (same as the timeout
                        # path): the caller records this round as a
                        # settled GAP — nacked, invisible to reads — and
                        # a copy still delivered to a standby store would
                        # needlessly resurrect it at the next promotion
                        # (harmless under later-record-wins replay, but a
                        # nack should suppress what it can).
                        for b, f in futs.items():
                            if not f.done():
                                senders[b].cancel(f)
                        raise
                    # Same deposition guard as the member-removed branch
                    # above: the fence duty STOPS the replicator in the
                    # same breath as the OP_SET_CONTROLLER apply that
                    # shrinks the member set — "sender stopped" plus
                    # "member left" here usually MEANS deposed, and a
                    # waiver would settle a round the promoted
                    # controller never stored (chaos-soak-caught acked
                    # loss, sibling of the branch above).
                    if not self.active():
                        raise FencedError(
                            "controller deposed (local metadata)"
                        ) from None
                    waived.append(bid)
                    break  # member left the set: ack no longer required

        if records:
            log.debug(
                "round settled: %d records; acked by %s, waived %s, "
                "members now %s",
                len(records), acked, waived, sorted(self.members_fn()),
            )

    # -- catch-up (controller duty worker thread) --

    def catchup(self, bid: int, store, timeout_s: float = 600.0) -> None:
        """Stream the full local store prefix to a joining broker; returns
        when the standby holds it. Caller proposes set membership after,
        then calls finish_join()."""
        s = self._sender(bid)
        with self._lock:
            self._joining.add(bid)
        s.begin_buffer()
        last_fut: Optional[Future] = None
        try:
            batch: list = []
            nbytes = 0
            for rec in store.scan():
                batch.append(rec)
                nbytes += len(rec[3])
                if (
                    len(batch) >= _CATCHUP_BATCH_RECORDS
                    or nbytes >= _CATCHUP_BATCH_BYTES
                ):
                    last_fut = s.enqueue_catchup(batch)
                    batch, nbytes = [], 0
            if batch or last_fut is None:
                last_fut = s.enqueue_catchup(batch)
        finally:
            s.end_buffer()
        last_fut.result(timeout=timeout_s)

    def finish_join(self, bid: int) -> None:
        with self._lock:
            self._joining.discard(bid)
