"""Shared-memory SPSC frame ring: the host-plane process boundary.

One ring carries length-prefixed, CRC-guarded frames ONE direction
between exactly two processes (the broker dispatcher and one host-plane
worker — parallel/hostplane.py runs a pair per worker). The design
target is the PROFILE.md host wall: payload bytes must cross the
process boundary ONCE, as the pre-packed frame the codec already
produced, with no pickling and no per-message re-encode (the
multiprocessing.Queue default pays a pickle + a pipe write + a pickle
per hop — measured at ~3x the bytes touched).

Layout (`multiprocessing.shared_memory.SharedMemory`):

  [0:4)    magic (u32) — attach-time sanity check
  [8:16)   capacity of the data area (u64)
  [16:24)  head (u64): consumer cursor, absolute monotone byte count
  [24:32)  tail (u64): producer cursor, absolute monotone byte count
  [64:64+capacity) data

Frames are `[u32 body_len][u32 crc32(body)][body]`, padded to 8-byte
alignment, always CONTIGUOUS in the data area: a frame that would
straddle the end is preceded by a WRAP marker (`body_len ==
0xFFFFFFFF`, written only when >= 4 bytes remain) and starts at
offset 0 of the next lap. Cursors are absolute, so `fill = tail -
head` needs no emptiness flag and `capacity - fill` is free space.

Torn-write contract: the producer writes header + body FIRST and
advances `tail` LAST — a producer crashing mid-frame leaves the frame
invisible (the consumer never reads past `tail`), which is the
worker-crash-mid-frame story the host plane's recovery tests pin. The
CRC additionally catches a publish of corrupt bytes (a torn tail
advance, stray writes): the consumer raises `TornFrameError` instead
of handing garbage to the codec.

Blocking is polled (two processes share no OS futex here): a short
spin, then an escalating sleep capped at 1 ms — the ring is a
throughput device, and under load the spin path is the only one taken.
"""

from __future__ import annotations

import struct
import time
import zlib
from multiprocessing import shared_memory
from typing import Optional

MAGIC = 0x52514D52  # "RQMR"
_HDR_BYTES = 64
_WRAP = 0xFFFFFFFF
_FRAME_HDR = 8
# Hard per-frame cap (matches the wire codec's defensive bound — a
# corrupt length must never drive a multi-GB copy).
MAX_FRAME = 64 * 1024 * 1024


class RingClosedError(Exception):
    """The ring was closed locally; no further push/pop is legal."""


class RingFullError(Exception):
    """push() timed out against a full ring (consumer stalled/dead)."""


class TornFrameError(Exception):
    """A published frame failed its CRC or carried an insane length —
    the peer crashed mid-publish or the mapping was corrupted. The ring
    is unusable from here (cursors can no longer be trusted)."""


def _sleep_backoff(spins: int) -> None:
    if spins < 64:
        return
    time.sleep(min(0.001, 0.00005 * (spins // 64)))


class ShmRing:
    """One direction of a dispatcher<->worker pair. Exactly one process
    calls push(), exactly one calls pop() — SPSC by contract (the host
    plane serializes each side onto a dedicated thread)."""

    def __init__(self, shm: shared_memory.SharedMemory, capacity: int,
                 owner: bool) -> None:
        self._shm = shm
        self._buf = shm.buf
        self._cap = capacity
        self._owner = owner
        self._closed = False

    # -- construction ------------------------------------------------------

    @classmethod
    def create(cls, capacity: int) -> "ShmRing":
        if capacity < (1 << 12):
            raise ValueError(f"ring capacity {capacity} below 4 KiB floor")
        shm = shared_memory.SharedMemory(create=True,
                                         size=_HDR_BYTES + capacity)
        struct.pack_into("<I", shm.buf, 0, MAGIC)
        struct.pack_into("<Q", shm.buf, 8, capacity)
        struct.pack_into("<QQ", shm.buf, 16, 0, 0)
        return cls(shm, capacity, owner=True)

    @classmethod
    def attach(cls, name: str) -> "ShmRing":
        # NB: no resource_tracker.unregister here. The spawned worker
        # SHARES the dispatcher's tracker process, so attach-side
        # registration lands in the same cache entry the create side
        # made — the dispatcher's unlink retires it exactly once. An
        # attach-side unregister (the commonly-cited 3.10 workaround)
        # would remove the dispatcher's registration out from under its
        # own unlink and spray KeyErrors from the tracker.
        shm = shared_memory.SharedMemory(name=name)
        magic, = struct.unpack_from("<I", shm.buf, 0)
        if magic != MAGIC:
            shm.close()
            raise ValueError(f"shm segment {name!r} is not a ShmRing")
        cap, = struct.unpack_from("<Q", shm.buf, 8)
        return cls(shm, int(cap), owner=False)

    @property
    def name(self) -> str:
        return self._shm.name

    @property
    def capacity(self) -> int:
        return self._cap

    # -- cursors -----------------------------------------------------------

    def _head(self) -> int:
        return struct.unpack_from("<Q", self._buf, 16)[0]

    def _tail(self) -> int:
        return struct.unpack_from("<Q", self._buf, 24)[0]

    def fill_fraction(self) -> float:
        """Occupancy in [0, 1] — the host plane's admin.stats gauge."""
        if self._closed:
            return 0.0
        return (self._tail() - self._head()) / self._cap

    # -- producer side -----------------------------------------------------

    def _reserve(self, n: int, timeout_s: Optional[float]
                 ) -> Optional[tuple[int, int, int]]:
        """Wait for `n` contiguous body bytes; returns (tail, idx,
        need) with any WRAP marker already written — `need` is the
        8-byte-aligned frame footprint the publish advances tail by —
        or None when `timeout_s == 0` and the ring is full (the
        fire-and-forget contract). Raises RingFullError on a positive
        timeout elapsing."""
        need = _FRAME_HDR + ((n + 7) & ~7)
        deadline = (time.monotonic() + timeout_s) if timeout_s else None
        spins = 0
        while True:
            tail = self._tail()
            head = self._head()
            idx = tail % self._cap
            room_to_end = self._cap - idx
            want = need if room_to_end >= need else room_to_end + need
            if self._cap - (tail - head) >= want:
                break
            if timeout_s == 0:
                return None
            if deadline is not None and time.monotonic() > deadline:
                raise RingFullError(
                    f"ring full for {timeout_s}s ({n}-byte frame)"
                )
            spins += 1
            _sleep_backoff(spins)
            if self._closed:
                raise RingClosedError("ring closed")
        if room_to_end < need:
            if room_to_end >= 4:
                struct.pack_into("<I", self._buf, _HDR_BYTES + idx, _WRAP)
            tail += room_to_end
            idx = 0
        return tail, idx, need

    def push(self, body, timeout_s: Optional[float] = 5.0) -> bool:
        """Publish one frame; False on timeout against a full ring when
        `timeout_s` is 0 (the non-blocking fire-and-forget mirror path),
        RingFullError on a positive timeout elapsing. One-part alias of
        push_parts — ONE publish sequence owns the torn-write
        contract."""
        return self.push_parts((body,), timeout_s=timeout_s)

    def push_parts(self, parts, timeout_s: Optional[float] = 5.0) -> bool:
        """Publish ONE frame whose body is the concatenation of `parts`
        (bytes-like), each copied into the ring exactly once — push()'s
        single-part case, and the scatter-gather path for bodies whose
        tail some other buffer already holds (the settled-mirror
        publish: a ~40-byte encoded header prefix + the row block,
        wire/codec.py encode_dict_with_blob). No bytes() copies: the
        slice assignment and the incremental crc32 both take any buffer
        — the body is touched exactly once each way (the module's
        design goal, priced per-message in PROFILE.md); byte parity of
        the split and whole forms is pinned in tests/test_shmring.py."""
        if self._closed:
            raise RingClosedError("ring closed")
        n = sum(len(p) for p in parts)
        if n == 0 or n > min(MAX_FRAME, self._cap // 2):
            raise ValueError(f"frame body of {n} bytes out of range")
        slot = self._reserve(n, timeout_s)
        if slot is None:
            return False
        tail, idx, need = slot
        base = _HDR_BYTES + idx
        pos = base + _FRAME_HDR
        crc = 0
        for p in parts:
            self._buf[pos : pos + len(p)] = p
            crc = zlib.crc32(p, crc)
            pos += len(p)
        struct.pack_into("<II", self._buf, base, n, crc & 0xFFFFFFFF)
        # Publish point: the 8-byte tail write is the ONLY thing that
        # makes the frame visible (torn-write contract, module doc).
        struct.pack_into("<Q", self._buf, 24, tail + need)
        return True

    # -- consumer side -----------------------------------------------------

    def pop(self, timeout_s: Optional[float] = None) -> Optional[bytearray]:
        """Next frame body (a fresh writable bytearray — safe to hand to
        np.frombuffer), or None on timeout. Raises TornFrameError on a
        CRC/length violation."""
        deadline = (time.monotonic() + timeout_s) if timeout_s else None
        spins = 0
        while True:
            if self._closed:
                raise RingClosedError("ring closed")
            head = self._head()
            if self._tail() != head:
                break
            if timeout_s == 0:
                return None
            if deadline is not None and time.monotonic() > deadline:
                return None
            spins += 1
            _sleep_backoff(spins)
        idx = head % self._cap
        room_to_end = self._cap - idx
        if room_to_end < _FRAME_HDR:
            struct.pack_into("<Q", self._buf, 16, head + room_to_end)
            return self.pop(timeout_s=timeout_s)
        base = _HDR_BYTES + idx
        n, crc = struct.unpack_from("<II", self._buf, base)
        if n == _WRAP:
            struct.pack_into("<Q", self._buf, 16, head + room_to_end)
            return self.pop(timeout_s=timeout_s)
        if n == 0 or n > min(MAX_FRAME, self._cap // 2) \
                or _FRAME_HDR + n > room_to_end:
            raise TornFrameError(
                f"frame length {n} insane at ring offset {idx}"
            )
        body = bytearray(self._buf[base + _FRAME_HDR : base + _FRAME_HDR + n])
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            raise TornFrameError(f"frame CRC mismatch at ring offset {idx}")
        struct.pack_into("<Q", self._buf, 16, head + _FRAME_HDR + ((n + 7) & ~7))
        return body

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        # Release the exported memoryview BEFORE closing the mapping
        # (BufferError otherwise) — nothing below touches _buf again.
        self._buf = None
        try:
            self._shm.close()
        except Exception:
            pass
        if self._owner:
            try:
                self._shm.unlink()
            except Exception:
                pass
