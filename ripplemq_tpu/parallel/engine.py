"""Compiled engine entry points: local (vmap) and SPMD (shard_map) modes.

The core steps in `ripplemq_tpu.core.step` are written once against the
axis name "replica". This module binds them two ways:

- **local**: `jax.vmap(..., axis_name="replica")` stacks all replicas on a
  leading axis of a single device's arrays. Used for single-chip
  deployments and deterministic tests — the replication round is then a
  pure function: same tensors in → same commit index out (SURVEY.md §4).

- **spmd**: `shard_map` over a (replica, part) `Mesh` — one device per
  replica × partition-shard; psums ride ICI/DCN. This is the multi-chip
  production path.

Both produce bit-identical semantics (asserted in tests/test_spmd.py).
"""

from __future__ import annotations

import functools
import warnings
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ripplemq_tpu.core.config import EngineConfig, stride_alias_hazard
from ripplemq_tpu.core.state import (
    FusedReplicaState,
    ReplicaState,
    StepInput,
    StepOutput,
    fuse_state,
    init_state,
    unfuse_state,
)
from ripplemq_tpu.core import step as core_step
from ripplemq_tpu.ops.append import append_rows, append_rows_active

try:  # jax>=0.6 exposes shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map


class LocalEngineFns(NamedTuple):
    init: Callable[[], ReplicaState]          # -> state with leading [R] axis
    step: Callable[..., tuple[ReplicaState, StepOutput]]
    step_many: Callable[..., tuple[ReplicaState, StepOutput]]  # chained rounds
    step_sparse: Callable[..., tuple[ReplicaState, StepOutput]]  # active-set
    step_many_sparse: Callable[..., tuple[ReplicaState, StepOutput]]
    vote: Callable[..., tuple[ReplicaState, jax.Array, jax.Array]]
    read: Callable[..., tuple[jax.Array, jax.Array, jax.Array]]
    read_many: Callable[..., tuple[jax.Array, jax.Array, jax.Array]]  # batched
    read_offset: Callable[..., jax.Array]
    resync: Callable[..., ReplicaState]
    init_from: Callable[[ReplicaState], ReplicaState]  # single-replica image -> [R] state


class SpmdEngineFns(NamedTuple):
    init: Callable[[], ReplicaState]
    step: Callable[..., tuple[ReplicaState, StepOutput]]
    step_many: Callable[..., tuple[ReplicaState, StepOutput]]
    step_sparse: Callable[..., tuple[ReplicaState, StepOutput]]
    step_many_sparse: Callable[..., tuple[ReplicaState, StepOutput]]
    vote: Callable[..., tuple[ReplicaState, jax.Array, jax.Array]]
    read: Callable[..., tuple[jax.Array, jax.Array, jax.Array]]
    read_many: Callable[..., tuple[jax.Array, jax.Array, jax.Array]]
    read_offset: Callable[..., jax.Array]
    resync: Callable[..., ReplicaState]
    init_from: Callable[[ReplicaState], ReplicaState]
    mesh: Mesh


# ---------------------------------------------------------------------------
# Resync (shared): copy one healthy replica's rows into a recovering replica.
# ---------------------------------------------------------------------------

def _resync(cfg: EngineConfig, state: ReplicaState, src: jax.Array,
            dst: jax.Array, part_mask: jax.Array) -> ReplicaState:
    """Overwrite replica `dst`'s state for masked partitions with replica
    `src`'s (the leader's) state. State here carries an explicit leading
    replica axis [R, ...]. This is the snapshot-install analogue: the
    reference inherits full log replay from JRaft and has no FSM snapshots
    (SURVEY.md §5 checkpoint); here recovery is one on-device copy.
    """
    R = cfg.replicas

    def copy_leaf(leaf):
        src_rows = leaf[src]                       # [P, ...]
        mask = part_mask.reshape((1, -1) + (1,) * (leaf.ndim - 2))
        is_dst = (jnp.arange(R) == dst).reshape((R,) + (1,) * (leaf.ndim - 1))
        return jnp.where(is_dst & mask, src_rows[None], leaf)

    return jax.tree.map(copy_leaf, state)


# ---------------------------------------------------------------------------
# Local (single device, replicas vmapped)
# ---------------------------------------------------------------------------

def make_local_fns(cfg: EngineConfig) -> LocalEngineFns:
    R = cfg.replicas
    rep_idx = jnp.arange(R, dtype=jnp.int32)
    default_quorum = jnp.full((cfg.partitions,), cfg.quorum, jnp.int32)

    # cfg.fused_control swaps the control phase AND the state layout: the
    # bookkeeping scalars ride one stacked [R, K, P] ctrl array
    # (core.state.FusedReplicaState) advanced by wide fused ops
    # (core.step.replica_control_fused). Bit-identical semantics either
    # way (tests/test_control_fusion.py); the read paths work on both
    # layouts through FusedReplicaState's named accessors.
    fused = cfg.fused_control
    ctrl_fn = (core_step.replica_control_fused if fused
               else core_step.replica_control)
    vote_fn = core_step.vote_step_fused if fused else core_step.vote_step

    @jax.jit
    def _init():
        one = init_state(cfg)
        if fused:
            one = fuse_state(one)
        return jax.tree.map(lambda x: jnp.broadcast_to(x, (R,) + x.shape).copy(), one)

    vctrl = jax.vmap(
        functools.partial(ctrl_fn, cfg),
        in_axes=(0, None, 0, None, None, None),
        axis_name=core_step.AXIS,
    )
    default_trim = jnp.zeros((cfg.partitions,), jnp.int32)

    def _ext(ctl):
        # Packed write windows (cfg.packed_writes): the control phase
        # derived the replica-invariant extent; None keeps the legacy
        # full-window kernels byte-for-byte untouched.
        return ctl.extent[0] if cfg.packed_writes else None

    @functools.partial(jax.jit, donate_argnums=(0,))
    def _step_j(state, inp: StepInput, alive, quorum, trim):
        # Control phase per replica (vmapped), then ONE batched write phase
        # on the full [R, P, S+B, SB] ring (Pallas DMA kernel on TPU; the
        # window lands at the physical ring position base % slots).
        new_state, ctl = vctrl(state, inp, rep_idx, alive, quorum, trim)
        log_data = append_rows(
            state.log_data, inp.entries, ctl.out.base[0] % cfg.slots,
            ctl.do_write, extents=_ext(ctl)
        )
        new_state = new_state._replace(log_data=log_data)
        # outputs are replica-invariant after the psum; take replica 0's copy
        return new_state, jax.tree.map(lambda x: x[0], ctl.out)

    def _step(state, inp, alive, quorum=None, trim=None):
        return _step_j(state, inp, alive,
                       default_quorum if quorum is None else quorum,
                       default_trim if trim is None else trim)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def _step_many_j(state, inputs: StepInput, alive, quorum, trim):
        # K chained rounds in ONE dispatch: `inputs` leaves carry a
        # leading chain axis [K, ...]. Dispatch latency (which dominates
        # behind a network tunnel: ~ms per launch vs ~tens of µs of
        # compute for a small round) amortizes over the chain; each scan
        # iteration is a COMPLETE quorum round — ballot before write,
        # atomic, commit advanced — so chaining changes throughput, not
        # semantics. alive/quorum/trim are chain-constant, which gives
        # the per-slot committed-prefix property the host batcher relies
        # on (broker.dataplane burst drain): once a slot's round fails
        # (quorum/capacity under fixed conditions), every later round of
        # the chain fails too.
        def body(st, inp):
            new_st, ctl = vctrl(st, inp, rep_idx, alive, quorum, trim)
            log = append_rows(
                st.log_data, inp.entries, ctl.out.base[0] % cfg.slots,
                ctl.do_write, extents=_ext(ctl)
            )
            return (
                new_st._replace(log_data=log),
                jax.tree.map(lambda x: x[0], ctl.out),
            )

        return jax.lax.scan(body, state, inputs)

    def _step_many(state, inputs, alive, quorum=None, trim=None):
        return _step_many_j(state, inputs, alive,
                            default_quorum if quorum is None else quorum,
                            default_trim if trim is None else trim)

    # Active-set (sparse) variants: `inp.entries` is a tiny dummy (the
    # control phase never reads it); the real rows arrive compacted as
    # entries_c [A, B, SB] + slot_ids [A] (-1 pads) and land via the
    # active-set write kernel. A sparse round ships A/P of the dense
    # input bytes — and input transfer rides every dispatch (the broker
    # batcher uses these; see ops.append.append_rows_active).
    @functools.partial(jax.jit, donate_argnums=(0,))
    def _step_sparse_j(state, inp, entries_c, slot_ids, alive, quorum, trim):
        new_state, ctl = vctrl(state, inp, rep_idx, alive, quorum, trim)
        log_data = append_rows_active(
            state.log_data, entries_c, slot_ids,
            ctl.out.base[0] % cfg.slots, ctl.do_write, extents=_ext(ctl)
        )
        new_state = new_state._replace(log_data=log_data)
        return new_state, jax.tree.map(lambda x: x[0], ctl.out)

    def _step_sparse(state, inp, entries_c, slot_ids, alive, quorum=None,
                     trim=None):
        return _step_sparse_j(state, inp, entries_c, slot_ids, alive,
                              default_quorum if quorum is None else quorum,
                              default_trim if trim is None else trim)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def _step_many_sparse_j(state, inputs, entries_c, slot_ids, alive,
                            quorum, trim):
        def body(st, per_round):
            inp, ec, ids = per_round
            new_st, ctl = vctrl(st, inp, rep_idx, alive, quorum, trim)
            log = append_rows_active(
                st.log_data, ec, ids, ctl.out.base[0] % cfg.slots,
                ctl.do_write, extents=_ext(ctl)
            )
            return (
                new_st._replace(log_data=log),
                jax.tree.map(lambda x: x[0], ctl.out),
            )

        return jax.lax.scan(body, state, (inputs, entries_c, slot_ids))

    def _step_many_sparse(state, inputs, entries_c, slot_ids, alive,
                          quorum=None, trim=None):
        return _step_many_sparse_j(
            state, inputs, entries_c, slot_ids, alive,
            default_quorum if quorum is None else quorum,
            default_trim if trim is None else trim)

    vvote = jax.vmap(
        functools.partial(vote_fn, cfg),
        in_axes=(0, None, None, 0, None, None),
        axis_name=core_step.AXIS,
    )

    @functools.partial(jax.jit, donate_argnums=(0,))
    def _vote_j(state, cand, cand_term, alive, quorum):
        new_state, elected, votes = vvote(state, cand, cand_term, rep_idx,
                                          alive, quorum)
        return new_state, elected[0], votes[0]

    def _vote(state, cand, cand_term, alive, quorum=None):
        return _vote_j(state, cand, cand_term, alive,
                       default_quorum if quorum is None else quorum)

    @jax.jit
    def _read(state, replica, partition, offset):
        replica = jnp.clip(replica, 0, R - 1)
        one = jax.tree.map(lambda x: x[replica], state)
        return core_step.read_batch(cfg, one, partition, offset)

    @jax.jit
    def _read_many(state, replicas, partitions, offsets):
        # Batched committed reads: Q independent (replica, partition,
        # offset) queries in ONE dispatch — the consume-side mirror of
        # append batching (each read dispatch costs a full host<->device
        # round trip, which dominates when many consumers poll). Queries
        # address the full log via read_batch_at: each moves only its
        # own window, never a whole-replica slice.
        def one(rep, part, off):
            return core_step.read_batch_at(
                cfg, state.log_data, state.commit, rep, part, off
            )

        return jax.vmap(one)(replicas, partitions, offsets)

    @jax.jit
    def _read_offset(state, replica, partition, consumer_slot):
        replica = jnp.clip(replica, 0, R - 1)
        one = jax.tree.map(lambda x: x[replica], state)
        return core_step.read_offset(one, partition, consumer_slot)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def _resync_fn(state, src, dst, part_mask):
        if fused:
            # _resync's masking assumes [R, P, ...] leaves; the fused
            # ctrl leaf is [R, K, P]. Resync is the rare recovery path,
            # so round-trip through the named layout instead of teaching
            # the masking about the stacked axis.
            return fuse_state(
                _resync(cfg, unfuse_state(state), src, dst, part_mask)
            )
        return _resync(cfg, state, src, dst, part_mask)

    def _init_from(image: ReplicaState):
        """Install a recovered single-replica image on every replica slot
        (all replicas are identical post-commit — only committed rounds
        are ever persisted)."""
        import numpy as np
        full = jax.tree.map(
            lambda x: jnp.asarray(np.broadcast_to(np.asarray(x), (R,) + np.asarray(x).shape)),
            image,
        )
        return fuse_state(full) if fused else full

    return LocalEngineFns(_init, _step, _step_many, _step_sparse,
                          _step_many_sparse, _vote, _read, _read_many,
                          _read_offset, _resync_fn, _init_from)


# ---------------------------------------------------------------------------
# SPMD (mesh: replica × part)
# ---------------------------------------------------------------------------

def _state_specs(cfg: EngineConfig) -> ReplicaState:
    """PartitionSpecs for the full-cluster state [R, P, ...]: replica axis
    over "replica", partition axis over "part"."""
    return ReplicaState(
        log_data=P("replica", "part", None, None),
        log_end=P("replica", "part"),
        last_term=P("replica", "part"),
        current_term=P("replica", "part"),
        commit=P("replica", "part"),
        offsets=P("replica", "part", None),
    )


def _fused_state_specs(cfg: EngineConfig) -> FusedReplicaState:
    """PartitionSpecs for the fused-control state (cfg.fused_control):
    the stacked ctrl buffer is [R, K, P] — replica axis sharded, the K
    bookkeeping rows replicated WITHIN a device, partition axis sharded
    over "part". Each device then holds its shard's whole [K, local_P]
    bookkeeping block, so a round's four scalar advances stay ONE wide
    select on one local buffer and the two leader broadcasts ride ONE
    [2, local_P] psum over the replica mesh axis (one ICI collective
    where the legacy control phase issues two)."""
    return FusedReplicaState(
        log_data=P("replica", "part", None, None),
        ctrl=P("replica", None, "part"),
        offsets=P("replica", "part", None),
    )


def _input_specs() -> StepInput:
    """Inputs carry no replica axis: XLA's data distribution replicates
    them over the replica mesh axis (this IS the AppendEntries fan-out).
    extents is always present here: None extents are pytree-empty and
    would be a treedef mismatch against the compiled specs, so the spmd
    wrappers fill missing extents with the full window first
    (_fill_extents)."""
    return StepInput(
        entries=P("part", None, None),
        counts=P("part"),
        off_slots=P("part", None),
        off_vals=P("part", None),
        off_counts=P("part"),
        leader=P("part"),
        term=P("part"),
        extents=P("part"),
    )



def spmd_arg_shardings(mesh: Mesh, chain: bool = False):
    """NamedShardings for staging step arguments on an spmd mesh:
    ``(inp, alive, quorum, trim)`` keyed by name. Bench/profile harnesses
    COMMIT inputs to these before a timed window — device arrays with
    unspecified shardings make every call re-resolve shardings on the
    python dispatch path (measured -12% on the spmd side only,
    bench._run_spmd_parity). The broker needs no staging (it hands the
    binding fresh host numpy arrays each round); this is for resident-
    input measurement loops. ``chain=True`` prefixes the unsharded chain
    axis the step_many scan inputs carry."""
    in_specs = _input_specs()
    if chain:
        in_specs = jax.tree.map(
            lambda s: P(*((None,) + tuple(s))), in_specs,
            is_leaf=lambda s: isinstance(s, P),
        )
    named = lambda s: NamedSharding(mesh, s)
    return {
        "inp": jax.tree.map(named, in_specs,
                            is_leaf=lambda s: isinstance(s, P)),
        "alive": named(P("part", None)),
        "quorum": named(P("part")),
        "trim": named(P("part")),
    }


def _smap(f, mesh, in_specs, out_specs):
    """shard_map with the varying-manual-axes checker off: the Pallas
    write kernel's out_shape carries no vma annotation, which newer JAX
    rejects under check_vma inside shard_map on TPU. The checker is a
    static lint, not a semantics change; the engine's replication
    invariants are asserted dynamically by tests/test_spmd.py."""
    try:
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False)
    except TypeError:  # older jax: no check_vma parameter
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs)


def make_spmd_fns(cfg: EngineConfig, mesh: Mesh) -> SpmdEngineFns:
    R = cfg.replicas
    part_shards = mesh.shape["part"]
    if mesh.shape["replica"] != R:
        raise ValueError(
            f"mesh replica axis {mesh.shape['replica']} != cfg.replicas {R}"
        )
    if cfg.partitions % part_shards:
        raise ValueError("partitions must divide evenly over the part axis")
    local_P = cfg.partitions // part_shards

    # cfg.fused_control under shard_map: the same stacked-ctrl layout and
    # fused ops as the local binding (core.step.replica_control_fused),
    # with fused PartitionSpecs — the two leader broadcasts become ONE
    # real [2, local_P] psum on the replica mesh axis (one ICI collective
    # per round where the legacy control phase issues two). Bit-identical
    # committed prefixes to both the legacy-spmd and fused-vmap paths
    # (tests/test_spmd.py parity matrix).
    fused = cfg.fused_control
    ctrl_fn = (core_step.replica_control_fused if fused
               else core_step.replica_control)
    vote_fn = core_step.vote_step_fused if fused else core_step.vote_step

    # The ring-stride aliasing rule priced at the PER-DEVICE shape: each
    # mesh device holds ONE replica's [local_P, S+B, SB] ring block, so
    # local_P is the concurrent strided-DMA stream count — the global-P
    # verdict EngineConfig warns with at construction can be wrong in
    # both directions for a sharded deployment (core.config).
    hazard = stride_alias_hazard(cfg.slots, cfg.max_batch, cfg.slot_bytes,
                                 streams=local_P)
    if hazard is not None:
        warnings.warn(
            f"spmd binding: per-device shard holds {local_P} partition "
            f"rings; {hazard}", UserWarning, stacklevel=2,
        )

    st_specs = _fused_state_specs(cfg) if fused else _state_specs(cfg)
    in_specs = _input_specs()
    rep_ids = jnp.arange(R, dtype=jnp.int32)

    def _squeeze(tree):
        return jax.tree.map(lambda x: x[0], tree)

    def _expand(tree):
        return jax.tree.map(lambda x: x[None], tree)

    def _norm_alive(alive):
        """Engine-level liveness is always [P, R] (per-partition replica
        masks; see core.step._normalize_alive); a [R] mask is broadcast."""
        alive = jnp.asarray(alive)
        if alive.ndim == 1:
            alive = jnp.broadcast_to(alive[None, :], (cfg.partitions, R))
        return alive

    default_quorum = jnp.full((cfg.partitions,), cfg.quorum, jnp.int32)

    default_trim = jnp.zeros((cfg.partitions,), jnp.int32)

    def _fill_extents(inp: StepInput) -> StepInput:
        """Hand-built inputs may leave extents=None (pytree-empty); the
        compiled specs carry a per-part extents shard, so fill with the
        full window (== the legacy write shape). Chained inputs carry
        the leading chain axis on every leaf, counts included."""
        if inp.extents is not None:
            return inp
        return inp._replace(
            extents=jnp.full(inp.counts.shape, cfg.max_batch, jnp.int32)
        )

    def _gather_part(tree):
        """Replicate per-shard [P_local] outputs to full [P] on every
        device. Outputs are tiny int32/bool vectors, and full replication
        lets the host fetch them with a plain np.asarray even when the
        mesh spans processes (multi-host: every process holds an
        addressable copy). Built as a masked psum — the same pattern as
        the read path — so shard_map's replication checker knows the
        result is invariant over "part"."""
        idx = jax.lax.axis_index("part")

        def g(x):
            v = x.astype(jnp.int32) if x.dtype == jnp.bool_ else x
            full = jnp.zeros((part_shards,) + v.shape, v.dtype)
            full = jax.lax.dynamic_update_index_in_dim(full, v, idx, 0)
            out = jax.lax.psum(full, "part").reshape(
                (part_shards * v.shape[0],) + v.shape[1:]
            )
            return out.astype(jnp.bool_) if x.dtype == jnp.bool_ else out

        return jax.tree.map(g, tree)

    # ---- step -------------------------------------------------------------
    def step_body(state, inp, rep, alive, quorum, trim):
        st = _squeeze(state)          # strip the size-1 replica block dim
        new_st, ctl = ctrl_fn(
            cfg, st, inp, rep[0], alive, quorum, trim
        )
        # Write phase on this device's [1, P_local, S+B, SB] ring block.
        log_data = append_rows(
            st.log_data[None], inp.entries, ctl.out.base % cfg.slots,
            ctl.do_write[None],
            extents=ctl.extent if cfg.packed_writes else None,
        )
        new_st = new_st._replace(log_data=log_data[0])
        # out is psum-replicated over "replica"; gather it over "part".
        return _expand(new_st), _gather_part(ctl.out)

    smapped_step = _smap(
        step_body,
        mesh,
        in_specs=(st_specs, in_specs, P("replica"), P("part", None), P("part"),
                  P("part")),
        out_specs=(st_specs, StepOutput(P(), P(), P(), P())),
    )

    @functools.partial(jax.jit, donate_argnums=(0,))
    def _step_j(state, inp, alive, quorum, trim):
        return smapped_step(state, inp, rep_ids, _norm_alive(alive), quorum,
                            trim)

    def _step(state, inp, alive, quorum=None, trim=None):
        return _step_j(state, _fill_extents(inp), alive,
                       default_quorum if quorum is None else quorum,
                       default_trim if trim is None else trim)

    # Chained rounds (see the local binding's _step_many_j for the
    # rationale): scan INSIDE shard_map, so one dispatch commits K
    # complete quorum rounds with all collectives on the mesh.
    def step_many_body(state, inputs, rep, alive, quorum, trim):
        def body(st_block, inp):
            new_st, out = step_body(st_block, inp, rep, alive, quorum, trim)
            return new_st, out

        return jax.lax.scan(body, state, inputs)

    in_specs_k = jax.tree.map(
        lambda s: P(*((None,) + tuple(s))), in_specs,
        is_leaf=lambda s: isinstance(s, P),
    )
    smapped_step_many = _smap(
        step_many_body,
        mesh,
        in_specs=(st_specs, in_specs_k, P("replica"), P("part", None),
                  P("part"), P("part")),
        out_specs=(st_specs, StepOutput(P(), P(), P(), P())),
    )

    @functools.partial(jax.jit, donate_argnums=(0,))
    def _step_many_j(state, inputs, alive, quorum, trim):
        return smapped_step_many(state, inputs, rep_ids, _norm_alive(alive),
                                 quorum, trim)

    def _step_many(state, inputs, alive, quorum=None, trim=None):
        return _step_many_j(state, _fill_extents(inputs), alive,
                            default_quorum if quorum is None else quorum,
                            default_trim if trim is None else trim)

    # ---- sparse (active-set) steps ---------------------------------------
    # entries_c/slot_ids are replicated to every shard; each shard maps
    # the GLOBAL ids into its partition range (-1 = not mine/padding) and
    # writes only its own blocks.
    def _local_ids(ids):
        my_shard = jax.lax.axis_index("part")
        lo = my_shard * local_P
        mine = (ids >= lo) & (ids < lo + local_P)
        return jnp.where(mine, ids - lo, -1)

    def step_sparse_body(state, inp, entries_c, slot_ids, rep, alive,
                         quorum, trim):
        st = _squeeze(state)
        new_st, ctl = ctrl_fn(
            cfg, st, inp, rep[0], alive, quorum, trim
        )
        log_data = append_rows_active(
            st.log_data[None], entries_c, _local_ids(slot_ids),
            ctl.out.base % cfg.slots, ctl.do_write[None],
            extents=ctl.extent if cfg.packed_writes else None,
        )
        new_st = new_st._replace(log_data=log_data[0])
        return _expand(new_st), _gather_part(ctl.out)

    smapped_step_sparse = _smap(
        step_sparse_body,
        mesh,
        in_specs=(st_specs, in_specs, P(None, None, None), P(None),
                  P("replica"), P("part", None), P("part"), P("part")),
        out_specs=(st_specs, StepOutput(P(), P(), P(), P())),
    )

    @functools.partial(jax.jit, donate_argnums=(0,))
    def _step_sparse_j(state, inp, entries_c, slot_ids, alive, quorum, trim):
        return smapped_step_sparse(state, inp, entries_c, slot_ids, rep_ids,
                                   _norm_alive(alive), quorum, trim)

    def _step_sparse(state, inp, entries_c, slot_ids, alive, quorum=None,
                     trim=None):
        return _step_sparse_j(state, _fill_extents(inp), entries_c, slot_ids,
                              alive,
                              default_quorum if quorum is None else quorum,
                              default_trim if trim is None else trim)

    def step_many_sparse_body(state, inputs, entries_c, slot_ids, rep,
                              alive, quorum, trim):
        def body(st_block, per_round):
            inp, ec, ids = per_round
            return step_sparse_body(st_block, inp, ec, ids, rep, alive,
                                    quorum, trim)

        return jax.lax.scan(body, state, (inputs, entries_c, slot_ids))

    smapped_step_many_sparse = _smap(
        step_many_sparse_body,
        mesh,
        in_specs=(st_specs, in_specs_k, P(None, None, None, None),
                  P(None, None), P("replica"), P("part", None), P("part"),
                  P("part")),
        out_specs=(st_specs, StepOutput(P(), P(), P(), P())),
    )

    @functools.partial(jax.jit, donate_argnums=(0,))
    def _step_many_sparse_j(state, inputs, entries_c, slot_ids, alive,
                            quorum, trim):
        return smapped_step_many_sparse(
            state, inputs, entries_c, slot_ids, rep_ids,
            _norm_alive(alive), quorum, trim)

    def _step_many_sparse(state, inputs, entries_c, slot_ids, alive,
                          quorum=None, trim=None):
        return _step_many_sparse_j(
            state, _fill_extents(inputs), entries_c, slot_ids, alive,
            default_quorum if quorum is None else quorum,
            default_trim if trim is None else trim)

    # ---- vote -------------------------------------------------------------
    def vote_body(state, cand, cand_term, rep, alive, quorum):
        st = _squeeze(state)
        new_st, elected, votes = vote_fn(
            cfg, st, cand, cand_term, rep[0], alive, quorum
        )
        elected, votes = _gather_part((elected, votes))
        return _expand(new_st), elected, votes

    smapped_vote = _smap(
        vote_body,
        mesh,
        in_specs=(st_specs, P("part"), P("part"), P("replica"),
                  P("part", None), P("part")),
        out_specs=(st_specs, P(), P()),
    )

    @functools.partial(jax.jit, donate_argnums=(0,))
    def _vote_j(state, cand, cand_term, alive, quorum):
        return smapped_vote(state, cand, cand_term, rep_ids,
                            _norm_alive(alive), quorum)

    def _vote(state, cand, cand_term, alive, quorum=None):
        return _vote_j(state, cand, cand_term, alive,
                       default_quorum if quorum is None else quorum)

    # ---- read (broadcast the serving replica's window to every device) ----
    def read_body(state, rep, replica, partition, offset):
        st = _squeeze(state)
        my_rep = rep[0]
        # global partition -> (shard, local index); shards are contiguous
        shard = partition // local_P
        local_idx = partition % local_P
        my_shard = jax.lax.axis_index("part")
        data, lens, count = core_step.read_batch(cfg, st, local_idx, offset)
        sel = (my_rep == replica) & (my_shard == shard)
        zero = jnp.int32(0)
        data = jax.lax.psum(jnp.where(sel, data, 0), ("replica", "part"))
        lens = jax.lax.psum(jnp.where(sel, lens, 0), ("replica", "part"))
        count = jax.lax.psum(jnp.where(sel, count, zero), ("replica", "part"))
        return data, lens, count

    smapped_read = _smap(
        read_body,
        mesh,
        in_specs=(st_specs, P("replica"), P(), P(), P()),
        out_specs=(P(), P(), P()),
    )

    @jax.jit
    def _read(state, replica, partition, offset):
        replica = jnp.clip(replica, 0, R - 1)
        partition = jnp.clip(partition, 0, cfg.partitions - 1)
        return smapped_read(state, rep_ids, replica, partition, offset)

    # Batched reads: Q queries, ONE dispatch, one psum for the whole
    # batch (the consume-side mirror of append batching).
    def read_many_body(state, rep, replicas, partitions, offsets):
        st = _squeeze(state)
        my_rep = rep[0]
        my_shard = jax.lax.axis_index("part")

        def one(replica, partition, offset):
            shard = partition // local_P
            local_idx = partition % local_P
            data, lens, count = core_step.read_batch(cfg, st, local_idx,
                                                     offset)
            sel = (my_rep == replica) & (my_shard == shard)
            return (
                jnp.where(sel, data, 0),
                jnp.where(sel, lens, 0),
                jnp.where(sel, count, jnp.int32(0)),
            )

        data, lens, count = jax.vmap(one)(replicas, partitions, offsets)
        data = jax.lax.psum(data, ("replica", "part"))
        lens = jax.lax.psum(lens, ("replica", "part"))
        count = jax.lax.psum(count, ("replica", "part"))
        return data, lens, count

    smapped_read_many = _smap(
        read_many_body,
        mesh,
        in_specs=(st_specs, P("replica"), P(), P(), P()),
        out_specs=(P(), P(), P()),
    )

    @jax.jit
    def _read_many(state, replicas, partitions, offsets):
        replicas = jnp.clip(replicas, 0, R - 1)
        partitions = jnp.clip(partitions, 0, cfg.partitions - 1)
        return smapped_read_many(state, rep_ids, replicas, partitions,
                                 offsets)

    def read_off_body(state, rep, replica, partition, consumer_slot):
        st = _squeeze(state)
        shard = partition // local_P
        local_idx = partition % local_P
        sel = (rep[0] == replica) & (jax.lax.axis_index("part") == shard)
        val = core_step.read_offset(st, local_idx, consumer_slot)
        return jax.lax.psum(jnp.where(sel, val, 0), ("replica", "part"))

    smapped_read_off = _smap(
        read_off_body,
        mesh,
        in_specs=(st_specs, P("replica"), P(), P(), P()),
        out_specs=P(),
    )

    @jax.jit
    def _read_offset(state, replica, partition, consumer_slot):
        replica = jnp.clip(replica, 0, R - 1)
        partition = jnp.clip(partition, 0, cfg.partitions - 1)
        return smapped_read_off(state, rep_ids, replica, partition, consumer_slot)

    # ---- resync -----------------------------------------------------------
    def resync_body(state, rep, src, dst, part_mask):
        st = _squeeze(state)
        if fused:
            # The masking below assumes [local_P, ...] leaves; the fused
            # ctrl leaf is [K, local_P]. Resync is the rare recovery
            # path, so round-trip through the named layout (exact both
            # ways) instead of teaching the masking about the stacked
            # axis — the same trade the local binding makes.
            st = unfuse_state(st)
        my_rep = rep[0]
        # broadcast src replica's masked rows to everyone, then overwrite dst
        def leaf(x):
            m = part_mask.reshape((-1,) + (1,) * (x.ndim - 1))
            src_rows = jax.lax.psum(
                jnp.where((my_rep == src) & m, x, jnp.zeros_like(x)), "replica"
            )
            return jnp.where((my_rep == dst) & m, src_rows, x)

        new_st = jax.tree.map(leaf, st)
        if fused:
            new_st = fuse_state(new_st)
        return _expand(new_st)

    smapped_resync = _smap(
        resync_body,
        mesh,
        in_specs=(st_specs, P("replica"), P(), P(), P("part")),
        out_specs=st_specs,
    )

    @functools.partial(jax.jit, donate_argnums=(0,))
    def _resync_fn(state, src, dst, part_mask):
        return smapped_resync(state, rep_ids, src, dst, part_mask)

    # ---- init -------------------------------------------------------------
    def _place(one: ReplicaState):
        """Install a single-replica image (always the NAMED layout — the
        recovery path hands plain ReplicaStates) on every replica slot,
        sharded per st_specs; fused configs stack the ctrl scalars
        first so the placed state matches the compiled layout."""
        if fused:
            one = fuse_state(one)
        full = jax.tree.map(
            lambda x: jnp.broadcast_to(jnp.asarray(x), (R,) + jnp.asarray(x).shape),
            one,
        )
        shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), st_specs,
                                 is_leaf=lambda s: isinstance(s, P))
        return jax.tree.map(jax.device_put, full, shardings)

    def _init():
        return _place(init_state(cfg))

    return SpmdEngineFns(_init, _step, _step_many, _step_sparse,
                         _step_many_sparse, _vote, _read, _read_many,
                         _read_offset, _resync_fn, _place, mesh)
