"""Device-mesh construction for the replication engine.

Two mesh axes:

- ``"replica"`` — the replication factor. One device per replica; quorum
  votes are psums over this axis, and the AppendEntries broadcast rides it
  (ICI within a host, DCN across hosts via jax.distributed). Replaces the
  reference's broker-to-broker Bolt RPC fan-out
  (mq-broker/.../TopicsRaftServer.java:106, BrokerRpcClient.java).

- ``"part"`` — partition sharding. Partitions are data-parallel:
  independent logs, no cross-partition collectives, so this axis only
  shards the leading P axis of the state (the reference's "many Raft
  groups multiplexed on one server", PartitionRaftServer.java:93, becomes
  a sharded tensor axis). Each device then holds local_P =
  partitions / part_shards rings — the count that prices the HBM
  stride-aliasing rule on that device (core.config.stride_alias_hazard;
  make_spmd_fns re-checks it per shard) and the knob that scales P past
  one chip's HBM. Sizing: part_shards must divide partitions evenly and
  replicas * part_shards devices must exist (README "SPMD engine").
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


def init_distributed(
    coordinator: str,
    num_hosts: int,
    host_index: int,
) -> int:
    """Join this process to a multi-host mesh via jax.distributed.

    `coordinator` is host 0's "host:port"; every participating process
    calls this ONCE before any other JAX use, after which jax.devices()
    returns the GLOBAL device list and make_mesh() builds meshes whose
    collectives ride ICI within a host and DCN across hosts — the scale
    path the reference reaches with one JRaft/Bolt JVM per machine
    (reference: mq-broker/src/main/java/metadata/raft/
    PartitionRaftServer.java:83-93 peers across hosts). Returns the
    global device count.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_hosts,
        process_id=host_index,
    )
    return len(jax.devices())


def pick_axes(n_devices: int, replicas: int | None = None) -> tuple[int, int]:
    """Choose (replica, part) axis sizes for n devices.

    An explicitly requested replication factor must divide the device
    count — silently degrading RF would weaken quorum durability without
    warning. With no request, pick the largest of (5, 3, 2, 1) that
    divides; remaining devices shard partitions.
    """
    if replicas is not None:
        if n_devices % replicas:
            raise ValueError(
                f"replication factor {replicas} does not divide {n_devices} "
                f"devices; refusing to silently weaken the quorum"
            )
        return replicas, n_devices // replicas
    for r in (5, 3, 2, 1):
        if n_devices % r == 0:
            return r, n_devices // r
    return 1, n_devices


def make_mesh(
    replicas: int,
    part_shards: int = 1,
    devices: list | None = None,
) -> Mesh:
    """Build a (replica, part) mesh over the given (or all) devices."""
    devices = devices if devices is not None else jax.devices()
    need = replicas * part_shards
    if len(devices) < need:
        raise ValueError(
            f"need {need} devices for mesh (replica={replicas}, part={part_shards}), "
            f"have {len(devices)}"
        )
    grid = np.asarray(devices[:need]).reshape(replicas, part_shards)
    return Mesh(grid, axis_names=("replica", "part"))
