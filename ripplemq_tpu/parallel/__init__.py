"""Mesh construction and SPMD execution of the core replication steps."""

from ripplemq_tpu.parallel.mesh import make_mesh, pick_axes
from ripplemq_tpu.parallel.engine import LocalEngineFns, SpmdEngineFns, make_local_fns, make_spmd_fns

__all__ = [
    "make_mesh",
    "pick_axes",
    "LocalEngineFns",
    "SpmdEngineFns",
    "make_local_fns",
    "make_spmd_fns",
]
