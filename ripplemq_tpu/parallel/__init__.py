"""Mesh construction and SPMD execution of the core replication steps.

Re-exports are lazy (PEP 562): `parallel.shmring` / `parallel.hostplane`
are the jax-free modules the spawned host-plane workers import, and an
eager mesh/engine import here would charge every worker boot the full
jax initialization.
"""

__all__ = [
    "make_mesh",
    "pick_axes",
    "LocalEngineFns",
    "SpmdEngineFns",
    "make_local_fns",
    "make_spmd_fns",
]

_MESH = ("make_mesh", "pick_axes")


def __getattr__(name):
    if name in _MESH:
        from ripplemq_tpu.parallel import mesh

        return getattr(mesh, name)
    if name in __all__:
        from ripplemq_tpu.parallel import engine

        return getattr(engine, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
