"""Multi-host SPMD proof: one replication round committed across OS
processes.

Run the SAME command on every host (here: two processes on one machine,
each contributing virtual CPU devices — the same wiring carries real
TPU pods, where each host contributes its local chips over ICI and the
processes meet over DCN):

    python -m ripplemq_tpu.parallel.multihost_check \
        --coordinator 127.0.0.1:9777 --num-hosts 2 --host-index {0,1}

Each process joins the jax.distributed coordination service, builds ONE
global (replica x part) mesh over all hosts' devices, and executes a
full data round + election round. The quorum psum then physically
crosses the process boundary — this is the DCN claim of parallel.mesh
made executable (and is what tests/test_multihost.py asserts in CI).
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="python -m ripplemq_tpu.parallel.multihost_check")
    ap.add_argument("--coordinator", required=True, help="host0's host:port")
    ap.add_argument("--num-hosts", type=int, required=True)
    ap.add_argument("--host-index", type=int, required=True)
    ap.add_argument("--local-devices", type=int, default=0,
                    help="force N virtual CPU devices on this process "
                         "(testing without real multi-chip hosts); 0 = "
                         "use the platform's real devices")
    args = ap.parse_args(argv)

    if args.local_devices:
        # Must precede JAX backend init.
        flags = os.environ.get("XLA_FLAGS", "")
        flags = " ".join(
            f for f in flags.split()
            if "xla_force_host_platform_device_count" not in f
        )
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{args.local_devices}"
        ).strip()
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if args.local_devices:
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from ripplemq_tpu.core.config import EngineConfig
    from ripplemq_tpu.core.encode import build_step_input
    from ripplemq_tpu.parallel.engine import make_spmd_fns
    from ripplemq_tpu.parallel.mesh import init_distributed, make_mesh, pick_axes

    n = init_distributed(args.coordinator, args.num_hosts, args.host_index)
    replicas, part_shards = pick_axes(n)
    P = 2 * part_shards
    # Production levers on: the DCN proof must cover the binding
    # deployments run — fused control's stacked leader-broadcast psum is
    # the collective that crosses the process boundary here (ISSUE 6).
    cfg = EngineConfig(
        partitions=P, replicas=replicas, slots=64, slot_bytes=32,
        max_batch=8, read_batch=8, max_consumers=8, max_offset_updates=4,
        fused_control=True, packed_writes=True,
    )
    mesh = make_mesh(replicas, part_shards)
    fns = make_spmd_fns(cfg, mesh)
    state = fns.init()

    # Data round: identical host inputs on every process (the controller
    # broadcast); the ballot psum crosses the process boundary.
    inp = build_step_input(
        cfg, appends={p: [b"mh-%d" % p] for p in range(P)}, leader=0, term=1
    )
    alive = np.ones((P, replicas), bool)
    quorum = np.full((P,), cfg.quorum, np.int32)
    state, out = fns.step(state, inp, alive, quorum)
    committed = np.asarray(out.committed)  # outputs are fully replicated
    assert committed.all(), f"round did not commit: {committed}"
    assert (np.asarray(out.votes) == replicas).all()

    # Election round across the same mesh.
    state, elected, votes = fns.vote(
        state, np.zeros((P,), np.int32), np.full((P,), 2, np.int32),
        alive, quorum,
    )
    assert np.asarray(elected).all(), "election failed"
    jax.block_until_ready(jax.tree.leaves(state))
    print(
        f"MULTIHOST_OK host={args.host_index}/{args.num_hosts} "
        f"devices={n} mesh=({replicas}x{part_shards})",
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
