"""Multi-core host plane: per-partition-group worker subprocesses.

PROFILE.md's honest wall is ~28 µs of interpreter CPU per message
spread across broker threads — the GIL, not the engine (<2 µs), caps
the e2e path. This module shards the broker's HOST path (submit
validation, pid/seq stamping, payload packing, settled-mirror serving
of consumer reads) into N worker subprocesses, each owning the
disjoint partition-group slice `slot % host_workers == worker_id`,
connected to the dispatcher by a pair of shared-memory frame rings
(parallel/shmring.py). Payload bytes are packed ONCE, by the worker,
into the exact `[k, slot_bytes]` row block the engine appends
(core/encode.py row format) — the block crosses the ring, the broker
wraps it in a zero-copy numpy view (DataPlane.submit_packed), and
nothing is re-pickled per hop.

The device program stays where it was: ONE DataPlane on the current
controller, one replication plane, one settle pipeline — committed
prefixes are byte-identical to the single-process plane by
construction. What moves off the broker's GIL is the per-message
interpreter work around the engine.

Worker lifetime: spawned (never forked — the broker process is full of
threads and a JAX runtime) from a module whose import chain is kept
jax-free (the package __init__s are lazy), so a worker boots in
~100 ms. A dead worker is detected by its receive thread; every
pending request fails with the typed, retryable WorkerUnavailableError
(no silent hangs), the worker respawns with a bumped GENERATION, and
its stamping pid is invalidated until the broker registers a fresh
per-(worker, generation) pid — a respawned worker's restarted sequence
counters must never ride an old pid into the cluster dedup table
(that would collapse fresh batches as replays: acked loss).

Idempotence stamping: each worker stamps pid-less produces with its
OWN metadata-issued pid (`set_pid`, driven by the broker's pid duty)
plus per-slot sequence counters — slices are disjoint, so counters
need no cross-process coordination.

Mirror serving: the controller's settle thread publishes each settled
round's rows (fire-and-forget, never blocking settle) to the owning
worker, which keeps the newest CONTIGUOUS run per slot under a byte
budget and serves consume reads from it. Any uncertainty — a gap from
a dropped publish, an offset below the window, a dead worker — falls
back to the DataPlane read path, which remains the authority.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FuturesTimeoutError
from typing import Optional

from ripplemq_tpu.obs.lockwitness import make_lock
from ripplemq_tpu.obs.spans import SpanRing, ctx_from_wire
from ripplemq_tpu.parallel.shmring import (
    RingClosedError,
    ShmRing,
    TornFrameError,
)
from ripplemq_tpu.utils.logs import get_logger
from ripplemq_tpu.wire import codec

log = get_logger("hostplane")

_ROW_HDR = 8  # length u32 + term u32 (core/config.ROW_HEADER)


class WorkerUnavailableError(Exception):
    """The owning host worker is dead or mid-respawn. RETRYABLE by
    contract (wire/retry.py classifies the `worker_unavailable:` wire
    prefix): the dispatcher respawns the worker and a retry lands."""


class OversizeBatchError(Exception):
    """The batch would not fit a ring frame (frames cap at half the
    ring). NOT a refusal: the produce path falls back to the
    in-process submit/stamp/pack branch, which has no such bound —
    killing the worker over one giant batch (and re-killing every
    respawn when the client retries) is the failure mode this check
    exists to prevent."""


def worker_of(slot: int, n_workers: int) -> int:
    """The partition-group map: slot -> owning worker."""
    return slot % n_workers


# --------------------------------------------------------------------------
# Worker process side (import chain must stay jax-free: spawn boots this)
# --------------------------------------------------------------------------


def _pack_rows(msgs: list, slot_bytes: int) -> tuple[bytes, list[int]]:
    """Pure-python twin of core/encode.pack_payload_rows: one
    header-prefixed `slot_bytes` row per message, zero term (the
    batcher stamps the round term at drain). Returns (block, lens)."""
    out = bytearray(len(msgs) * slot_bytes)
    lens = []
    pos = 0
    for m in msgs:
        n = len(m)
        lens.append(n)
        out[pos : pos + 4] = n.to_bytes(4, "little")
        out[pos + _ROW_HDR : pos + _ROW_HDR + n] = m
        pos += slot_bytes
    return bytes(out), lens


class _SlotMirror:
    """One slot's settled-row window: the newest contiguous run of
    mirror frames, capped at `budget` bytes (oldest frames drop, the
    window start rises)."""

    __slots__ = ("start", "end", "frames", "nbytes", "slot_bytes")

    def __init__(self, slot_bytes: int) -> None:
        self.start = 0
        self.end = 0
        self.frames: list[tuple[int, int, bytes]] = []  # (base, end, rows)
        self.nbytes = 0
        self.slot_bytes = slot_bytes

    def publish(self, base: int, rows: bytes, budget: int) -> None:
        nrows = len(rows) // self.slot_bytes
        if nrows <= 0:
            return
        if not self.frames or base != self.end:
            if base < self.start:
                return  # stale duplicate below the window
            # Gap (a dropped publish or a fresh worker): restart the
            # contiguous run — correctness lives in the fallback path.
            self.frames = []
            self.nbytes = 0
            self.start = base
        self.frames.append((base, base + nrows, rows))
        self.end = base + nrows
        self.nbytes += len(rows)
        while self.nbytes > budget and len(self.frames) > 1:
            b, e, r = self.frames.pop(0)
            self.nbytes -= len(r)
            self.start = self.frames[0][0]

    def read(self, offset: int, max_msgs: Optional[int]
             ) -> Optional[tuple[list[bytes], int]]:
        """(messages, next_offset) served like DataPlane.read's hot
        window — length-0 rows are alignment padding and are walked
        over — or None when the offset is outside the window (the
        dispatcher falls back to the engine read path)."""
        if offset < self.start:
            return None
        if offset >= self.end:
            return [], offset  # tail poll: empty, position unmoved
        SB = self.slot_bytes
        msgs: list[bytes] = []
        pos = offset
        last_row_end = offset
        for base, end, rows in self.frames:
            if end <= pos:
                continue
            i = pos - base
            while i < end - base:
                off = i * SB
                n = int.from_bytes(rows[off : off + 4], "little")
                if n > 0:
                    msgs.append(bytes(rows[off + _ROW_HDR : off + _ROW_HDR + n]))
                    last_row_end = base + i + 1
                    if max_msgs is not None and len(msgs) >= max_msgs:
                        return msgs, last_row_end
                i += 1
            pos = end
        return msgs, pos if msgs else self.end


def _host_worker_main(worker_id: int, req_name: str, resp_name: str,
                      slot_bytes: int, payload_bytes: int, max_batch: int,
                      mirror_budget: int) -> None:
    """Worker loop: pop request frames, serve, push responses. Exits
    when the dispatcher unlinks the rings, on a torn frame (the
    dispatcher died mid-publish), or when the parent process is gone."""
    req = ShmRing.attach(req_name)
    resp = ShmRing.attach(resp_name)
    mirrors: dict[int, _SlotMirror] = {}
    pid = 0
    seqs: dict[int, int] = {}
    served = stamped = 0
    parent = os.getppid()
    # Worker-side span ring. The proc label carries the OS pid so two
    # generations of the same worker index never collide in span-id
    # space. Records for a sampled submit ride back to the dispatcher
    # inside the existing response frame (no extra ring traffic);
    # span_cursor tracks what has already been shipped.
    spans = SpanRing(f"worker{worker_id}.{os.getpid()}")
    span_cursor = -1
    try:
        while True:
            try:
                frame = req.pop(timeout_s=0.25)
            except (TornFrameError, RingClosedError):
                return
            if frame is None:
                if os.getppid() != parent:
                    return  # orphaned: the broker process died
                continue
            m = codec.decode(frame)
            op = m.get("op")
            if op in ("submit", "submit_raw"):
                served += 1
                out = {"id": m["id"], "ok": True}
                # Sampled submits carry the dispatcher's worker.hop ctx;
                # unsampled ones have no tctx and sp is the NULL_SPAN
                # (no clock read, no allocation). A refused batch leaves
                # its spans un-ended — absent, a partial trace.
                sp = spans.span("worker.serve",
                                ctx_from_wire(m.get("tctx")), {"op": op})
                if op == "submit_raw":
                    # Raw dispatch: the broker peeked only the routing
                    # scalars off this client frame — THIS decode, on
                    # the worker's core, is the frame's first and only
                    # full decode (the deleted hop was broker decode →
                    # ring re-encode → worker decode).
                    try:
                        inner = codec.decode(m["frame"])
                    except ValueError:
                        inner = None
                    msgs = (inner.get("messages")
                            if isinstance(inner, dict) else None)
                    if not isinstance(msgs, list):
                        resp.push(codec.encode(
                            {"id": m["id"], "ok": False,
                             "why": "malformed raw produce frame"}))
                        continue
                else:
                    msgs = m["msgs"]
                vs = spans.span("worker.validate", sp.ctx)
                bad = None
                if not msgs:
                    bad = "empty messages"
                else:
                    for x in msgs:
                        if not isinstance(x, (bytes, bytearray, memoryview)):
                            bad = "payloads must be bytes"
                            break
                        if len(x) == 0:
                            bad = ("empty messages are not supported "
                                   "(length-0 rows mark alignment padding)")
                            break
                        if len(x) > payload_bytes:
                            bad = (f"payload of {len(x)} bytes exceeds "
                                   f"payload_bytes {payload_bytes}")
                            break
                if bad is not None:
                    # NB: ring-protocol refusals ride a `why` field, not
                    # `error` — these frames never reach a wire client
                    # (the dispatcher re-raises/falls back), so they are
                    # deliberately outside the wire retry taxonomy.
                    out = {"id": m["id"], "ok": False, "why": bad}
                    resp.push(codec.encode(out))
                    continue
                vs.end()
                ss = spans.span("worker.stamp", sp.ctx)
                if m.get("pid") is not None:
                    bpid, bseq = int(m["pid"]), int(m.get("seq", -1))
                else:
                    slot = int(m["slot"])
                    if pid > 0:
                        bpid = pid
                        bseq = seqs.get(slot, 0)
                        seqs[slot] = bseq + len(msgs)
                        stamped += len(msgs)
                    else:
                        bpid, bseq = 0, -1
                ss.end()
                ps = spans.span("worker.pack", sp.ctx)
                chunks = []
                for i in range(0, len(msgs), max_batch):
                    block, lens = _pack_rows(msgs[i : i + max_batch],
                                             slot_bytes)
                    chunks.append([lens, block])
                ps.end()
                out["pid"] = bpid
                out["seq"] = bseq
                out["chunks"] = chunks
                sp.end(msgs=len(msgs))
                if sp.ctx is not None:
                    # Ship only the records this request added: the ring
                    # is single-threaded here, so everything past the
                    # cursor belongs to this (sampled) submit.
                    recs = spans.snapshot(after=span_cursor)
                    if recs:
                        span_cursor = recs[-1]["seq"]
                        out["spans"] = recs
                resp.push(codec.encode(out))
            elif op == "read":
                served += 1
                slot = int(m["slot"])
                mir = mirrors.get(slot)
                res = None
                if mir is not None:
                    # Clamp the answer to the response ring's frame cap
                    # (half the ring): an uncapped read (max_msgs=None)
                    # of a full mirror window would push an oversize
                    # frame and kill this worker. A clipped answer is
                    # correct by contract — next_offset points at the
                    # last delivered row, the consumer continues.
                    cap = max(1, (resp.capacity // 2 - 1024)
                              // (payload_bytes + 16))
                    mx = m.get("max")
                    mx = cap if mx is None else min(int(mx), cap)
                    res = mir.read(int(m["offset"]), mx)
                if res is None:
                    resp.push(codec.encode(
                        {"id": m["id"], "ok": False,
                         "why": "mirror_behind"}))
                else:
                    msgs, end = res
                    resp.push(codec.encode(
                        {"id": m["id"], "ok": True, "msgs": msgs,
                         "end": end}))
            elif op == "mirror":
                slot = int(m["slot"])
                mir = mirrors.get(slot)
                if mir is None:
                    mir = mirrors[slot] = _SlotMirror(slot_bytes)
                mir.publish(int(m["base"]), bytes(m["rows"]), mirror_budget)
            elif op == "pid":
                # A pid install always resets the sequence counters:
                # the broker only ever installs a FRESH per-(worker,
                # generation) pid, whose counters must start at zero.
                pid = int(m["pid"])
                seqs = {}
            elif op == "ping":
                resp.push(codec.encode({
                    "id": m["id"], "ok": True, "served": served,
                    "stamped": stamped,
                    "mirror_bytes": sum(x.nbytes for x in mirrors.values()),
                    "pid": pid,
                }))
            elif op == "stop":
                return
    finally:
        req.close()
        resp.close()


# --------------------------------------------------------------------------
# Dispatcher (broker) side
# --------------------------------------------------------------------------


class _WorkerHandle:
    """One worker: its process, its ring pair, and the send/recv thread
    pair that serializes ring access (the rings are SPSC)."""

    def __init__(self, plane: "HostPlane", idx: int, gen: int) -> None:
        import multiprocessing as mp

        self.idx = idx
        self.gen = gen
        self.dead = False
        self.req_ring = ShmRing.create(plane.ring_bytes)
        self.resp_ring = ShmRing.create(plane.ring_bytes)
        self._plane = plane
        self._sendq: "queue.Queue" = queue.Queue(maxsize=4096)
        self._plock = make_lock("_WorkerHandle._plock")
        self._pending: dict[int, Future] = {}
        self._next_id = 0
        ctx = mp.get_context("spawn")
        self.proc = ctx.Process(
            target=_host_worker_main,
            args=(idx, self.req_ring.name, self.resp_ring.name,
                  plane.slot_bytes, plane.payload_bytes, plane.max_batch,
                  plane.mirror_budget),
            daemon=True,
            name=f"hostworker-{idx}",
        )
        self.proc.start()
        self._send_thread = threading.Thread(
            target=self._send_loop, daemon=True,
            name=f"hostplane-send-{idx}",
        )
        self._recv_thread = threading.Thread(
            target=self._recv_loop, daemon=True,
            name=f"hostplane-recv-{idx}",
        )
        self._send_thread.start()
        self._recv_thread.start()

    # -- request plumbing --

    def request(self, op: dict, timeout_s: float) -> dict:
        """Round-trip one op. The request id is the per-stream sequence
        number: ids are assigned in send order and the worker answers
        in arrival order, so responses pipeline — many RPC threads keep
        many ops in flight on one ring pair."""
        if self.dead:
            raise WorkerUnavailableError(
                f"host worker {self.idx} (gen {self.gen}) is down"
            )
        fut: Future = Future()
        try:
            self._sendq.put((op, fut), timeout=timeout_s)
        except queue.Full:
            raise WorkerUnavailableError(
                f"host worker {self.idx} send queue full"
            ) from None
        try:
            return fut.result(timeout=timeout_s)
        # concurrent.futures.TimeoutError is a distinct class from the
        # builtin before Python 3.11 — catch both (the repo-wide rule).
        except (TimeoutError, FuturesTimeoutError):
            raise WorkerUnavailableError(
                f"host worker {self.idx} unresponsive after {timeout_s}s"
            ) from None

    def post(self, op: dict) -> bool:
        """Fire-and-forget: NEVER blocks the caller — a full queue
        drops the frame (the worker's contiguity check turns a mirror
        drop into a clean fallback, not corruption)."""
        if self.dead:
            return False
        try:
            self._sendq.put_nowait((op, None))
            return True
        except queue.Full:
            return False

    def post_parts(self, parts: list) -> bool:
        """Fire-and-forget scatter-gather publish (the settled-mirror
        path): `parts` is a pre-encoded frame split as
        [codec prefix, payload buffer] — the send loop hands it to
        ShmRing.push_parts so the payload (rows the broker mirror
        already holds) is copied exactly ONCE, into shared memory,
        instead of being re-buffered through codec.encode's output
        bytearray + bytes() snapshot first. Same drop contract as
        post()."""
        if self.dead:
            return False
        try:
            self._sendq.put_nowait((parts, None))
            return True
        except queue.Full:
            return False

    def _send_loop(self) -> None:
        while True:
            item = self._sendq.get()
            if item is None:
                return
            op, fut = item
            rid = None
            parts = None
            if fut is not None:
                with self._plock:
                    rid = self._next_id
                    self._next_id += 1
                    self._pending[rid] = fut
                if isinstance(op, tuple):
                    # Raw-frame request (submit_raw): (meta, blob key,
                    # undecoded frame). The id rides the meta prefix and
                    # the frame crosses into shared memory untouched —
                    # same scatter-gather as post_parts, but round-trip.
                    meta, bkey, blob = op
                    parts = [
                        codec.encode_dict_with_blob(
                            {**meta, "id": rid}, bkey, blob),
                        blob,
                    ]
                else:
                    op = dict(op)
                    op["id"] = rid
            try:
                if isinstance(op, list):
                    # Pre-split scatter-gather frame (post_parts): the
                    # payload part crosses into shared memory directly,
                    # skipping the encode-buffer re-copy.
                    pushed = self.req_ring.push_parts(op, timeout_s=0)
                elif parts is not None:
                    pushed = self.req_ring.push_parts(parts, timeout_s=5.0)
                else:
                    pushed = self.req_ring.push(
                        codec.encode(op),
                        timeout_s=0 if fut is None else 5.0,
                    )
            except ValueError as e:
                # Oversize frame: refuse THIS request only — the worker
                # and every other in-flight op are fine (the submit
                # path pre-checks sizes, so this is a backstop).
                if fut is not None:
                    with self._plock:
                        self._pending.pop(rid, None)
                    if not fut.done():
                        fut.set_exception(OversizeBatchError(str(e)))
                continue
            except Exception as e:
                # Ring closed/full/torn: the worker side of this pair
                # is gone or wedged — fail the window AND hand the
                # handle to the respawn path (unless stop() already
                # latched `dead`, in which case this is shutdown).
                already = self.dead
                self._fail_all(e)
                if not already:
                    self._plane._worker_died(self)
                return
            if not pushed and fut is not None:
                with self._plock:
                    self._pending.pop(rid, None)
                if not fut.done():
                    fut.set_exception(WorkerUnavailableError(
                        f"host worker {self.idx} ring full"
                    ))

    def _recv_loop(self) -> None:
        while not self.dead:
            try:
                frame = self.resp_ring.pop(timeout_s=0.2)
            except (TornFrameError, RingClosedError) as e:
                # A torn response = the worker died mid-publish: this
                # MUST reach the respawn path, not just latch `dead` —
                # otherwise the slice is down until broker restart.
                # (stop() latches `dead` before closing the rings, so a
                # shutdown-raised RingClosedError skips the respawn.)
                already = self.dead
                self._fail_all(e)
                if not already:
                    self._plane._worker_died(self)
                return
            if frame is None:
                if not self.proc.is_alive():
                    self._fail_all(None)
                    self._plane._worker_died(self)
                    return
                continue
            m = codec.decode(frame)
            with self._plock:
                fut = self._pending.pop(m.get("id"), None)
            if fut is not None and not fut.done():
                fut.set_result(m)

    def _fail_all(self, exc: Optional[Exception]) -> None:
        with self._plock:
            # `dead` rides the same mutex as the pending table: the
            # latch and the table drain must be one atomic transition
            # (a submit racing the drain must either register (and be
            # failed here) or see the latch — ownership lint, PR 11).
            self.dead = True
            pending = list(self._pending.values())
            self._pending.clear()
        err = WorkerUnavailableError(
            f"host worker {self.idx} (gen {self.gen}) died"
            + (f": {exc}" if exc else "")
        )
        for fut in pending:
            if not fut.done():
                fut.set_exception(err)

    def occupancy(self) -> float:
        try:
            return self.req_ring.fill_fraction()
        except Exception:
            return 0.0

    def stop(self, unlink: bool = True) -> None:
        with self._plock:
            self.dead = True
        try:
            # Best-effort wake for an idle send loop. NEVER a blocking
            # put: with the queue full and the send loop already dead,
            # a blocking put hangs whichever thread runs stop()
            # (respawn path or broker shutdown) forever. A live send
            # loop blocked inside push() wakes via ring close below.
            self._sendq.put_nowait(None)
        except queue.Full:
            pass
        try:
            if self.proc.is_alive():
                self.proc.terminate()
                self.proc.join(timeout=1.0)
                if self.proc.is_alive():
                    self.proc.kill()
                    self.proc.join(timeout=1.0)
        except Exception:
            pass
        self._fail_all(None)
        if unlink:
            self.req_ring.close()
            self.resp_ring.close()


class HostPlane:
    """Dispatcher for `n_workers` host-plane workers. Thread-safe: RPC
    worker threads call submit()/read(), the settle thread publish(),
    the duty loop set_worker_pid()/stats()."""

    def __init__(self, n_workers: int, slot_bytes: int, payload_bytes: int,
                 max_batch: int, ring_bytes: int = 1 << 22,
                 mirror_budget: int = 4 << 20,
                 recorder=None, spans=None) -> None:
        if n_workers < 1:
            raise ValueError("n_workers must be >= 1")
        self.n_workers = n_workers
        self.slot_bytes = slot_bytes
        self.payload_bytes = payload_bytes
        self.max_batch = max_batch
        self.ring_bytes = ring_bytes
        self.mirror_budget = mirror_budget
        self.recorder = recorder
        # Broker span ring (obs/spans.SpanRing) or None. Worker-side
        # span records riding back in submit responses are ingested
        # here so admin.spans serves one page covering both processes.
        self.spans = spans
        self._lock = make_lock("HostPlane._lock")
        self._workers: list[Optional[_WorkerHandle]] = [None] * n_workers
        self._gens = [0] * n_workers
        self._last_respawn = [0.0] * n_workers
        self._restarts = 0
        self._stopped = False

    def start(self) -> None:
        with self._lock:
            for i in range(self.n_workers):
                if self._workers[i] is None:
                    self._workers[i] = _WorkerHandle(self, i, self._gens[i])

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            workers = [w for w in self._workers if w is not None]
            self._workers = [None] * self.n_workers
        for w in workers:
            w.stop()

    # -- worker lifecycle --

    def _worker_died(self, handle: _WorkerHandle) -> None:
        """Called by the dead worker's recv thread: respawn with a
        bumped generation (rate-limited — a worker that dies at boot
        must not spin the dispatcher)."""
        if self.recorder is not None:
            self.recorder.record("host_worker_down", worker=handle.idx,
                                 generation=handle.gen)
        log.warning("host worker %d (gen %d) died; respawning",
                    handle.idx, handle.gen)
        handle.stop()
        with self._lock:
            if self._stopped or self._workers[handle.idx] is not handle:
                return
            self._workers[handle.idx] = None
        # Backoff OUTSIDE the lock (submitters probe `dead` bare).
        since = time.monotonic() - self._last_respawn[handle.idx]
        if since < 1.0:
            time.sleep(1.0 - since)
        with self._lock:
            if self._stopped or self._workers[handle.idx] is not None:
                return
            self._gens[handle.idx] += 1
            gen = self._gens[handle.idx]
            self._last_respawn[handle.idx] = time.monotonic()
            self._restarts += 1
            self._workers[handle.idx] = _WorkerHandle(self, handle.idx, gen)
        if self.recorder is not None:
            self.recorder.record("host_worker_restart", worker=handle.idx,
                                 generation=gen)

    def _handle(self, slot: int) -> _WorkerHandle:
        idx = worker_of(slot, self.n_workers)
        with self._lock:
            w = self._workers[idx]
        if w is None or w.dead:
            raise WorkerUnavailableError(
                f"host worker {idx} for partition slot {slot} is "
                f"respawning; retry"
            )
        return w

    # -- host-path ops --

    def submit(self, slot: int, messages: list, pid=None, seq=None,
               timeout_s: float = 5.0, tctx=None) -> dict:
        """Validate + stamp + pack one produce batch on the owning
        worker. Returns {"pid", "seq", "chunks": [(lens, packed), ...]}
        (chunks are max_batch-sized row blocks). Raises
        WorkerUnavailableError (typed, retryable) when the worker is
        down, ValueError on a validation refusal."""
        # Pre-check BOTH directions against the per-frame cap (half the
        # ring): the request carries the raw payloads, the response the
        # slot_bytes-rounded packed blocks. An oversize batch must
        # never reach the ring push — a worker-side push failure kills
        # the worker, and the client's retry would re-kill each respawn.
        cap = self.ring_bytes // 2
        k = len(messages)
        req_bound = sum(map(len, messages)) + 8 * k + 256
        resp_bound = k * (self.slot_bytes + 16) + 256
        if req_bound > cap or resp_bound > cap:
            raise OversizeBatchError(
                f"{k}-message batch needs ~{max(req_bound, resp_bound)} "
                f"bytes against a {cap}-byte frame cap "
                f"(host_ring_bytes {self.ring_bytes}); falling back to "
                f"the in-process submit path"
            )
        op = {"op": "submit", "slot": int(slot), "msgs": list(messages)}
        if pid is not None:
            op["pid"] = int(pid)
            op["seq"] = int(seq if seq is not None else -1)
        if tctx is not None:
            op["tctx"] = tctx  # wire form: [trace_id, parent_span_id]
        resp = self._handle(slot).request(op, timeout_s)
        if not resp.get("ok"):
            raise ValueError(str(resp.get("why", "submit refused")))
        if self.spans is not None and resp.get("spans"):
            self.spans.ingest(resp["spans"])
        return resp

    def submit_raw(self, slot: int, frame, n_msgs: int, pid=None, seq=None,
                   timeout_s: float = 5.0) -> dict:
        """submit() from an UNDECODED client produce frame: the frame
        crosses the ring verbatim (scatter-gather, one copy into shared
        memory) and the owning worker performs its only full decode —
        the dispatcher contributed a scalar peek, not a decode→re-encode
        hop. `n_msgs` is the peeked message count (response-size bound);
        same refusal contract as submit()."""
        cap = self.ring_bytes // 2
        k = int(n_msgs)
        req_bound = len(frame) + 512
        resp_bound = k * (self.slot_bytes + 16) + 256
        if req_bound > cap or resp_bound > cap:
            raise OversizeBatchError(
                f"{k}-message raw frame needs ~{max(req_bound, resp_bound)} "
                f"bytes against a {cap}-byte frame cap "
                f"(host_ring_bytes {self.ring_bytes}); falling back to "
                f"the in-process submit path"
            )
        meta = {"op": "submit_raw", "slot": int(slot)}
        if pid is not None:
            meta["pid"] = int(pid)
            meta["seq"] = int(seq if seq is not None else -1)
        resp = self._handle(slot).request((meta, "frame", frame), timeout_s)
        if not resp.get("ok"):
            raise ValueError(str(resp.get("why", "submit refused")))
        return resp

    def read(self, slot: int, offset: int, max_msgs: Optional[int],
             timeout_s: float = 2.0) -> Optional[tuple[list, int]]:
        """Serve a consume read from the owning worker's settled
        mirror; None when the mirror cannot serve it (fall back to the
        engine read path) — including when the worker is down."""
        try:
            resp = self._handle(slot).request(
                {"op": "read", "slot": int(slot), "offset": int(offset),
                 "max": max_msgs},
                timeout_s,
            )
        except WorkerUnavailableError:
            return None
        if not resp.get("ok"):
            return None
        return list(resp["msgs"]), int(resp["end"])

    def publish(self, slot: int, base: int, rows) -> None:
        """Fire-and-forget settled-mirror push (settle thread). A drop
        (full queue, dead worker) is safe: the worker's contiguity
        check resets its window and reads fall back.

        The rows are published as a REFERENCE + range, not a copy: the
        frame is pre-split into (encoded header prefix, the row
        buffer) and ShmRing.push_parts writes both straight into
        shared memory — the broker mirror already holds these exact
        bytes (DataPlane._mirror_records), and the old path re-buffered
        them twice through codec.encode before the one copy that
        matters (byte parity pinned in tests/test_hostplane.py)."""
        if len(rows) + 256 > self.ring_bytes // 2:
            return  # frame would exceed the ring cap: drop, not kill
        idx = worker_of(slot, self.n_workers)
        with self._lock:
            w = self._workers[idx]
        if w is not None:
            prefix = codec.encode_dict_with_blob(
                {"op": "mirror", "slot": int(slot), "base": int(base)},
                "rows", rows,
            )
            w.post_parts([prefix, rows])

    def set_worker_pid(self, idx: int, pid: int,
                       gen: Optional[int] = None) -> None:
        """Install worker `idx`'s stamping pid (0 invalidates). `gen`
        fences the install to the generation the pid was REGISTERED
        for: a respawn between the caller's generation snapshot and
        this install must drop the pid, not hand an old generation's
        pid to a worker whose sequence counters restarted at zero
        (that collapses fresh batches as dedup replays: acked loss).
        The fence is dispatcher-side — a handle that respawned after
        the snapshot is a different object with a different gen, and a
        post to the OLD handle no-ops on its dead latch."""
        with self._lock:
            w = self._workers[idx]
            if w is None or (gen is not None and w.gen != gen):
                return
        w.post({"op": "pid", "pid": int(pid)})

    def generations(self) -> list[int]:
        with self._lock:
            return list(self._gens)

    def worker_pids(self) -> list[int]:
        """OS pids of the live worker subprocesses (bench CPU
        accounting; dead/respawning slots are skipped)."""
        with self._lock:
            workers = list(self._workers)
        return [w.proc.pid for w in workers
                if w is not None and not w.dead and w.proc.pid is not None]

    def stats(self, ping_timeout_s: float = 0.5) -> dict:
        """Liveness/occupancy snapshot (admin.stats `host_plane`)."""
        with self._lock:
            workers = list(self._workers)
        alive = 0
        served = 0
        occupancy = []
        for w in workers:
            if w is None or w.dead:
                occupancy.append(-1.0)
                continue
            alive += 1
            occupancy.append(round(w.occupancy(), 4))
            try:
                pong = w.request({"op": "ping"}, ping_timeout_s)
                served += int(pong.get("served", 0))
            except Exception:
                pass  # liveness snapshot: a stalled ping is not fatal
        return {
            "workers": self.n_workers,
            "alive": alive,
            "restarts": self._restarts,
            "served": served,
            "occupancy": occupancy,
        }
