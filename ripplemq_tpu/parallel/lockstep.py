"""Lockstep engine driver: one controller, N engine-worker processes.

Multi-controller JAX requires EVERY process in a jax.distributed mesh to
launch the SAME computations in the SAME order — collectives rendezvous
across processes. The broker architecture has ONE controller driving the
device program from host RPCs, so the other hosts run engine WORKERS:
the controller broadcasts each engine call's host inputs (tiny numpy
arrays) to every worker over the wire transport, then launches its own
copy; each worker replays the call on its process's shard of the global
mesh, and the collective completes across hosts. This is the distributed
communication backend's control side — data rides XLA collectives over
ICI/DCN (parallel.mesh), the call stream rides TCP. The reference's
equivalent control plane is Bolt RPC between per-host JRaft groups
(reference: mq-broker/src/main/java/metadata/raft/
PartitionRaftServer.java:83-93, BrokerRpcClient.java).

Ordering: the controller uses one pipelined TCP connection per worker
(in-order delivery) and stamps a sequence number; workers execute under
a lock, verifying the sequence. The controller fires the broadcast
BEFORE launching its local copy — workers may start first; the
collective rendezvous synchronizes everyone.

Failure: if a worker process dies mid-call, the controller's collective
blocks until jax.distributed's coordination-service heartbeat declares
the process dead and terminates the mesh — the same blast radius as
losing a host of a TPU pod slice. Controller failover (broker/
replication.py) then recovers the data plane from the committed-round
stream, exactly as for a single-host controller death.
"""

from __future__ import annotations

import threading
from typing import Any

import numpy as np

from ripplemq_tpu.obs.lockwitness import make_lock
from ripplemq_tpu.utils.logs import get_logger

log = get_logger("lockstep")

LOCKSTEP_TYPE = "engine.lockstep"


class LockstepSendError(RuntimeError):
    """A lockstep broadcast failed BEFORE any worker received the call
    (and before the local launch): the sequence was restored, no process
    diverged, and the plane remains fully usable — the failed round is
    simply retried. `retryable` is the marker DataPlane._fail_round maps
    to a NotCommittedError so producers see an ordinary retryable
    refusal instead of a transport stack trace."""

    retryable = True


# --------------------------------------------------------- wire marshalling

def enc_value(v) -> Any:
    """Encode host call arguments for the wire codec (which speaks None/
    bool/int/float/str/bytes/list/dict): numpy arrays and scalars become
    tagged dicts, tuples become tagged lists (so NamedTuple pytrees like
    ReplicaState survive), everything else passes through."""
    if isinstance(v, (np.ndarray, np.generic)):
        a = np.asarray(v)
        return {"__nd__": str(a.dtype), "shape": list(a.shape),
                "data": a.tobytes()}
    if isinstance(v, tuple):
        return {"__tuple__": [enc_value(x) for x in v]}
    if isinstance(v, list):
        return [enc_value(x) for x in v]
    if hasattr(v, "_fields"):  # NamedTuple pytree (e.g. StepInput)
        return {"__tuple__": [enc_value(x) for x in v]}
    return v


def dec_value(v) -> Any:
    if isinstance(v, dict) and "__nd__" in v:
        a = np.frombuffer(v["data"], dtype=np.dtype(v["__nd__"]))
        return a.reshape(v["shape"])
    if isinstance(v, dict) and "__tuple__" in v:
        return tuple(dec_value(x) for x in v["__tuple__"])
    if isinstance(v, list):
        return [dec_value(x) for x in v]
    return v


# --------------------------------------------------------------- controller

class LockstepController:
    """Wraps SpmdEngineFns: every engine call is broadcast to the worker
    set before the local launch. Presents the same callable surface as
    the wrapped fns (duck-typed for DataPlane)."""

    def __init__(self, inner, cfg, part_shards: int,
                 workers: list[str], client, rpc_timeout_s: float = 120.0):
        self._inner = inner
        self._workers = list(workers)
        self._client = client
        if getattr(client, "call_async", None) is None:
            raise ValueError(
                "lockstep needs a pipelining transport (call_async): the "
                "controller must launch its own collective WHILE workers "
                "replay, or the mesh rendezvous deadlocks"
            )
        self._timeout = rpc_timeout_s
        self._seq = 0
        self._lock = make_lock("LockstepController._lock")
        self.mesh = inner.mesh
        # Set (to a reason string) the first time a broadcast or replay
        # fails: the mesh is permanently out of lockstep — no later call
        # can succeed, and the broker reading this flag must surrender
        # the device program (abdication → standby promotion). Never
        # cleared: a broken controller builds a NEW plane, not this one.
        self.broken: str | None = None
        # Workers build their engine from this exact shape (no local op
        # to overlap: configure launches nothing on the mesh).
        with self._lock:
            # bools stay bools (fused_control/packed_writes) so the
            # worker rebuilds the EXACT EngineConfig — a mesh whose
            # processes disagree on the compiled program deadlocks.
            futs = self._send("configure", [
                {k: (bool(v) if isinstance(v, bool) else int(v))
                 for k, v in cfg.__dict__.items()},
                int(part_shards),
            ])
        self._check(futs)

    def _send(self, method: str, args: list) -> list:
        self._seq += 1
        req = {
            "type": LOCKSTEP_TYPE,
            "seq": self._seq,
            "method": method,
            "args": [enc_value(a) for a in args],
        }
        futs = []
        for addr in self._workers:
            try:
                futs.append((addr, self._client.call_async(addr, dict(req))))
            except Exception as e:
                if not futs:
                    # Nothing was dispatched: no worker ever saw this
                    # sequence number, so restoring it keeps the stream
                    # replayable — the failure is TRANSIENT (a dropped
                    # connection the next call re-establishes), not a
                    # lockstep break. Graceful degradation: the round
                    # fails retryably instead of condemning the plane.
                    self._seq -= 1
                    raise LockstepSendError(
                        f"lockstep send to {addr} failed before any "
                        f"dispatch: {type(e).__name__}: {e}"
                    ) from e
                # Partial dispatch: earlier workers WILL replay this seq,
                # later ones never got it — the mesh is out of lockstep
                # for good (the _call except path marks broken).
                raise
        return futs

    def _check(self, futs) -> None:
        for addr, fut in futs:
            resp = fut.result(timeout=self._timeout)
            if not resp.get("ok"):
                # The worker failed to replay: the mesh is now out of
                # lockstep — surface loudly (the controller's next
                # collective would hang until the coordination service
                # notices).
                raise RuntimeError(
                    f"lockstep worker {addr} failed: {resp.get('error')}"
                )

    def _call(self, method: str, args: list, local_fn):
        """Broadcast, run the local copy CONCURRENTLY with the workers'
        replay (the collective rendezvous needs every process inside the
        computation — waiting for acks first would deadlock), then check
        the acks. The lock spans send + local LAUNCH so the controller's
        computation order always matches the sequence order the workers
        replay in (a cross-thread inversion would rendezvous mismatched
        collectives)."""
        try:
            with self._lock:
                futs = self._send(method, args)
                result = local_fn()
        except LockstepSendError:
            # Pre-broadcast failure: _send restored the sequence and no
            # process (worker OR local) ran anything — the plane stays
            # healthy and the NEXT call may succeed. Do not set broken.
            raise
        except Exception as e:
            # Broadcast (or local launch) failed after the stream became
            # non-replayable (some worker holds a seq the others never
            # saw, or the local copy diverged): permanently broken. The
            # latch flips under the sequence lock (ownership lint,
            # PR 11): every engine entry point can reach this line, and
            # an unguarded write left the break diagnostic ordered by
            # nothing (error path — the extra acquire costs nothing).
            with self._lock:
                self.broken = f"{type(e).__name__}: {e}"
            raise
        try:
            self._check(futs)
        except Exception as e:
            # The local launch already ran — donated input buffers are
            # gone and `result` holds their replacement. Attach it so the
            # caller (DataPlane) can adopt the new state and fail loudly
            # with the lockstep-break diagnostic, instead of wedging every
            # subsequent engine call on donated-buffer errors.
            with self._lock:
                self.broken = f"{type(e).__name__}: {e}"
            e.lockstep_result = result
            raise
        return result

    # ---- engine surface (mirrors SpmdEngineFns) ----
    def init(self):
        return self._call("init", [], lambda: self._inner.init())

    def init_from(self, image):
        return self._call("init_from", [image],
                          lambda: self._inner.init_from(image))

    def step(self, state, inp, alive, quorum=None, trim=None):
        return self._call(
            "step", [inp, alive, quorum, trim],
            lambda: self._inner.step(state, inp, alive, quorum, trim),
        )

    def step_many(self, state, inputs, alive, quorum=None, trim=None):
        return self._call(
            "step_many", [inputs, alive, quorum, trim],
            lambda: self._inner.step_many(state, inputs, alive, quorum, trim),
        )

    def step_sparse(self, state, inp, entries_c, slot_ids, alive,
                    quorum=None, trim=None):
        return self._call(
            "step_sparse", [inp, entries_c, slot_ids, alive, quorum, trim],
            lambda: self._inner.step_sparse(state, inp, entries_c, slot_ids,
                                            alive, quorum, trim),
        )

    def step_many_sparse(self, state, inputs, entries_c, slot_ids, alive,
                         quorum=None, trim=None):
        return self._call(
            "step_many_sparse",
            [inputs, entries_c, slot_ids, alive, quorum, trim],
            lambda: self._inner.step_many_sparse(
                state, inputs, entries_c, slot_ids, alive, quorum, trim),
        )

    def vote(self, state, cand, cand_term, alive, quorum=None):
        return self._call(
            "vote", [cand, cand_term, alive, quorum],
            lambda: self._inner.vote(state, cand, cand_term, alive, quorum),
        )

    def read(self, state, replica, partition, offset):
        return self._call(
            "read", [replica, partition, offset],
            lambda: self._inner.read(state, replica, partition, offset),
        )

    def read_many(self, state, replicas, partitions, offsets):
        return self._call(
            "read_many", [replicas, partitions, offsets],
            lambda: self._inner.read_many(state, replicas, partitions,
                                          offsets),
        )

    def read_offset(self, state, replica, partition, consumer_slot):
        return self._call(
            "read_offset", [replica, partition, consumer_slot],
            lambda: self._inner.read_offset(state, replica, partition,
                                            consumer_slot),
        )

    def resync(self, state, src, dst, part_mask):
        return self._call(
            "resync", [src, dst, part_mask],
            lambda: self._inner.resync(state, src, dst, part_mask),
        )

    def fetch_state(self, state, field: str) -> np.ndarray:
        """Materialize one process-sharded state leaf on the host. The
        allgather is itself a global-mesh collective, so it must be
        broadcast like any other call — a bare np.asarray on the
        controller would hang waiting for the workers. Fused-control
        states serve the named scalars (log_end/current_term/commit) as
        ctrl-buffer views (core.state.FusedReplicaState properties) —
        the slice is along the unsharded K axis, and controller and
        workers launch the identical getattr, so the mesh stays in
        lockstep for it like any other computation."""

        def local():
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(
                getattr(state, field), tiled=True
            ))

        return self._call("fetch_state", [field], local)


# ------------------------------------------------------------------- worker

class LockstepWorker:
    """Replays the controller's engine-call stream on this process's
    shard of the global mesh. Wire handler for LOCKSTEP_TYPE requests
    (plug into a TcpServer dispatch)."""

    def __init__(self) -> None:
        self._lock = make_lock("LockstepWorker._lock")
        self._expected_seq = 1
        self._fns = None
        self._state = None

    def handle(self, req: dict) -> dict:
        try:
            with self._lock:
                seq = int(req["seq"])
                if seq != self._expected_seq:
                    return {"ok": False,
                            "error": f"lockstep break: got seq {seq}, "
                                     f"expected {self._expected_seq}"}
                self._execute(str(req["method"]),
                              [dec_value(a) for a in req["args"]])
                self._expected_seq += 1
            return {"ok": True}
        except Exception as e:  # report, don't kill the server thread
            log.warning("lockstep replay failed: %s: %s",
                        type(e).__name__, e)
            return {"ok": False,
                    "error": f"internal: {type(e).__name__}: {e}"}

    def _execute(self, method: str, args: list) -> None:
        if method == "configure":
            from ripplemq_tpu.core.config import EngineConfig
            from ripplemq_tpu.parallel.engine import make_spmd_fns
            from ripplemq_tpu.parallel.mesh import make_mesh

            cfg_dict, part_shards = args
            cfg = EngineConfig(**{
                k: (v if isinstance(v, bool) else int(v))
                for k, v in cfg_dict.items()
            })
            mesh = make_mesh(cfg.replicas, int(part_shards))
            self._fns = make_spmd_fns(cfg, mesh)
            self._cfg = cfg
            log.info("lockstep worker configured: %s over mesh %s",
                     cfg, dict(mesh.shape))
            return
        if self._fns is None:
            raise RuntimeError("lockstep worker not configured")
        fns = self._fns
        if method == "init":
            self._state = fns.init()
        elif method == "init_from":
            from ripplemq_tpu.core.state import ReplicaState

            self._state = fns.init_from(ReplicaState(*args[0]))
        elif method == "step":
            inp_t, alive, quorum, trim = args
            from ripplemq_tpu.core.state import StepInput

            self._state, _ = fns.step(self._state, StepInput(*inp_t),
                                      alive, quorum, trim)
        elif method == "step_many":
            inp_t, alive, quorum, trim = args
            from ripplemq_tpu.core.state import StepInput

            self._state, _ = fns.step_many(self._state, StepInput(*inp_t),
                                           alive, quorum, trim)
        elif method == "step_sparse":
            inp_t, entries_c, slot_ids, alive, quorum, trim = args
            from ripplemq_tpu.core.state import StepInput

            self._state, _ = fns.step_sparse(
                self._state, StepInput(*inp_t), entries_c, slot_ids,
                alive, quorum, trim)
        elif method == "step_many_sparse":
            inp_t, entries_c, slot_ids, alive, quorum, trim = args
            from ripplemq_tpu.core.state import StepInput

            self._state, _ = fns.step_many_sparse(
                self._state, StepInput(*inp_t), entries_c, slot_ids,
                alive, quorum, trim)
        elif method == "vote":
            cand, cand_term, alive, quorum = args
            self._state, _, _ = fns.vote(self._state, cand, cand_term,
                                         alive, quorum)
        elif method == "read":
            replica, partition, offset = args
            fns.read(self._state, replica, partition, offset)
        elif method == "read_many":
            replicas, partitions, offsets = args
            fns.read_many(self._state, replicas, partitions, offsets)
        elif method == "read_offset":
            replica, partition, cslot = args
            fns.read_offset(self._state, replica, partition, cslot)
        elif method == "resync":
            src, dst, mask = args
            self._state = fns.resync(self._state, src, dst, mask)
        elif method == "fetch_state":
            from jax.experimental import multihost_utils

            multihost_utils.process_allgather(
                getattr(self._state, str(args[0])), tiled=True
            )
        else:
            raise ValueError(f"unknown lockstep method {method!r}")
