"""Engine-worker process: one non-controller host of a multi-host mesh.

Run one per additional host (the controller broker runs on host 0 with
--coordinator/--engine-workers; see broker/__main__.py):

    python -m ripplemq_tpu.parallel.worker \
        --coordinator host0:9777 --num-hosts 2 --host-index 1 \
        --listen-port 9810

The worker starts its TCP endpoint FIRST (so the controller's first
lockstep broadcast always lands), then joins the jax.distributed mesh
(which blocks until every host arrives), then replays the controller's
engine-call stream (parallel.lockstep) until terminated. The engine
shape arrives in the controller's `configure` call — no shape flags
needed here — including the fused_control/packed_writes levers, so the
worker compiles the EXACT program (fused state layout included) the
controller launches; a mesh whose processes disagree on the compiled
program deadlocks at the first collective.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="python -m ripplemq_tpu.parallel.worker")
    ap.add_argument("--coordinator", required=True, help="host0's host:port")
    ap.add_argument("--num-hosts", type=int, required=True)
    ap.add_argument("--host-index", type=int, required=True)
    ap.add_argument("--listen-host", default="0.0.0.0")
    ap.add_argument("--listen-port", type=int, required=True)
    ap.add_argument("--local-devices", type=int, default=0,
                    help="force N virtual CPU devices (testing without "
                         "real chips); 0 = the platform's real devices")
    ap.add_argument("--log-level", default="INFO")
    args = ap.parse_args(argv)

    if args.local_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        flags = " ".join(
            f for f in flags.split()
            if "xla_force_host_platform_device_count" not in f
        )
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{args.local_devices}"
        ).strip()
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if args.local_devices:
        jax.config.update("jax_platforms", "cpu")

    from ripplemq_tpu.parallel.lockstep import LOCKSTEP_TYPE, LockstepWorker
    from ripplemq_tpu.parallel.mesh import init_distributed
    from ripplemq_tpu.utils.logs import configure_logging, get_logger
    from ripplemq_tpu.wire.transport import TcpServer

    configure_logging(args.log_level)
    log = get_logger("worker")

    worker = LockstepWorker()

    def dispatch(req: dict) -> dict:
        if req.get("type") == LOCKSTEP_TYPE:
            return worker.handle(req)
        return {"ok": False, "error": f"unknown request {req.get('type')!r}"}

    server = TcpServer(args.listen_host, args.listen_port, dispatch)
    server.start()  # listening BEFORE the mesh forms (see module doc)
    n = init_distributed(args.coordinator, args.num_hosts, args.host_index)
    log.info("engine worker %d/%d up: %d global devices, listening on %s:%d",
             args.host_index, args.num_hosts, n,
             args.listen_host, args.listen_port)
    print(f"WORKER_READY host={args.host_index} devices={n}", flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    try:
        while not stop.wait(timeout=1.0):
            pass
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
