"""Wire protocol + transports for the client/host edge.

The reference moves every RPC (client↔broker, broker↔broker, Raft
traffic) over Bolt TCP with Java serialization, dispatched by class name
(reference: mq-common request DTOs;
mq-broker/.../MessageAppendRequestProcessor.java:70-72 `interest()`).
Here the host edge is a compact self-describing binary codec over
length-prefixed frames with request-id pipelining, dispatched by a
`"type"` string — and, crucially, it carries ONLY control + payload
traffic between clients and brokers: the replica plane (AppendEntries,
quorum votes) does not ride this transport at all; it rides XLA
collectives on the device mesh (see ripplemq_tpu.parallel).

Two interchangeable transports:
- `InProcNetwork` — deterministic in-process fake for N-broker
  single-process tests with fault injection (drops, partitions, delays);
  the piece SURVEY.md §4 notes the reference never had.
- `TcpServer`/`TcpClient` — real sockets for multi-process clusters.
"""

from ripplemq_tpu.wire.codec import decode, encode, read_frame, write_frame
from ripplemq_tpu.wire.retry import (
    DeadlineExceeded,
    RetryPolicy,
    fatal_response_error,
)
from ripplemq_tpu.wire.transport import (
    InProcNetwork,
    RpcError,
    RpcTimeout,
    TcpClient,
    TcpServer,
    Transport,
)

__all__ = [
    "DeadlineExceeded",
    "RetryPolicy",
    "fatal_response_error",
    "decode",
    "encode",
    "read_frame",
    "write_frame",
    "InProcNetwork",
    "RpcError",
    "RpcTimeout",
    "TcpClient",
    "TcpServer",
    "Transport",
]
