"""Self-describing binary codec ("rb-enc") + frame IO.

Replaces the reference's double Java serialization (once at the Bolt RPC
layer, once inside Raft log entries — reference:
mq-broker/.../TopicsRequestProcessor.java:56-63) with a single compact
encoding. Message payload bytes pass through verbatim — no base64, no
string coercion.

Supported values: None, bool, int (64-bit signed), float, str, bytes,
list, dict[str, value]. Ints use a varint zig-zag; strings/bytes are
length-prefixed.

**Bulk-frame fast path.** A list whose elements are all bytes-like — the
shape of every produce/consume body and replication record batch — is
encoded as a PACKED VECTOR: one struct-packed u32 length table plus one
concatenated blob, instead of a tag + varint + copy per element through
the generic recursion. Decode slices the blob through a single
memoryview (each element is carved out of the frame body directly — no
intermediate buffer per element). The generic per-element encoding
remains fully supported and wire-compatible for every other value (and
for A/B: `encode(v, bulk=False)` forces it; both forms decode to the
same value).

Frame format on the socket:
    uint32 BE total length | uint64 BE request id | encoded body
Request ids let one connection pipeline many in-flight requests and match
responses out of order (the reference's Bolt invokeSync allows one
outstanding request per call — SURVEY.md §3.2 lists "no client
pipelining" among its throughput bottlenecks).
"""

from __future__ import annotations

import socket
import struct
import time

_NONE = b"n"
_TRUE = b"t"
_FALSE = b"f"
_INT = b"i"
_FLOAT = b"d"
_STR = b"s"
_BYTES = b"b"
_LIST = b"l"
_DICT = b"m"
_VEC = b"v"  # packed bytes vector: count | u32-LE length table | blob

MAX_FRAME = 64 * 1024 * 1024  # hard cap against corrupt/hostile lengths

_INT64_MIN, _INT64_MAX = -(2**63), 2**63 - 1

_BYTES_LIKE = (bytes, bytearray, memoryview)


def _write_varint(out: bytearray, n: int) -> None:
    # zig-zag then LEB128; the zig-zag is only correct within 64 bits, so
    # out-of-range ints must error rather than silently corrupt.
    if not _INT64_MIN <= n <= _INT64_MAX:
        raise OverflowError(f"int {n} outside the codec's 64-bit range")
    zz = (n << 1) ^ (n >> 63) if n < 0 else (n << 1)
    while True:
        b = zz & 0x7F
        zz >>= 7
        if zz:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_varint(buf: memoryview, pos: int) -> tuple[int, int]:
    shift = 0
    zz = 0
    while True:
        b = buf[pos]
        pos += 1
        zz |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")
    return (zz >> 1) ^ -(zz & 1), pos


def _encode_into(out: bytearray, v, bulk: bool) -> None:
    if v is None:
        out += _NONE
    elif v is True:
        out += _TRUE
    elif v is False:
        out += _FALSE
    elif isinstance(v, int):
        out += _INT
        _write_varint(out, v)
    elif isinstance(v, float):
        out += _FLOAT
        out += struct.pack(">d", v)
    elif isinstance(v, str):
        raw = v.encode("utf-8")
        out += _STR
        _write_varint(out, len(raw))
        out += raw
    elif isinstance(v, _BYTES_LIKE):
        if type(v) is memoryview:
            v = _flat_view(v)
        out += _BYTES
        _write_varint(out, len(v))
        out += v
    elif isinstance(v, (list, tuple)):
        if bulk and v and all(isinstance(x, _BYTES_LIKE) for x in v):
            _encode_vector(out, v)
            return
        out += _LIST
        _write_varint(out, len(v))
        for item in v:
            _encode_into(out, item, bulk)
    elif isinstance(v, dict):
        out += _DICT
        _write_varint(out, len(v))
        for k, item in v.items():
            if not isinstance(k, str):
                raise TypeError(f"dict keys must be str, got {type(k).__name__}")
            raw = k.encode("utf-8")
            _write_varint(out, len(raw))
            out += raw
            _encode_into(out, item, bulk)
    else:
        raise TypeError(f"unencodable type {type(v).__name__}")


def _flat_view(v: memoryview):
    """A strided or multi-dimensional memoryview can't concatenate into
    the output buffer (and len() would count first-axis items, not
    bytes) — flatten those through one bytes() copy; the common flat
    case passes through untouched."""
    if v.contiguous and v.ndim == 1 and v.itemsize == 1:
        return v
    return bytes(v)


def _encode_vector(out: bytearray, items) -> None:
    """list[bytes] as one length table + one concatenated blob. Element
    lengths are u32 (any element that could overflow one also overflows
    the 64 MB frame cap long before)."""
    items = [_flat_view(x) if type(x) is memoryview else x for x in items]
    out += _VEC
    _write_varint(out, len(items))
    out += struct.pack(f"<{len(items)}I", *map(len, items))
    for x in items:
        out += x


# --- codec telemetry --------------------------------------------------------
# PROCESS-GLOBAL frame counters (the codec is stateless module functions
# shared by every transport in the process, so these aggregate across
# brokers of an in-proc cluster — admin.metrics labels them as such).
# Plain-int adds, unlocked: same accepted-race contract as obs.metrics
# counters. `enable_stats(False)` removes even the two clock reads per
# frame (the ClusterConfig.obs A/B knob reaches here through the broker).


class _CodecStats:
    __slots__ = ("encode_frames", "encode_bytes", "encode_ns",
                 "decode_frames", "decode_bytes", "decode_ns")

    def __init__(self) -> None:
        self.encode_frames = 0
        self.encode_bytes = 0
        self.encode_ns = 0
        self.decode_frames = 0
        self.decode_bytes = 0
        self.decode_ns = 0


_STATS = _CodecStats()
_STATS_ENABLED = True


def enable_stats(on: bool) -> None:
    global _STATS_ENABLED
    _STATS_ENABLED = bool(on)


def codec_stats() -> dict:
    """Wire-encodable snapshot (avg_us derived so rates survive the
    racy-read contract gracefully)."""
    s = _STATS
    return {
        "enabled": _STATS_ENABLED,
        "encode_frames": s.encode_frames,
        "encode_bytes": s.encode_bytes,
        "encode_avg_us": round(s.encode_ns / s.encode_frames / 1e3, 2)
        if s.encode_frames else 0,
        "decode_frames": s.decode_frames,
        "decode_bytes": s.decode_bytes,
        "decode_avg_us": round(s.decode_ns / s.decode_frames / 1e3, 2)
        if s.decode_frames else 0,
    }


def encode(v, bulk: bool = True) -> bytes:
    """Encode one value. `bulk=False` disables the packed-vector fast
    path (generic per-element encoding for bytes lists) — the legacy
    wire form, kept for A/B and interop tests; both decode identically."""
    stats = _STATS_ENABLED
    t0 = time.perf_counter_ns() if stats else 0
    out = bytearray()
    _encode_into(out, v, bulk)
    raw = bytes(out)
    if stats:
        s = _STATS
        s.encode_ns += time.perf_counter_ns() - t0
        s.encode_frames += 1
        s.encode_bytes += len(raw)
    return raw


def encode_dict_with_blob(meta: dict, key: str, blob) -> bytes:
    """PREFIX bytes such that `prefix + blob` is byte-identical to
    `encode({**meta, key: bytes(blob)})` with the blob entry LAST.

    The scatter-gather half of the settled-mirror publish path
    (parallel/hostplane.py): the mirror rows already live in the
    broker's host mirror, and `encode()` would copy them TWICE more
    (bytearray append + the final bytes() snapshot) just to prepend a
    ~40-byte header. With this prefix the caller hands
    `[prefix, rows]` to ShmRing.push_parts and the payload is touched
    exactly once — the copy into shared memory. decode() cannot tell
    the two forms apart (tests/test_shmring.py pins byte parity).

    Stats account the LOGICAL frame (prefix + blob), mirroring
    encode()."""
    stats = _STATS_ENABLED
    t0 = time.perf_counter_ns() if stats else 0
    if key in meta:
        raise ValueError(f"blob key {key!r} duplicates a meta key")
    if type(blob) is memoryview:
        blob = _flat_view(blob)
    out = bytearray()
    out += _DICT
    _write_varint(out, len(meta) + 1)
    for k, item in meta.items():
        if not isinstance(k, str):
            raise TypeError(f"dict keys must be str, got {type(k).__name__}")
        raw = k.encode("utf-8")
        _write_varint(out, len(raw))
        out += raw
        _encode_into(out, item, True)
    raw = key.encode("utf-8")
    _write_varint(out, len(raw))
    out += raw
    out += _BYTES
    _write_varint(out, len(blob))
    prefix = bytes(out)
    if stats:
        s = _STATS
        s.encode_ns += time.perf_counter_ns() - t0
        s.encode_frames += 1
        s.encode_bytes += len(prefix) + len(blob)
    return prefix


def _read_length(buf: memoryview, pos: int) -> tuple[int, int]:
    """Decode a length/count prefix, rejecting malformed frames cleanly: a
    negative decoded length would make buf[pos:pos+n] silently yield an
    empty slice and move pos BACKWARDS, and an oversized one would loop on
    garbage — both must be decode errors, not confusing downstream ones."""
    n, pos = _read_varint(buf, pos)
    if n < 0:
        raise ValueError(f"negative length {n} at {pos}")
    if n > len(buf) - pos:
        raise ValueError(f"length {n} at {pos} exceeds remaining buffer")
    return n, pos


def _decode_at(buf: memoryview, pos: int):
    tag = bytes(buf[pos : pos + 1])
    pos += 1
    if tag == _NONE:
        return None, pos
    if tag == _TRUE:
        return True, pos
    if tag == _FALSE:
        return False, pos
    if tag == _INT:
        return _read_varint(buf, pos)
    if tag == _FLOAT:
        return struct.unpack(">d", buf[pos : pos + 8])[0], pos + 8
    if tag == _STR:
        n, pos = _read_length(buf, pos)
        return str(buf[pos : pos + n], "utf-8"), pos + n
    if tag == _BYTES:
        n, pos = _read_length(buf, pos)
        return bytes(buf[pos : pos + n]), pos + n
    if tag == _VEC:
        n, pos = _read_length(buf, pos)
        if 4 * n > len(buf) - pos:
            raise ValueError(f"vector table of {n} at {pos} exceeds buffer")
        lens = struct.unpack_from(f"<{n}I", buf, pos)
        pos += 4 * n
        if sum(lens) > len(buf) - pos:
            raise ValueError(f"vector blob at {pos} exceeds remaining buffer")
        items = []
        for ln in lens:
            # One bytes() per element straight off the frame's memoryview
            # — the single unavoidable copy; no intermediate slicing.
            items.append(bytes(buf[pos : pos + ln]))
            pos += ln
        return items, pos
    if tag == _LIST:
        n, pos = _read_length(buf, pos)
        items = []
        for _ in range(n):
            item, pos = _decode_at(buf, pos)
            items.append(item)
        return items, pos
    if tag == _DICT:
        n, pos = _read_length(buf, pos)
        d = {}
        for _ in range(n):
            klen, pos = _read_length(buf, pos)
            k = str(buf[pos : pos + klen], "utf-8")
            pos += klen
            d[k], pos = _decode_at(buf, pos)
        return d, pos
    raise ValueError(f"bad tag byte {tag!r} at {pos - 1}")


def _skip_at(buf: memoryview, pos: int) -> int:
    """Advance past one encoded value WITHOUT materializing it — the
    raw-dispatch peek's workhorse (a packed message vector is skipped
    by its length table alone; no per-element bytes() copies)."""
    tag = bytes(buf[pos : pos + 1])
    pos += 1
    if tag in (_NONE, _TRUE, _FALSE):
        return pos
    if tag == _INT:
        _, pos = _read_varint(buf, pos)
        return pos
    if tag == _FLOAT:
        return pos + 8
    if tag in (_STR, _BYTES):
        n, pos = _read_length(buf, pos)
        return pos + n
    if tag == _VEC:
        n, pos = _read_length(buf, pos)
        if 4 * n > len(buf) - pos:
            raise ValueError(f"vector table of {n} at {pos} exceeds buffer")
        lens = struct.unpack_from(f"<{n}I", buf, pos)
        pos += 4 * n
        total = sum(lens)
        if total > len(buf) - pos:
            raise ValueError(f"vector blob at {pos} exceeds remaining buffer")
        return pos + total
    if tag == _LIST:
        n, pos = _read_length(buf, pos)
        for _ in range(n):
            pos = _skip_at(buf, pos)
        return pos
    if tag == _DICT:
        n, pos = _read_length(buf, pos)
        for _ in range(n):
            klen, pos = _read_length(buf, pos)
            pos += klen
            pos = _skip_at(buf, pos)
        return pos
    raise ValueError(f"bad tag byte {tag!r} at {pos - 1}")


def peek_fields(raw, want) -> "dict | None":
    """Decode ONLY the requested top-level fields of an encoded dict,
    structurally skipping everything else (no payload materialization).

    The raw-frame dispatch peek (broker/server.py _raw_produce): the
    accept path needs the routing scalars — type, topic, partition, the
    idempotence pid/seq — to route an undecoded produce frame to its
    owning host worker, which then performs the frame's single full
    decode. Requested fields that hold a packed vector or list decode
    to their ELEMENT COUNT (int), bytes values to their byte length —
    enough for admission/size checks without touching the blob.

    Returns None for anything that is not a well-formed encoded dict:
    the caller falls back to the ordinary decode path, which produces
    the canonical error."""
    buf = memoryview(raw)
    try:
        if bytes(buf[0:1]) != _DICT:
            return None
        n, pos = _read_length(buf, 1)
        out: dict = {}
        for _ in range(n):
            klen, pos = _read_length(buf, pos)
            k = str(buf[pos : pos + klen], "utf-8")
            pos += klen
            if k in want:
                tag = bytes(buf[pos : pos + 1])
                if tag in (_VEC, _LIST):
                    out[k], _ = _read_length(buf, pos + 1)
                    pos = _skip_at(buf, pos)
                elif tag == _BYTES:
                    ln, p2 = _read_length(buf, pos + 1)
                    out[k] = ln
                    pos = p2 + ln
                else:
                    out[k], pos = _decode_at(buf, pos)
            else:
                pos = _skip_at(buf, pos)
        if pos != len(buf):
            return None
        return out
    except (ValueError, IndexError, struct.error, UnicodeDecodeError):
        return None


def decode(raw: bytes | memoryview):
    stats = _STATS_ENABLED
    t0 = time.perf_counter_ns() if stats else 0
    v, pos = _decode_at(memoryview(raw), 0)
    if pos != len(raw):
        raise ValueError(f"trailing bytes after value ({pos} != {len(raw)})")
    if stats:
        s = _STATS
        s.decode_ns += time.perf_counter_ns() - t0
        s.decode_frames += 1
        s.decode_bytes += len(raw)
    return v


# --- frame IO ---------------------------------------------------------------

_HEADER = struct.Struct(">IQ")  # length (body only), request id

# Below this, header+body concatenate into one send (the copy is cheaper
# than a second syscall); at or above, the body is sent as its own
# sendall so a multi-megabyte replication frame is never copied again
# just to prepend 12 bytes.
_SPLIT_SEND_BYTES = 64 * 1024


def write_frame(sock: socket.socket, req_id: int, body: bytes) -> None:
    header = _HEADER.pack(len(body), req_id)
    if len(body) < _SPLIT_SEND_BYTES:
        sock.sendall(header + body)
    else:
        sock.sendall(header)
        sock.sendall(body)


def _read_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(n - got)
        if not chunk:
            raise ConnectionError("socket closed mid-frame")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def read_frame(sock: socket.socket) -> tuple[int, bytes]:
    """Read one frame; returns (request id, body). Raises ConnectionError
    on EOF, ValueError on an oversized length (corruption guard)."""
    header = _read_exact(sock, _HEADER.size)
    length, req_id = _HEADER.unpack(header)
    if length > MAX_FRAME:
        raise ValueError(f"frame length {length} exceeds cap {MAX_FRAME}")
    return req_id, _read_exact(sock, length)
