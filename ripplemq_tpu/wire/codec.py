"""Self-describing binary codec ("rb-enc") + frame IO.

Replaces the reference's double Java serialization (once at the Bolt RPC
layer, once inside Raft log entries — reference:
mq-broker/.../TopicsRequestProcessor.java:56-63) with a single compact
encoding. Message payload bytes pass through verbatim — no base64, no
string coercion.

Supported values: None, bool, int (64-bit signed), float, str, bytes,
list, dict[str, value]. Ints use a varint zig-zag; strings/bytes are
length-prefixed.

Frame format on the socket:
    uint32 BE total length | uint64 BE request id | encoded body
Request ids let one connection pipeline many in-flight requests and match
responses out of order (the reference's Bolt invokeSync allows one
outstanding request per call — SURVEY.md §3.2 lists "no client
pipelining" among its throughput bottlenecks).
"""

from __future__ import annotations

import io
import socket
import struct

_NONE = b"n"
_TRUE = b"t"
_FALSE = b"f"
_INT = b"i"
_FLOAT = b"d"
_STR = b"s"
_BYTES = b"b"
_LIST = b"l"
_DICT = b"m"

MAX_FRAME = 64 * 1024 * 1024  # hard cap against corrupt/hostile lengths


_INT64_MIN, _INT64_MAX = -(2**63), 2**63 - 1


def _write_varint(out: io.BytesIO, n: int) -> None:
    # zig-zag then LEB128; the zig-zag is only correct within 64 bits, so
    # out-of-range ints must error rather than silently corrupt.
    if not _INT64_MIN <= n <= _INT64_MAX:
        raise OverflowError(f"int {n} outside the codec's 64-bit range")
    zz = (n << 1) ^ (n >> 63) if n < 0 else (n << 1)
    while True:
        b = zz & 0x7F
        zz >>= 7
        if zz:
            out.write(bytes((b | 0x80,)))
        else:
            out.write(bytes((b,)))
            return


def _read_varint(buf: memoryview, pos: int) -> tuple[int, int]:
    shift = 0
    zz = 0
    while True:
        b = buf[pos]
        pos += 1
        zz |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")
    return (zz >> 1) ^ -(zz & 1), pos


def _encode_into(out: io.BytesIO, v) -> None:
    if v is None:
        out.write(_NONE)
    elif v is True:
        out.write(_TRUE)
    elif v is False:
        out.write(_FALSE)
    elif isinstance(v, int):
        out.write(_INT)
        _write_varint(out, v)
    elif isinstance(v, float):
        out.write(_FLOAT)
        out.write(struct.pack(">d", v))
    elif isinstance(v, str):
        raw = v.encode("utf-8")
        out.write(_STR)
        _write_varint(out, len(raw))
        out.write(raw)
    elif isinstance(v, (bytes, bytearray, memoryview)):
        raw = bytes(v)
        out.write(_BYTES)
        _write_varint(out, len(raw))
        out.write(raw)
    elif isinstance(v, (list, tuple)):
        out.write(_LIST)
        _write_varint(out, len(v))
        for item in v:
            _encode_into(out, item)
    elif isinstance(v, dict):
        out.write(_DICT)
        _write_varint(out, len(v))
        for k, item in v.items():
            if not isinstance(k, str):
                raise TypeError(f"dict keys must be str, got {type(k).__name__}")
            raw = k.encode("utf-8")
            _write_varint(out, len(raw))
            out.write(raw)
            _encode_into(out, item)
    else:
        raise TypeError(f"unencodable type {type(v).__name__}")


def encode(v) -> bytes:
    out = io.BytesIO()
    _encode_into(out, v)
    return out.getvalue()


def _read_length(buf: memoryview, pos: int) -> tuple[int, int]:
    """Decode a length/count prefix, rejecting malformed frames cleanly: a
    negative decoded length would make buf[pos:pos+n] silently yield an
    empty slice and move pos BACKWARDS, and an oversized one would loop on
    garbage — both must be decode errors, not confusing downstream ones."""
    n, pos = _read_varint(buf, pos)
    if n < 0:
        raise ValueError(f"negative length {n} at {pos}")
    if n > len(buf) - pos:
        raise ValueError(f"length {n} at {pos} exceeds remaining buffer")
    return n, pos


def _decode_at(buf: memoryview, pos: int):
    tag = bytes(buf[pos : pos + 1])
    pos += 1
    if tag == _NONE:
        return None, pos
    if tag == _TRUE:
        return True, pos
    if tag == _FALSE:
        return False, pos
    if tag == _INT:
        return _read_varint(buf, pos)
    if tag == _FLOAT:
        return struct.unpack(">d", buf[pos : pos + 8])[0], pos + 8
    if tag == _STR:
        n, pos = _read_length(buf, pos)
        return str(buf[pos : pos + n], "utf-8"), pos + n
    if tag == _BYTES:
        n, pos = _read_length(buf, pos)
        return bytes(buf[pos : pos + n]), pos + n
    if tag == _LIST:
        n, pos = _read_length(buf, pos)
        items = []
        for _ in range(n):
            item, pos = _decode_at(buf, pos)
            items.append(item)
        return items, pos
    if tag == _DICT:
        n, pos = _read_length(buf, pos)
        d = {}
        for _ in range(n):
            klen, pos = _read_length(buf, pos)
            k = str(buf[pos : pos + klen], "utf-8")
            pos += klen
            d[k], pos = _decode_at(buf, pos)
        return d, pos
    raise ValueError(f"bad tag byte {tag!r} at {pos - 1}")


def decode(raw: bytes | memoryview):
    v, pos = _decode_at(memoryview(raw), 0)
    if pos != len(raw):
        raise ValueError(f"trailing bytes after value ({pos} != {len(raw)})")
    return v


# --- frame IO ---------------------------------------------------------------

_HEADER = struct.Struct(">IQ")  # length (body only), request id


def write_frame(sock: socket.socket, req_id: int, body: bytes) -> None:
    sock.sendall(_HEADER.pack(len(body), req_id) + body)


def _read_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(n - got)
        if not chunk:
            raise ConnectionError("socket closed mid-frame")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def read_frame(sock: socket.socket) -> tuple[int, bytes]:
    """Read one frame; returns (request id, body). Raises ConnectionError
    on EOF, ValueError on an oversized length (corruption guard)."""
    header = _read_exact(sock, _HEADER.size)
    length, req_id = _HEADER.unpack(header)
    if length > MAX_FRAME:
        raise ValueError(f"frame length {length} exceeds cap {MAX_FRAME}")
    return req_id, _read_exact(sock, length)
