"""RPC transports: deterministic in-process fake + real TCP sockets.

Both present the same tiny surface: a server side binds an address to a
`handler(request: dict) -> dict`, a client side does `call(addr, request)`.
Handlers answer `{"ok": True, ...}` on success and
`{"ok": False, "error": msg}` on application errors; transport-level
failures raise `RpcError` / `RpcTimeout`.

The reference's counterpart is one Bolt RPC server per broker with five
registered processors and sync `invokeSync` clients (reference:
mq-broker/.../TopicsRaftServer.java:106-120,
mq-common/.../MetadataClient.java:27,63-69). Differences by design:

- `InProcNetwork` exists for N-broker single-process tests with fault
  injection (node down, link partition) — the deterministic harness
  SURVEY.md §4 calls for; the reference could only test multi-broker
  behavior inside docker-compose.
- `TcpClient` pipelines: frames carry request ids, many calls can be in
  flight per connection (the reference is strictly one-at-a-time).
"""

from __future__ import annotations

import itertools
import socket
import threading
import time
# On Python < 3.11 concurrent.futures.TimeoutError is NOT the builtin
# TimeoutError, so Future.result timeouts must be caught as both.
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeoutError
from typing import Callable, Optional

from ripplemq_tpu.obs.lockwitness import make_lock

from ripplemq_tpu.wire import codec

Handler = Callable[[dict], dict]


class RpcError(Exception):
    """Transport-level RPC failure (connect refused, peer down, ...)."""


class RpcTimeout(RpcError):
    """No response within the deadline (network partition, dead peer)."""


class Transport:
    """Client-side transport interface."""

    def call(self, addr: str, request: dict, timeout: float = 3.0) -> dict:
        raise NotImplementedError

    def close(self) -> None:  # pragma: no cover - trivial default
        pass


# ---------------------------------------------------------------------------
# In-process fake network
# ---------------------------------------------------------------------------

class InProcNetwork:
    """Deterministic in-process network: handlers keyed by address string.

    Fault injection:
      - `set_down(addr)` / `set_up(addr)`: node crash — calls raise RpcError.
      - `block(a, b)` / `unblock(a, b)`: symmetric link partition between
        two endpoint addresses — calls raise RpcTimeout (a partition looks
        like silence, not a refusal).
      - `block_oneway(src, dst)` / `unblock_oneway`: ASYMMETRIC partition
        — only src→dst requests vanish; dst can still reach src. The
        classic half-open link that symmetric partitions cannot express
        (a leader that can send heartbeats but never hear acks).
      - `drop_next(src, dst, n)`: drop the next n requests on a link —
        exercises retry paths deterministically.
      - `dup_next(src, dst, n)`: deliver the next n requests on a link
        TWICE (handler runs twice; the first response is discarded) —
        exercises handler idempotence under at-least-once delivery.
      - `delay_next(src, dst, n, delay_s)`: stall the next n requests by
        `delay_s` on the caller's thread before the handler runs — a slow
        link that reorders traffic relative to other links.

    Calls run the handler synchronously on the caller's thread: no real
    concurrency is introduced by the network itself, so test interleavings
    are exactly the interleavings the test writes.
    """

    def __init__(self) -> None:
        self._handlers: dict[str, Handler] = {}
        self._down: set[str] = set()
        self._blocked: set[frozenset[str]] = set()
        self._blocked_oneway: set[tuple[str, str]] = set()
        self._drops: dict[tuple[str, str], int] = {}
        self._dups: dict[tuple[str, str], int] = {}
        self._delays: dict[tuple[str, str], tuple[int, float]] = {}
        self._lock = make_lock("InProcNetwork._lock")
        self.calls: list[tuple[str, str, str]] = []  # (src, dst, type) trace
        # Duplications actually DELIVERED (handler ran twice) — distinct
        # from charges consumed by requests that also hit a block/drop.
        # The chaos checker keys its exactly-once suspension on this.
        self.dups_applied = 0

    # -- server side --
    def register(self, addr: str, handler: Handler) -> None:
        with self._lock:
            self._handlers[addr] = handler

    def unregister(self, addr: str) -> None:
        with self._lock:
            self._handlers.pop(addr, None)

    # -- fault injection --
    def set_down(self, addr: str) -> None:
        with self._lock:
            self._down.add(addr)

    def set_up(self, addr: str) -> None:
        with self._lock:
            self._down.discard(addr)

    def block(self, a: str, b: str) -> None:
        with self._lock:
            self._blocked.add(frozenset((a, b)))

    def unblock(self, a: str, b: str) -> None:
        with self._lock:
            self._blocked.discard(frozenset((a, b)))

    def block_oneway(self, src: str, dst: str) -> None:
        with self._lock:
            self._blocked_oneway.add((src, dst))

    def unblock_oneway(self, src: str, dst: str) -> None:
        with self._lock:
            self._blocked_oneway.discard((src, dst))

    def heal(self) -> None:
        with self._lock:
            self._blocked.clear()
            self._blocked_oneway.clear()
            self._down.clear()
            self._drops.clear()
            self._dups.clear()
            self._delays.clear()

    def drop_next(self, src: str, dst: str, n: int = 1) -> None:
        with self._lock:
            self._drops[(src, dst)] = self._drops.get((src, dst), 0) + n

    def dup_next(self, src: str, dst: str, n: int = 1) -> None:
        with self._lock:
            self._dups[(src, dst)] = self._dups.get((src, dst), 0) + n

    def delay_next(self, src: str, dst: str, n: int = 1,
                   delay_s: float = 0.05) -> None:
        with self._lock:
            left, _ = self._delays.get((src, dst), (0, 0.0))
            self._delays[(src, dst)] = (left + n, float(delay_s))

    # -- client side --
    def client(self, src_addr: str = "client") -> "InProcClient":
        return InProcClient(self, src_addr)

    def deliver(self, src: str, dst: str, request: dict, timeout: float) -> dict:
        with self._lock:
            handler = self._handlers.get(dst)
            down = dst in self._down or src in self._down
            blocked = (frozenset((src, dst)) in self._blocked
                       or (src, dst) in self._blocked_oneway)
            pending_drops = self._drops.get((src, dst), 0)
            if pending_drops:
                self._drops[(src, dst)] = pending_drops - 1
            dup = 0
            pending_dups = self._dups.get((src, dst), 0)
            if pending_dups:
                self._dups[(src, dst)] = pending_dups - 1
                dup = 1
            delay_s = 0.0
            pending_delays, d = self._delays.get((src, dst), (0, 0.0))
            if pending_delays:
                self._delays[(src, dst)] = (pending_delays - 1, d)
                delay_s = d
            self.calls.append((src, dst, str(request.get("type"))))
        if handler is None or down:
            raise RpcError(f"{dst}: connection refused")
        if blocked or pending_drops:
            raise RpcTimeout(f"{src}->{dst}: timed out after {timeout}s")
        if delay_s > 0:
            # Synchronous by design: the slow link stalls the CALLER, the
            # same head-of-line effect a real slow socket produces.
            time.sleep(delay_s)
        # Round-trip through the codec so in-proc tests exercise the same
        # encoding constraints as real sockets (no sharing of mutables).
        wire_req = codec.decode(codec.encode(request))
        try:
            resp = handler(wire_req)
            if dup:
                # At-least-once delivery: the handler sees the request
                # again (fresh decode — no shared mutables between the
                # two executions); only the LAST response reaches the
                # caller, like a client retry whose first response was
                # lost in flight.
                resp = handler(codec.decode(codec.encode(request)))
                with self._lock:
                    self.dups_applied += 1
        except Exception as e:  # handler bug → application error, not crash
            resp = {"ok": False, "error": f"internal: {type(e).__name__}: {e}"}
        return codec.decode(codec.encode(resp))


class InProcClient(Transport):
    def __init__(self, net: InProcNetwork, src_addr: str) -> None:
        self._net = net
        self.src_addr = src_addr

    def call(self, addr: str, request: dict, timeout: float = 3.0) -> dict:
        return self._net.deliver(self.src_addr, addr, request, timeout)

    def call_async(self, addr: str, request: dict) -> Future:
        """Uniform pipelining surface: the in-proc network is
        synchronous BY DESIGN (deterministic interleavings), so this
        executes inline and returns an already-resolved future. Callers
        written against the async surface — windowed producers, the
        consumer readahead — then run unchanged on in-proc clusters
        without anyone burning a pool thread around a sync call."""
        fut: Future = Future()
        try:
            fut.set_result(self.call(addr, request))
        except Exception as e:
            fut.set_exception(e)
        return fut


# ---------------------------------------------------------------------------
# TCP
# ---------------------------------------------------------------------------

class TcpServer:
    """Length-prefixed-frame TCP server with a worker pool.

    One acceptor thread; one reader thread per connection; handlers run on
    a shared pool so a slow request (e.g. an append waiting on its device
    round) does not stall the connection's other pipelined requests.
    """

    def __init__(
        self,
        host: str,
        port: int,
        handler: Handler,
        workers: int = 16,
        raw_handler: Optional[Callable[[bytes], Optional[dict]]] = None,
    ) -> None:
        self._handler = handler
        # Raw-frame dispatch hook: sees the UNDECODED body before the
        # codec runs and may answer the request itself (the broker's
        # produce fast path peeks routing scalars and ships the frame
        # to the owning host worker, which performs the only decode).
        # Returning None falls through to the ordinary decode path —
        # the hook must never raise for "not mine".
        self._raw_handler = raw_handler
        self._sock = socket.create_server((host, port), reuse_port=False)
        self._sock.settimeout(0.2)
        self.host, self.port = self._sock.getsockname()[:2]
        self._pool = ThreadPoolExecutor(max_workers=workers)
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._conns: set[socket.socket] = set()
        self._lock = make_lock("TcpServer._lock")

    def start(self) -> None:
        t = threading.Thread(target=self._accept_loop, daemon=True, name="tcp-accept")
        t.start()
        self._threads.append(t)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns.add(conn)
            # Daemon reader thread per connection; deliberately untracked —
            # it exits when the socket dies, and stop() closes all sockets.
            threading.Thread(
                target=self._conn_loop, args=(conn,), daemon=True, name="tcp-conn"
            ).start()

    def _conn_loop(self, conn: socket.socket) -> None:
        write_lock = threading.Lock()
        try:
            while not self._stop.is_set():
                try:
                    req_id, body = codec.read_frame(conn)
                except (ConnectionError, ValueError, OSError):
                    return
                self._pool.submit(self._handle_one, conn, write_lock, req_id, body)
        finally:
            with self._lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _handle_one(self, conn, write_lock, req_id: int, body: bytes) -> None:
        try:
            resp = (self._raw_handler(body)
                    if self._raw_handler is not None else None)
            if resp is None:
                request = codec.decode(body)
                if not isinstance(request, dict):
                    raise ValueError("request must be a dict")
                resp = self._handler(request)
        except Exception as e:
            resp = {"ok": False, "error": f"internal: {type(e).__name__}: {e}"}
        try:
            with write_lock:
                codec.write_frame(conn, req_id, codec.encode(resp))
        except OSError:
            pass  # client went away; nothing to do

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        self._pool.shutdown(wait=False)


class _Conn:
    """One pooled client connection with a reader thread matching request
    ids to futures (pipelining)."""

    def __init__(self, addr: str, connect_timeout: float) -> None:
        host, port_s = addr.rsplit(":", 1)
        self.sock = socket.create_connection((host, int(port_s)), timeout=connect_timeout)
        self.sock.settimeout(None)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.write_lock = make_lock("_Conn.write_lock")
        self.pending: dict[int, Future] = {}
        self.pending_lock = make_lock("_Conn.pending_lock")
        self.dead = False
        self.reader = threading.Thread(target=self._read_loop, daemon=True,
                                       name=f"tcp-client-{addr}")
        self.reader.start()

    def _read_loop(self) -> None:
        try:
            while True:
                req_id, body = codec.read_frame(self.sock)
                with self.pending_lock:
                    fut = self.pending.pop(req_id, None)
                if fut is not None and not fut.cancelled():
                    try:
                        fut.set_result(codec.decode(body))
                    except Exception as e:
                        fut.set_exception(RpcError(f"bad response frame: {e}"))
        except (ConnectionError, ValueError, OSError) as e:
            self._fail_all(RpcError(f"connection lost: {e}"))

    def _fail_all(self, exc: Exception) -> None:
        # The dead latch flips INSIDE pending_lock (ownership lint,
        # PR 11): send() checks it under the same lock, so every future
        # either sees dead (refused) or sits in the dict this swap
        # takes — a latch flipped outside the critical section leaves
        # that pairing to the GIL's mercy.
        with self.pending_lock:
            self.dead = True
            pending, self.pending = self.pending, {}
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(exc)
        try:
            self.sock.close()
        except OSError:
            pass

    def send(self, req_id: int, body: bytes) -> Future:
        fut: Future = Future()
        with self.pending_lock:
            if self.dead:
                raise RpcError("connection closed")
            self.pending[req_id] = fut
        try:
            with self.write_lock:
                codec.write_frame(self.sock, req_id, body)
        except OSError as e:
            with self.pending_lock:
                self.pending.pop(req_id, None)
            self._fail_all(RpcError(f"send failed: {e}"))
            raise RpcError(f"send failed: {e}") from e
        return fut


class TcpClient(Transport):
    """Thread-safe pipelining client with one pooled connection per address."""

    def __init__(self, connect_timeout: float = 3.0) -> None:
        self._conns: dict[str, _Conn] = {}
        self._lock = make_lock("TcpClient._lock")
        self._ids = itertools.count(1)
        self._connect_timeout = connect_timeout

    def _conn_for(self, addr: str) -> _Conn:
        with self._lock:
            conn = self._conns.get(addr)
            if conn is not None and not conn.dead:
                return conn
        # connect outside the lock; last writer wins on a race
        try:
            conn = _Conn(addr, self._connect_timeout)
        except OSError as e:
            raise RpcError(f"{addr}: connect failed: {e}") from e
        with self._lock:
            existing = self._conns.get(addr)
            if existing is not None and not existing.dead:
                conn._fail_all(RpcError("superseded"))
                return existing
            self._conns[addr] = conn
        return conn

    def call_async(self, addr: str, request: dict) -> Future:
        body = codec.encode(request)
        conn = self._conn_for(addr)
        req_id = next(self._ids)
        fut = conn.send(req_id, body)
        fut._rmq_conn, fut._rmq_req_id = conn, req_id  # for timeout cleanup
        return fut

    def call(self, addr: str, request: dict, timeout: float = 3.0) -> dict:
        fut = self.call_async(addr, request)
        try:
            return fut.result(timeout=timeout)
        except (TimeoutError, FuturesTimeoutError):
            # Drop the pending entry: the connection may stay alive for a
            # long time, and abandoned futures must not accumulate.
            with fut._rmq_conn.pending_lock:
                fut._rmq_conn.pending.pop(fut._rmq_req_id, None)
            fut.cancel()
            raise RpcTimeout(f"{addr}: no response after {timeout}s") from None

    def close(self) -> None:
        with self._lock:
            conns, self._conns = list(self._conns.values()), {}
        for conn in conns:
            conn._fail_all(RpcError("client closed"))
